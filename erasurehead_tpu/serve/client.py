"""Clients for the serve daemon's network fronts.

Two transports, one contract:

  - :class:`ServeClient` — newline-delimited JSON over the AF_UNIX
    socket (see server.SocketFront): one ``submit`` line per request,
    streamed ``result`` lines back as the daemon's packed dispatches
    land. A reader thread demultiplexes the responses, so any number of
    submissions may be in flight on one connection; results arrive in
    COMPLETION order — match them up by ``request_id`` (or ``label``).
  - :class:`HttpServeClient` — the HTTP/1.1 JSONL front
    (serve/http_front.py): ``POST /v1/submit`` per request plus one
    long-lived chunked ``GET /v1/stream`` connection the reader thread
    drains. Auth is a per-tenant bearer token.

Failure taxonomy (the part the reference's mpirun-and-pray lifecycle
never had):

  - **daemon death** raises :class:`ServeUnavailableError` naming the
    endpoint and the last event seen on the wire — never a raw
    ``queue.Empty`` or socket errno;
  - **backpressure** (socket ``rejected`` line / HTTP 429) raises
    :class:`ServeRejectedError` carrying the daemon's ``retry_after_s``
    quote — or, with ``max_retries > 0``, is retried in-client on a
    DETERMINISTIC capped-exponential schedule that honors the quote
    (``wait = max(retry_after_s, min(cap, base * 2**attempt))``, no
    jitter: a rejected request's resubmission is idempotent by digest,
    so synchronized retries cost duplicate 429s, not duplicate rows);
  - **a client-side wait timeout** stays ``queue.Empty`` (the daemon is
    alive, the result genuinely isn't ready); the server-side
    ``request_timeout_s`` knob turns a stalled dispatch into a typed
    error *result* instead.
"""

from __future__ import annotations

import json
import queue as queue_lib
import socket
import threading
import time
from typing import Optional


class ServeUnavailableError(RuntimeError):
    """The daemon went away (connect refused, connection dropped, or the
    reader hit EOF) — distinguishable from a result that merely isn't
    ready yet. ``endpoint`` names the socket path or URL; ``last_event``
    is the last wire message type seen before the drop (None = the
    connection never spoke)."""

    def __init__(self, endpoint: str, last_event: Optional[str],
                 detail: str = ""):
        self.endpoint = endpoint
        self.last_event = last_event
        msg = (
            f"serve daemon unavailable at {endpoint} "
            f"(last event seen: {last_event or 'none'})"
        )
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class ServeRejectedError(RuntimeError):
    """Backpressure: the daemon answered 429/"rejected" instead of
    accepting. ``retry_after_s`` is the schedule quote to honor."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


def backoff_s(
    attempt: int,
    retry_after_s: Optional[float],
    base: float = 0.1,
    cap: float = 10.0,
) -> float:
    """The deterministic capped-exponential wait before retry number
    ``attempt`` (0-based): the daemon's retry-after quote wins when it is
    the longer, the exponential floor keeps a client whose quotes are
    stale from hammering, and the cap bounds the tail."""
    exp = min(cap, base * (2.0 ** attempt))
    return max(float(retry_after_s or 0.0), exp)


class ServeClient:
    """One connection to a serve daemon's unix socket."""

    def __init__(self, path: str, timeout: Optional[float] = None):
        self.path = path
        self.last_event: Optional[str] = None
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        try:
            self._sock.connect(path)
        except OSError as e:
            raise ServeUnavailableError(path, None, str(e)) from e
        self._wlock = threading.Lock()
        self._accepted: "queue_lib.Queue[dict]" = queue_lib.Queue()
        self._results: "queue_lib.Queue[dict]" = queue_lib.Queue()
        self._closed = threading.Event()
        self.rejected_total = 0  # 429/"rejected" replies seen
        self.retried_total = 0  # submissions re-sent after a rejection
        self._reader = threading.Thread(
            target=self._read_loop, name="eh-serve-client", daemon=True
        )
        self._reader.start()

    def _read_loop(self) -> None:
        buf = b""
        try:
            while True:
                try:
                    chunk = self._sock.recv(1 << 16)
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    raw, buf = buf.split(b"\n", 1)
                    if not raw.strip():
                        continue
                    try:
                        msg = json.loads(raw)
                    except json.JSONDecodeError:
                        continue
                    self.last_event = msg.get("type")
                    if msg.get("type") == "result":
                        self._results.put(msg)
                    else:  # accepted / rejected / error — submit replies
                        self._accepted.put(msg)
        finally:
            self._closed.set()

    def _unavailable(self, detail: str = "") -> ServeUnavailableError:
        return ServeUnavailableError(self.path, self.last_event, detail)

    def submit(
        self,
        tenant: str,
        label: str,
        config: dict,
        target_loss: Optional[float] = None,
        data_seed: int = 0,
        timeout: Optional[float] = 30.0,
        priority: int = 0,
        max_retries: int = 0,
        backoff_base: float = 0.1,
        backoff_cap: float = 10.0,
    ) -> str:
        """Submit one trajectory request; returns its request_id.

        Raises RuntimeError when the daemon refuses the payload,
        :class:`ServeRejectedError` on backpressure once ``max_retries``
        deterministic capped-exponential attempts (honoring the daemon's
        retry-after quotes) are exhausted, and
        :class:`ServeUnavailableError` when the daemon is gone. Thread-
        safe: the accepted reply is correlated purely by submit order, so
        the lock spans the send AND the reply — two concurrent
        submitters must not each read the other's request_id."""
        for attempt in range(max_retries + 1):
            line = json.dumps(
                {
                    "op": "submit",
                    "tenant": tenant,
                    "label": label,
                    "config": config,
                    "target_loss": target_loss,
                    "data_seed": data_seed,
                    "priority": priority,
                    "retry": attempt,
                }
            ) + "\n"
            with self._wlock:
                if self._closed.is_set():
                    raise self._unavailable("connection closed")
                try:
                    self._sock.sendall(line.encode())
                except OSError as e:
                    raise self._unavailable(str(e)) from e
                deadline = (
                    None
                    if timeout is None
                    else time.monotonic() + timeout
                )
                while True:
                    try:
                        reply = self._accepted.get(timeout=0.2)
                        break
                    except queue_lib.Empty:
                        if self._closed.is_set():
                            raise self._unavailable(
                                "connection closed while awaiting the "
                                "accepted reply"
                            ) from None
                        if deadline is not None and (
                            time.monotonic() >= deadline
                        ):
                            raise
            rtype = reply.get("type")
            if rtype == "accepted":
                # what-if ETA quote (daemon --eta-surface; None without
                # one): exposed on the client rather than the return
                # value so existing submit() callers keep their
                # request_id contract
                self.last_eta_s = reply.get("eta_s")
                return reply["request_id"]
            if rtype == "rejected":
                retry_after = float(reply.get("retry_after_s") or 0.0)
                self.rejected_total += 1
                if attempt < max_retries:
                    self.retried_total += 1
                    time.sleep(
                        backoff_s(
                            attempt, retry_after,
                            base=backoff_base, cap=backoff_cap,
                        )
                    )
                    continue
                raise ServeRejectedError(
                    reply.get("message", "serve daemon rejected the "
                              "request (overloaded)"),
                    retry_after_s=retry_after,
                )
            raise RuntimeError(
                f"serve daemon refused the request: "
                f"{reply.get('message', reply)}"
            )
        raise AssertionError("unreachable")  # loop always returns/raises

    def result(self, timeout: Optional[float] = None) -> dict:
        """The next finished trajectory (completion order, any of this
        connection's requests): {"request_id", "tenant", "label",
        "status", "row", "error", "resumed"}. Raises ``queue.Empty`` on
        a live-daemon timeout and :class:`ServeUnavailableError` when
        the daemon died with results still owed."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            try:
                return self._results.get(timeout=0.2)
            except queue_lib.Empty:
                if self._closed.is_set() and self._results.empty():
                    raise self._unavailable(
                        "connection closed with results still owed "
                        "(rows are journaled; resubmit to re-fetch)"
                    ) from None
                if deadline is not None and time.monotonic() >= deadline:
                    raise

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class HttpServeClient:
    """One tenant's connection to the HTTP JSONL front.

    ``submit`` POSTs per request (a fresh connection each time — the
    submit path is stateless, so daemon restarts are invisible to it
    beyond a retriable :class:`ServeUnavailableError`); ``result`` drains
    the long-lived chunked ``/v1/stream`` connection a reader thread
    owns. Timing hooks for the load generator: ``on_line(msg)`` fires on
    every stream line as it is read."""

    def __init__(
        self,
        host: str,
        port: int,
        tenant: str,
        token: Optional[str] = None,
        timeout: float = 30.0,
        on_line=None,
    ):
        self.host, self.port = host, int(port)
        self.tenant = tenant
        self.token = token
        self.timeout = float(timeout)
        self.endpoint = f"http://{host}:{port}"
        self.last_event: Optional[str] = None
        self.overflow_dropped = 0  # rows the daemon shed on our stream
        self._on_line = on_line
        self.rejected_total = 0  # 429 replies seen
        self.retried_total = 0  # submissions re-sent after a 429
        self._results: "queue_lib.Queue[dict]" = queue_lib.Queue()
        self._closed = threading.Event()
        self._stop = False
        self._stream_resp = None
        self._reader = threading.Thread(
            target=self._stream_loop, name="eh-serve-http-client",
            daemon=True,
        )
        self._reader.start()

    # ---- submit ----------------------------------------------------------

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        if self.token is not None:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def submit(
        self,
        label: str,
        config: dict,
        target_loss: Optional[float] = None,
        data_seed: int = 0,
        priority: int = 0,
        max_retries: int = 0,
        backoff_base: float = 0.1,
        backoff_cap: float = 10.0,
    ) -> str:
        """POST one request; returns its request_id. 429s retry on the
        deterministic capped-exponential schedule honoring Retry-After
        (see :func:`backoff_s`); exhausted retries raise
        :class:`ServeRejectedError`; a dead daemon raises
        :class:`ServeUnavailableError`."""
        import http.client

        for attempt in range(max_retries + 1):
            body = json.dumps(
                {
                    "tenant": self.tenant,
                    "label": label,
                    "config": config,
                    "target_loss": target_loss,
                    "data_seed": data_seed,
                    "priority": priority,
                    "retry": attempt,
                }
            )
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout
            )
            try:
                conn.request(
                    "POST", "/v1/submit", body=body,
                    headers=self._headers(),
                )
                resp = conn.getresponse()
                payload = json.loads(resp.read() or b"{}")
            except (OSError, http.client.HTTPException) as e:
                # a reset/refused under burst load is transient (accept
                # backlog, front mid-restart): retriable on the same
                # schedule as a 429 — submission is idempotent by
                # digest, so a resent acceptance can't double-dispatch
                if attempt < max_retries and isinstance(
                    e, (ConnectionError, TimeoutError)
                ):
                    time.sleep(
                        backoff_s(
                            attempt, None,
                            base=backoff_base, cap=backoff_cap,
                        )
                    )
                    continue
                raise ServeUnavailableError(
                    self.endpoint, self.last_event, str(e)
                ) from e
            finally:
                conn.close()
            if resp.status == 202:
                self.last_eta_s = payload.get("eta_s")
                return payload["request_id"]
            if resp.status == 429:
                retry_after = float(
                    payload.get("retry_after_s")
                    or resp.getheader("Retry-After")
                    or 0.0
                )
                self.rejected_total += 1
                if attempt < max_retries:
                    self.retried_total += 1
                    time.sleep(
                        backoff_s(
                            attempt, retry_after,
                            base=backoff_base, cap=backoff_cap,
                        )
                    )
                    continue
                raise ServeRejectedError(
                    payload.get("message", "serve daemon rejected the "
                                "request (overloaded)"),
                    retry_after_s=retry_after,
                )
            raise RuntimeError(
                f"serve daemon refused the request "
                f"(HTTP {resp.status}): {payload.get('message', payload)}"
            )
        raise AssertionError("unreachable")

    # ---- result stream ---------------------------------------------------

    def _stream_loop(self) -> None:
        import http.client

        try:
            path = "/v1/stream"
            if self.token is None:
                path += f"?tenant={self.tenant}"
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=max(self.timeout, 10.0)
            )
            conn.request("GET", path, headers=self._headers())
            resp = conn.getresponse()
            self._stream_resp = conn
            if resp.status != 200:
                return
            while not self._stop:
                raw = resp.readline()  # chunked decoding is transparent
                if not raw:
                    return
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                self.last_event = msg.get("type")
                if self._on_line is not None:
                    self._on_line(msg)
                if msg.get("type") == "result":
                    self._results.put(msg)
                elif msg.get("type") == "overflow":
                    # the daemon shed rows our reader was too slow for;
                    # they are journaled — re-fetch by resubmitting
                    self.overflow_dropped += int(msg.get("dropped", 0))
        except Exception:  # noqa: BLE001 — reader thread must not crash
            return
        finally:
            self._closed.set()

    def result(self, timeout: Optional[float] = None) -> dict:
        """The next finished trajectory off the stream; ``queue.Empty``
        on a live timeout, :class:`ServeUnavailableError` once the
        stream is dead and drained."""
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        while True:
            try:
                return self._results.get(timeout=0.2)
            except queue_lib.Empty:
                if self._closed.is_set() and self._results.empty():
                    raise ServeUnavailableError(
                        self.endpoint, self.last_event,
                        "stream closed with results still owed (rows "
                        "are journaled; resubmit to re-fetch)",
                    ) from None
                if deadline is not None and time.monotonic() >= deadline:
                    raise

    def close(self) -> None:
        self._stop = True
        if self._stream_resp is not None:
            try:
                self._stream_resp.close()
            except OSError:
                pass
