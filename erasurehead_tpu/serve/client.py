"""Socket client for the serve daemon's unix-socket front.

The wire protocol is newline-delimited JSON (see server.SocketFront): one
``submit`` line per request, streamed ``result`` lines back as the
daemon's packed dispatches land. A reader thread demultiplexes the
responses, so any number of submissions may be in flight on one
connection; results arrive in COMPLETION order — match them up by
``request_id`` (or ``label``). Submissions themselves serialize briefly:
the daemon answers ``accepted`` lines in submit order with no correlation
tag, so :meth:`submit` holds a lock across its send + reply to keep
concurrent submitters from swapping request_ids.

    client = ServeClient("/tmp/eh-serve.sock")
    rid = client.submit("alice", "agc_s2", {"scheme": "approx",
                        "n_workers": 8, "num_collect": 4, "rounds": 20})
    res = client.result(timeout=300)   # {"request_id": rid, "row": ...}
    client.close()
"""

from __future__ import annotations

import json
import queue as queue_lib
import socket
import threading
from typing import Optional


class ServeClient:
    """One connection to a serve daemon's unix socket."""

    def __init__(self, path: str, timeout: Optional[float] = None):
        self.path = path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(path)
        self._wlock = threading.Lock()
        self._accepted: "queue_lib.Queue[dict]" = queue_lib.Queue()
        self._results: "queue_lib.Queue[dict]" = queue_lib.Queue()
        self._reader = threading.Thread(
            target=self._read_loop, name="eh-serve-client", daemon=True
        )
        self._reader.start()

    def _read_loop(self) -> None:
        buf = b""
        while True:
            try:
                chunk = self._sock.recv(1 << 16)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                raw, buf = buf.split(b"\n", 1)
                if not raw.strip():
                    continue
                try:
                    msg = json.loads(raw)
                except json.JSONDecodeError:
                    continue
                if msg.get("type") == "result":
                    self._results.put(msg)
                else:  # accepted / error — answers to submit, in order
                    self._accepted.put(msg)

    def submit(
        self,
        tenant: str,
        label: str,
        config: dict,
        target_loss: Optional[float] = None,
        data_seed: int = 0,
        timeout: Optional[float] = 30.0,
    ) -> str:
        """Submit one trajectory request; returns its request_id. Raises
        RuntimeError when the daemon refuses the payload. Thread-safe:
        the accepted reply is correlated purely by submit order, so the
        lock spans the send AND the reply — two concurrent submitters
        must not each read the other's request_id."""
        line = json.dumps(
            {
                "op": "submit",
                "tenant": tenant,
                "label": label,
                "config": config,
                "target_loss": target_loss,
                "data_seed": data_seed,
            }
        ) + "\n"
        with self._wlock:
            self._sock.sendall(line.encode())
            reply = self._accepted.get(timeout=timeout)
        if reply.get("type") != "accepted":
            raise RuntimeError(
                f"serve daemon refused the request: "
                f"{reply.get('message', reply)}"
            )
        # what-if ETA quote (daemon --eta-surface; None without one):
        # exposed on the client rather than the return value so existing
        # submit() callers keep their request_id contract
        self.last_eta_s = reply.get("eta_s")
        return reply["request_id"]

    def result(self, timeout: Optional[float] = None) -> dict:
        """The next finished trajectory (completion order, any of this
        connection's requests): {"request_id", "tenant", "label",
        "status", "row", "error", "resumed"}. Raises ``queue.Empty`` on
        timeout."""
        return self._results.get(timeout=timeout)

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
