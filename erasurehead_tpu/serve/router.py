"""Fleet router: one address in front of N serve replicas.

Stdlib only, same discipline as the HTTP front it proxies
(serve/http_front.py). Two pieces:

  - :class:`HashRing` — consistent hashing (sha256, ``VNODES`` virtual
    nodes per member) keyed by **(tenant, cohort_signature)**: requests
    that could PACK into one cohort dispatch hash to the same replica,
    so a replica's compiled-scan lowerings and device data stacks stay
    hot for exactly the traffic that reuses them. Adding or removing one
    replica remaps only ~1/N of the key space (pinned by test) — a
    deploy bounce does not flush every replica's cache, it flushes one.
  - :class:`FleetRouter` — a thin HTTP proxy: ``POST /v1/submit`` routes
    by affinity key to the primary replica and walks the DETERMINISTIC
    failover ring (the ring order after the primary) when a replica
    refuses the connection; ``GET /v1/stream`` fans IN every replica's
    stream for the tenant (re-dialing upstreams that bounce, so a
    rolling deploy doesn't strand a reader); ``/healthz``, ``/v1/fleet``
    and ``/metrics`` expose the membership table and fleet gauges.

The router holds NO request state: acceptance lives in each replica's
intake WAL, results in the per-tenant journals. Killing the router loses
nothing — clients re-resolve and resubmit (idempotent by digest).
Backpressure is passed through verbatim (429 + Retry-After), never
retried sideways: an overloaded replica is alive, and its quota is the
admission plane's business (serve/admission.py), not the router's.

Membership changes come from the fleet supervisor (serve/fleet.py):
``add_replica`` / ``remove_replica`` / ``set_alive`` mutate the ring
under a lock; in-flight proxies finish against the endpoints they
resolved, exactly like a DNS flip.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import queue as queue_lib
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Optional

from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY as _METRICS

#: virtual nodes per ring member: enough that one member's share of the
#: key space is smooth (stddev ~ 1/sqrt(VNODES) of its mean share)
VNODES = 64


def _hash(data: str) -> int:
    return int.from_bytes(
        hashlib.sha256(data.encode()).digest()[:8], "big"
    )


def affinity_key(tenant: str, config_payload: dict) -> str:
    """The routing key: (tenant, cohort_signature). Configs that would
    pack into one cohort (train/trainer.cohort_signature) route to one
    replica; unbatchable configs collapse onto the tenant alone. Falls
    back to the tenant when the payload cannot resolve — a misrouted
    BAD request costs nothing (the replica 400s it the same way)."""
    sig = None
    try:
        from erasurehead_tpu.serve.queue import config_from_payload
        from erasurehead_tpu.train import trainer

        sig = trainer.cohort_signature(config_from_payload(config_payload))
    except Exception:  # noqa: BLE001 — routing must never 500 on a key
        sig = None
    return json.dumps([tenant, repr(sig)])


class HashRing:
    """Consistent-hash ring over named members (sha256, VNODES virtual
    nodes each). ``lookup`` gives the primary; ``ring_order`` gives the
    full deterministic failover sequence for a key."""

    def __init__(self, members=(), vnodes: int = VNODES):
        self.vnodes = int(vnodes)
        self._members: set[str] = set()
        self._ring: list[tuple[int, str]] = []
        self._lock = threading.Lock()
        for m in members:
            self.add(m)

    @property
    def members(self) -> list[str]:
        with self._lock:
            return sorted(self._members)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    def add(self, member: str) -> None:
        member = str(member)
        with self._lock:
            if member in self._members:
                return
            self._members.add(member)
            for v in range(self.vnodes):
                self._ring.append((_hash(f"{member}#{v}"), member))
            self._ring.sort()

    def remove(self, member: str) -> None:
        member = str(member)
        with self._lock:
            if member not in self._members:
                return
            self._members.discard(member)
            self._ring = [(h, m) for h, m in self._ring if m != member]

    def lookup(self, key: str) -> Optional[str]:
        """The primary member for ``key`` (None on an empty ring)."""
        with self._lock:
            if not self._ring:
                return None
            i = bisect.bisect(self._ring, (_hash(key), ""))
            return self._ring[i % len(self._ring)][1]

    def ring_order(self, key: str) -> list[str]:
        """Every member, in the deterministic failover order for
        ``key``: the primary first, then each DISTINCT member as its
        first vnode appears walking the ring clockwise. Every client
        and the supervisor walk the same sequence, so \"the next live
        replica after the dead one\" is a single well-defined peer."""
        with self._lock:
            if not self._ring:
                return []
            start = bisect.bisect(self._ring, (_hash(key), ""))
            out: list[str] = []
            seen: set[str] = set()
            n = len(self._ring)
            for s in range(n):
                m = self._ring[(start + s) % n][1]
                if m not in seen:
                    seen.add(m)
                    out.append(m)
            return out


class FleetRouter:
    """The fleet's front door: consistent-hash submit proxy + fan-in
    stream proxy + membership/metrics surface."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 vnodes: int = VNODES):
        from erasurehead_tpu.serve.http_front import (
            _QuietThreadingHTTPServer,
        )

        self.ring = HashRing(vnodes=vnodes)
        #: replica name -> {"host", "port", "alive", "pressure"}
        self.replicas: dict[str, dict] = {}
        self._lock = threading.Lock()
        self.redirects_total = 0  # proxies that left the primary
        self.adoptions_total = 0  # adoptions the supervisor commanded
        self._started = time.monotonic()
        self._closing = False
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            server_version = "erasurehead-fleet-router"

            def log_message(self, fmt, *args):  # noqa: D102 — quiet
                pass

            def _reply(self, code: int, obj: dict, headers=()):
                body = (json.dumps(obj) + "\n").encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 — http.server API
                if self.path != "/v1/submit":
                    self._reply(404, {"type": "error",
                                      "message": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                try:
                    msg = json.loads(raw or b"{}")
                    tenant = str(msg.get("tenant") or "")
                    key = affinity_key(tenant, msg.get("config") or {})
                except Exception as e:  # noqa: BLE001 — per-request
                    self._reply(400, {"type": "error",
                                      "message": f"bad body: {e}"})
                    return
                order = router.ring.ring_order(key)
                if not order:
                    self._reply(
                        503,
                        {"type": "error",
                         "message": "fleet has no live replicas"},
                        headers=[("Retry-After", "1")],
                    )
                    return
                auth = self.headers.get("Authorization")
                code, body, retry_after = router._proxy_submit(
                    order, raw, auth, tenant
                )
                headers = []
                if retry_after is not None:
                    headers.append(("Retry-After", retry_after))
                bs = body if body.endswith(b"\n") else body + b"\n"
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(bs)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(bs)

            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    with router._lock:
                        live = [
                            n for n, r in router.replicas.items()
                            if r["alive"]
                        ]
                    self._reply(
                        200,
                        {
                            "status": "ok",
                            "role": "router",
                            "replicas_live": len(live),
                            "replicas": sorted(live),
                            "uptime_s": round(
                                time.monotonic() - router._started, 3
                            ),
                        },
                    )
                    return
                if path == "/v1/fleet":
                    self._reply(200, router.fleet_view())
                    return
                if path == "/metrics":
                    from erasurehead_tpu.obs import exporter

                    body = exporter.render_prometheus(
                        _METRICS, router.fleet_gauges()
                    ).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", exporter.PROM_CONTENT_TYPE
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/v1/stream":
                    params = dict(
                        kv.partition("=")[::2]
                        for kv in query.split("&")
                        if kv
                    )
                    tenant = params.get("tenant", "")
                    auth = self.headers.get("Authorization")
                    if not tenant and not auth:
                        self._reply(
                            400,
                            {"type": "error",
                             "message": "stream wants ?tenant= (or "
                                        "auth)"},
                        )
                        return
                    router._proxy_stream(self, tenant, auth)
                    return
                self._reply(404, {"type": "error",
                                  "message": f"no route {path}"})

        self._httpd = _QuietThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="eh-fleet-router",
            daemon=True,
        )
        self._thread.start()

    # ---- membership (mutated by the fleet supervisor) --------------------

    def add_replica(self, name: str, host: str, port: int) -> None:
        with self._lock:
            self.replicas[name] = {
                "host": host, "port": int(port), "alive": True,
                "pressure": None,
            }
        self.ring.add(name)

    def remove_replica(self, name: str) -> None:
        self.ring.remove(name)
        with self._lock:
            self.replicas.pop(name, None)

    def set_alive(self, name: str, alive: bool,
                  pressure=None) -> None:
        """Mark a replica routable or not WITHOUT forgetting it (the
        supervisor still knows its endpoints and WAL). Dead replicas
        leave the hash ring so no new keys resolve to them."""
        with self._lock:
            rec = self.replicas.get(name)
            if rec is None:
                return
            was = rec["alive"]
            rec["alive"] = bool(alive)
            if pressure is not None:
                rec["pressure"] = pressure
        if alive and not was:
            self.ring.add(name)
        elif was and not alive:
            self.ring.remove(name)

    def endpoint_of(self, name: str) -> Optional[tuple[str, int]]:
        with self._lock:
            rec = self.replicas.get(name)
            return (rec["host"], rec["port"]) if rec else None

    def live_endpoints(self) -> list[tuple[str, int]]:
        """Every routable replica's (host, port) — the stream fan-in
        set, and what /v1/fleet hands a client that wants to hold its
        own per-replica connections."""
        with self._lock:
            return [
                (r["host"], r["port"])
                for _, r in sorted(self.replicas.items())
                if r["alive"]
            ]

    def fleet_view(self) -> dict:
        with self._lock:
            table = {
                name: {
                    "host": r["host"], "port": r["port"],
                    "alive": r["alive"], "pressure": r["pressure"],
                }
                for name, r in sorted(self.replicas.items())
            }
        return {
            "replicas": table,
            "ring": self.ring.members,
            "vnodes": self.ring.vnodes,
            "redirects_total": self.redirects_total,
            "adoptions_total": self.adoptions_total,
        }

    def fleet_gauges(self) -> dict:
        """The fleet's live gauge plane for /metrics (rendered through
        obs/exporter.render_prometheus alongside the counter
        registry)."""
        from erasurehead_tpu.obs.exporter import fleet_gauges

        return fleet_gauges(self.fleet_view())

    # ---- proxying --------------------------------------------------------

    def _proxy_submit(self, order, raw: bytes, auth, tenant: str):
        """POST the raw submit body to the primary, walking the failover
        ring on CONNECTION failure (a dead replica), never on
        backpressure (an overloaded replica is alive — its 429 +
        Retry-After passes through verbatim). Returns (status, body,
        retry_after_header)."""
        import http.client

        headers = {"Content-Type": "application/json"}
        if auth:
            headers["Authorization"] = auth
        last_err = "no live replicas"
        for hop, name in enumerate(order):
            ep = self.endpoint_of(name)
            if ep is None:
                continue
            if hop > 0:
                self.redirects_total += 1
                _METRICS.counter("fleet.router_redirects").inc()
                events_lib.emit(
                    "fleet", action="route", replica=name,
                    tenant=tenant, hop=hop,
                )
            conn = http.client.HTTPConnection(
                ep[0], ep[1], timeout=30.0
            )
            try:
                conn.request("POST", "/v1/submit", body=raw,
                             headers=headers)
                resp = conn.getresponse()
                body = resp.read()
                return (
                    resp.status, body, resp.getheader("Retry-After")
                )
            except (OSError, http.client.HTTPException) as e:
                last_err = f"{name}: {type(e).__name__}: {e}"
                continue
            finally:
                conn.close()
        return (
            503,
            json.dumps(
                {"type": "error",
                 "message": f"no replica accepted the proxy: "
                            f"{last_err}"}
            ).encode(),
            "1",
        )

    def _proxy_stream(self, handler, tenant: str, auth) -> None:
        """Fan IN every replica's /v1/stream for the tenant into one
        chunked response. Upstream readers RE-DIAL on death (a bounced
        replica's replayed rows still reach the reader); the client
        dedups by request_id, so an adoption replay is exactly-once at
        the caller."""
        import http.client

        q: "queue_lib.Queue[bytes]" = queue_lib.Queue(maxsize=1024)
        stop = threading.Event()

        def pump(name: str) -> None:
            while not stop.is_set() and not self._closing:
                ep = self.endpoint_of(name)
                if ep is None:
                    return  # removed from the fleet for good
                try:
                    conn = http.client.HTTPConnection(
                        ep[0], ep[1], timeout=10.0
                    )
                    path = "/v1/stream"
                    h = {}
                    if auth:
                        h["Authorization"] = auth
                    else:
                        path += f"?tenant={tenant}"
                    conn.request("GET", path, headers=h)
                    resp = conn.getresponse()
                    if resp.status != 200:
                        conn.close()
                        time.sleep(0.5)
                        continue
                    while not stop.is_set():
                        raw = resp.readline()
                        if not raw:
                            break
                        try:
                            q.put(raw, timeout=1.0)
                        except queue_lib.Full:
                            pass  # slow reader: rows are journaled
                    conn.close()
                except OSError:
                    pass
                time.sleep(0.5)  # re-dial a bounced replica

        with self._lock:
            names = sorted(self.replicas)
        threads = [
            threading.Thread(
                target=pump, args=(n,), name=f"eh-router-pump-{n}",
                daemon=True,
            )
            for n in names
        ]
        for t in threads:
            t.start()
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "application/jsonlines")
            handler.send_header("Transfer-Encoding", "chunked")
            handler.end_headers()
            last_beat = time.monotonic()
            while not self._closing:
                try:
                    raw = q.get(timeout=0.2)
                except queue_lib.Empty:
                    if time.monotonic() - last_beat > 5.0:
                        beat = b'{"type": "ping"}\n'
                        handler.wfile.write(
                            f"{len(beat):x}\r\n".encode() + beat
                            + b"\r\n"
                        )
                        handler.wfile.flush()
                        last_beat = time.monotonic()
                    continue
                handler.wfile.write(
                    f"{len(raw):x}\r\n".encode() + raw + b"\r\n"
                )
                handler.wfile.flush()
                last_beat = time.monotonic()
            handler.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # reader went away; rows are journaled
        finally:
            stop.set()

    def close(self) -> None:
        self._closing = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
