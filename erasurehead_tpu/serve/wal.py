"""Intake write-ahead log: no accepted request is ever lost to a crash.

The per-tenant journals (train/journal.py) persist *finished* rows; this
WAL persists *acceptances*. Every config-resolvable request the daemon
admits is appended — as a ``request`` event record carrying the full wire
payload (serve/queue.config_payload) plus its idempotency digest — BEFORE
any dispatch work happens, through the same O_APPEND single-write
EventLogger the journals use (one ``write(2)`` per line, so a kill can
tear at most the final line).

On restart, :meth:`IntakeWAL.replay` hands the daemon back its working
set: every WAL record, deduped by digest (last acceptance wins). The
server resubmits each one through its normal intake path — records whose
rows already landed in the tenant's journal rehydrate bitwise with no
dispatch; the rest re-dispatch, warm against the on-disk compilation
cache (train/cache.enable_persistent_compilation_cache). The ``restart``
event records the split.

Requests carrying an in-process dataset OBJECT are not WAL'd (a live
array isn't serializable as an acceptance, and its submitter died with
the process anyway); the network fronts are always config-resolvable, so
everything that arrived over a socket is covered.

The WAL is append-only and never compacted in-place: replay cost is one
JSON parse per acceptance since the journal directory was created, and
rotating the directory rotates the WAL with the journals it indexes.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from erasurehead_tpu.obs import events as events_lib

#: WAL file name inside the serve journal directory
WAL_NAME = "intake_wal.jsonl"


class IntakeWAL:
    """Append-only acceptance log over ``<journal_dir>/intake_wal.jsonl``.

    Thread-safe: intake runs on the serve loop but resubmission helpers
    may append from client threads. The writer opens lazily in append
    mode so constructing the WAL never clobbers a crashed daemon's
    records."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, WAL_NAME)
        self._logger: Optional[events_lib.EventLogger] = None
        self._lock = threading.Lock()
        self._seen: set[str] = set()
        if os.path.exists(self.path):
            for rec in self._read():
                self._seen.add(rec["digest"])

    def _read(self) -> list[dict]:
        records: list[dict] = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn final line from a kill mid-write
                if (
                    isinstance(rec, dict)
                    and rec.get("type") == "request"
                    and isinstance(rec.get("digest"), str)
                    and isinstance(rec.get("config"), dict)
                ):
                    records.append(rec)
        return records

    def __len__(self) -> int:
        return len(self._seen)

    def seen(self, digest: str) -> bool:
        return digest in self._seen

    def append(
        self,
        *,
        tenant: str,
        request_id: str,
        label: str,
        digest: str,
        config_payload: dict,
        data_seed: int = 0,
        target_loss: Optional[float] = None,
        priority: int = 0,
    ) -> bool:
        """Record one acceptance; returns False (and writes nothing) when
        the digest is already WAL'd — the resubmission coalesces onto the
        in-flight original, and one acceptance record is enough to
        rehydrate both."""
        with self._lock:
            if digest in self._seen:
                return False
            if self._logger is None:
                self._logger = events_lib.EventLogger(self.path, mode="a")
            self._logger.emit(
                "request",
                tenant=tenant,
                request_id=request_id,
                label=label,
                digest=digest,
                config=config_payload,
                data_seed=int(data_seed),
                target_loss=target_loss,
                priority=int(priority),
            )
            self._seen.add(digest)
        return True

    def replay(self) -> list[dict]:
        """The deduped working set: one record per digest, last
        acceptance wins, in first-acceptance order."""
        if not os.path.exists(self.path):
            return []
        by_digest: dict[str, dict] = {}
        order: list[str] = []
        for rec in self._read():
            d = rec["digest"]
            if d not in by_digest:
                order.append(d)
            by_digest[d] = rec
        return [by_digest[d] for d in order]

    def close(self) -> None:
        with self._lock:
            if self._logger is not None:
                self._logger.close()
                self._logger = None
