"""Intake write-ahead log: no accepted request is ever lost to a crash.

The per-tenant journals (train/journal.py) persist *finished* rows; this
WAL persists *acceptances*. Every config-resolvable request the daemon
admits is appended — as a ``request`` event record carrying the full wire
payload (serve/queue.config_payload) plus its idempotency digest — BEFORE
any dispatch work happens, through the same O_APPEND single-write
EventLogger the journals use (one ``write(2)`` per line, so a kill can
tear at most the final line).

On restart, :meth:`IntakeWAL.replay` hands the daemon back its working
set: every WAL record, deduped by digest (last acceptance wins). The
server resubmits each one through its normal intake path — records whose
rows already landed in the tenant's journal rehydrate bitwise with no
dispatch; the rest re-dispatch, warm against the on-disk compilation
cache (train/cache.enable_persistent_compilation_cache). The ``restart``
event records the split.

Requests carrying an in-process dataset OBJECT are not WAL'd (a live
array isn't serializable as an acceptance, and its submitter died with
the process anyway); the network fronts are always config-resolvable, so
everything that arrived over a socket is covered.

The WAL is append-only and never compacted in-place: replay cost is one
JSON parse per acceptance since the journal directory was created, and
rotating the directory rotates the WAL with the journals it indexes.

Fleet adoption (:meth:`IntakeWAL.adopt`): when a serve-fleet replica is
declared DEAD (the K-consecutive-evidential-miss rule, serve/fleet.py),
a designated peer adopts its WAL — locking it with an O_EXCL sentinel so
the double-adoption race has exactly one winner, refusing a WAL whose
owner still answers /healthz, and deduplicating against the adopter's
own acceptances by request_digest. Accepted-never-lost thereby survives
daemon *death*, not just daemon restart.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

from erasurehead_tpu.obs import events as events_lib

#: WAL file name inside the serve journal directory
WAL_NAME = "intake_wal.jsonl"

#: sentinel written beside an adopted WAL (O_EXCL): exactly one peer may
#: ever adopt a dead replica's acceptances — the loser of the race gets
#: :class:`WalAdoptionError`, not a duplicate replay
ADOPT_SENTINEL_SUFFIX = ".adopted"


class WalAdoptionError(RuntimeError):
    """Adoption refused: the WAL is already adopted (sentinel exists) or
    its owner still answers /healthz (adopting a live daemon's WAL would
    double-dispatch its working set)."""


class IntakeWAL:
    """Append-only acceptance log over ``<journal_dir>/intake_wal.jsonl``.

    Thread-safe: intake runs on the serve loop but resubmission helpers
    may append from client threads. The writer opens lazily in append
    mode so constructing the WAL never clobbers a crashed daemon's
    records."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, WAL_NAME)
        self._logger: Optional[events_lib.EventLogger] = None
        self._lock = threading.Lock()
        self._seen: set[str] = set()
        if os.path.exists(self.path):
            for rec in self._read():
                self._seen.add(rec["digest"])

    def _read(self) -> list[dict]:
        return read_records(self.path)

    def __len__(self) -> int:
        return len(self._seen)

    def seen(self, digest: str) -> bool:
        return digest in self._seen

    def append(
        self,
        *,
        tenant: str,
        request_id: str,
        label: str,
        digest: str,
        config_payload: dict,
        data_seed: int = 0,
        target_loss: Optional[float] = None,
        priority: int = 0,
    ) -> bool:
        """Record one acceptance; returns False (and writes nothing) when
        the digest is already WAL'd — the resubmission coalesces onto the
        in-flight original, and one acceptance record is enough to
        rehydrate both."""
        with self._lock:
            if digest in self._seen:
                return False
            if self._logger is None:
                self._logger = events_lib.EventLogger(self.path, mode="a")
            self._logger.emit(
                "request",
                tenant=tenant,
                request_id=request_id,
                label=label,
                digest=digest,
                config=config_payload,
                data_seed=int(data_seed),
                target_loss=target_loss,
                priority=int(priority),
            )
            self._seen.add(digest)
        return True

    def replay(self) -> list[dict]:
        """The deduped working set: one record per digest, last
        acceptance wins, in first-acceptance order."""
        return dedup_records(read_records(self.path))

    def adopt(self, path: str, *, owner_alive=None) -> list[dict]:
        """Adopt a DEAD peer's WAL at ``path``: lock it (O_EXCL sentinel
        beside the WAL file), read its deduped working set, and return
        the records whose digests this WAL has not itself accepted —
        the adopter resubmits those through its normal intake, which
        WALs them again locally (so the acceptances now survive the
        adopter's own death too).

        ``replay()`` assumes the WAL belongs to the live process; this
        is the explicit cross-process entry point, and it refuses two
        ways a naive replay would double-dispatch:

          - ``owner_alive`` (a callable; e.g. a /healthz probe of the
            owner) returning True — adopting a live daemon's WAL would
            re-dispatch its in-flight working set;
          - a sentinel already present — exactly one peer wins the
            adoption race; the loser raises instead of replaying the
            same acceptances a second time.
        """
        src = os.path.abspath(path)
        if src == os.path.abspath(self.path):
            raise WalAdoptionError(
                f"a WAL cannot adopt itself ({src}); adoption is the "
                "cross-replica entry point — same-process restarts use "
                "replay()"
            )
        if owner_alive is not None and owner_alive():
            raise WalAdoptionError(
                f"refusing to adopt {src}: its owner still answers "
                "/healthz — adoption is for DEAD replicas (declared by "
                "the K-streak rule), not slow ones"
            )
        sentinel = src + ADOPT_SENTINEL_SUFFIX
        try:
            fd = os.open(
                sentinel, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644
            )
        except FileExistsError:
            raise WalAdoptionError(
                f"{src} is already adopted (sentinel {sentinel} "
                "exists): exactly one peer replays a dead replica's "
                "acceptances"
            ) from None
        with os.fdopen(fd, "w") as f:
            json.dump({"adopter_wal": os.path.abspath(self.path)}, f)
            f.write("\n")
        if not os.path.exists(src):
            return []
        with self._lock:
            seen = set(self._seen)
        return [
            rec
            for rec in dedup_records(read_records(src))
            if rec["digest"] not in seen
        ]

    def close(self) -> None:
        with self._lock:
            if self._logger is not None:
                self._logger.close()
                self._logger = None


def read_records(path: str) -> list[dict]:
    """Every well-formed acceptance record in a WAL file, in file order
    (tolerating a torn final line from a kill mid-write). Module-level so
    adoption can read a DEAD peer's WAL without constructing an
    :class:`IntakeWAL` over its directory (which would open a writer seam
    on a file the owner may still hold)."""
    if not os.path.exists(path):
        return []
    records: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn final line from a kill mid-write
            if (
                isinstance(rec, dict)
                and rec.get("type") == "request"
                and isinstance(rec.get("digest"), str)
                and isinstance(rec.get("config"), dict)
            ):
                records.append(rec)
    return records


def dedup_records(records: list[dict]) -> list[dict]:
    """One record per digest, last acceptance wins, first-acceptance
    order — the replay/adoption working-set view of a raw record list."""
    by_digest: dict[str, dict] = {}
    order: list[str] = []
    for rec in records:
        d = rec["digest"]
        if d not in by_digest:
            order.append(d)
        by_digest[d] = rec
    return [by_digest[d] for d in order]
