"""Sweep-as-a-service: multi-tenant cohort packing with admission control.

The serve daemon generalizes the cohort engine's batch dimension from "one
user's sweep" (train/trainer.train_cohort, PR 4) to "many concurrent
clients": compatible requests from different tenants bin-pack into shared
compiled dispatches — weighted-fair across tenants, so one chatty client
can't starve the rest — an admission controller bounds in-flight HBM,
backpressure rejects (429 / "rejected") instead of starving once the
intake queue crosses its high-water mark, and results stream back per
tenant with journal-backed resume and the sweep guard's full degradation
ladder as fault isolation. Acceptances are WAL'd and executables persist
in JAX's on-disk compilation cache, so a crashed daemon restarts warm:
zero fresh compiles, every accepted request rehydrated bitwise.

    serve/queue.py       request/result model + in-process handles
    serve/packer.py      signature bin-packing, weighted-fair + quotas
    serve/admission.py   HBM budget: estimates, measured refinement, evict
    serve/wal.py         intake write-ahead log (crash-safe acceptances)
    serve/server.py      the SweepServer loop + the unix-socket front
    serve/http_front.py  HTTP/1.1 JSONL front: auth, streaming, 429s
    serve/client.py      socket + HTTP clients for `erasurehead-tpu serve`
    serve/loadgen.py     closed-loop load generator (bench + smokes)
"""

from erasurehead_tpu.serve.client import (  # noqa: F401
    HttpServeClient,
    ServeClient,
    ServeRejectedError,
    ServeUnavailableError,
)
from erasurehead_tpu.serve.queue import (  # noqa: F401
    RequestHandle,
    RunRequest,
    ServeOverloadedError,
    ServeResult,
    config_from_payload,
    config_payload,
    request_digest,
)
from erasurehead_tpu.serve.server import (  # noqa: F401
    SocketFront,
    SweepServer,
    serving,
)
from erasurehead_tpu.serve.wal import IntakeWAL  # noqa: F401
