"""Sweep-as-a-service: multi-tenant cohort packing with admission control.

The serve daemon generalizes the cohort engine's batch dimension from "one
user's sweep" (train/trainer.train_cohort, PR 4) to "many concurrent
clients": compatible requests from different tenants bin-pack into shared
compiled dispatches, an admission controller bounds in-flight HBM, and
results stream back per tenant with journal-backed resume and the sweep
guard's full degradation ladder as fault isolation.

    serve/queue.py      request/result model + in-process handles
    serve/packer.py     signature bin-packing (cohort_signature + dataset)
    serve/admission.py  HBM budget: estimates, measured refinement, evict
    serve/server.py     the SweepServer loop + the unix-socket front
    serve/client.py     socket client for `erasurehead-tpu serve`
"""

from erasurehead_tpu.serve.queue import (  # noqa: F401
    RequestHandle,
    RunRequest,
    ServeResult,
    config_from_payload,
)
from erasurehead_tpu.serve.server import (  # noqa: F401
    SocketFront,
    SweepServer,
    serving,
)
