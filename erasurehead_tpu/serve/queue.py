"""Serve request/result model: what a client submits and what it gets back.

The serve daemon's unit of work is one trajectory request — a labeled
RunConfig from some tenant, optionally carrying its own dataset, arrival
schedule and loss target. The in-process API hands the submitter a
:class:`RequestHandle`; results stream back onto it as the packed cohort
dispatches land (one :class:`ServeResult` per request, in completion
order, not submission order).

The thin socket front (serve/client.py, ``erasurehead-tpu serve``) carries
the same model as JSON lines; :func:`config_from_payload` is the single
place a wire payload becomes a RunConfig, so the socket surface can never
accept a field the in-process surface would refuse.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue as queue_lib
import threading
from typing import Any, Optional

import numpy as np

from erasurehead_tpu.utils.config import RunConfig

_request_ids = itertools.count(1)
_id_lock = threading.Lock()


def new_request_id(tenant: str) -> str:
    """Process-unique request id, tenant-prefixed for readable logs."""
    with _id_lock:
        n = next(_request_ids)
    return f"{tenant}-req-{n:04d}"


@dataclasses.dataclass
class RunRequest:
    """One tenant's trajectory request.

    ``dataset`` is optional: in-process clients may pass a real Dataset
    (requests sharing one OBJECT share a device stack and can pack);
    config-only requests (the socket front) are resolved by the server's
    memoized dataset pool, keyed on the config's data-defining fields plus
    ``data_seed`` — so same-shape requests from different tenants resolve
    to the SAME dataset object and pack, while the trajectory ``seed``
    stays free to differ per request. ``arrivals`` is optional the same
    way (None = the deterministic per-config default schedule,
    trainer.default_arrivals)."""

    tenant: str
    label: str
    config: RunConfig
    dataset: Optional[Any] = None
    arrivals: Optional[np.ndarray] = None
    target_loss: Optional[float] = None
    data_seed: int = 0
    request_id: str = ""
    #: scheduling priority: higher dispatches sooner WITHIN a tenant's
    #: own queue (weighted-fair packing keeps tenants from outbidding
    #: each other — priority orders your work, not the neighborhood's)
    priority: int = 0
    #: client retry attempt number (0 = first try); rides the wire
    #: payload so the request event and per-tenant report can count
    #: retries that followed a 429
    retry: int = 0

    def __post_init__(self):
        if not self.tenant or not isinstance(self.tenant, str):
            raise ValueError(
                f"request tenant must be a non-empty string, got "
                f"{self.tenant!r}"
            )
        if not self.label or not isinstance(self.label, str):
            raise ValueError(
                f"request label must be a non-empty string, got "
                f"{self.label!r}"
            )
        if not self.request_id:
            self.request_id = new_request_id(self.tenant)


@dataclasses.dataclass
class ServeResult:
    """One finished trajectory, delivered back to its submitter.

    ``status``: ``"ok"`` / ``"diverged"`` (quarantined row — the science
    columns are NaN-free Nones downstream, the sweep/serve loop continued)
    / ``"error"`` (the dispatch failed beyond the degradation ladder;
    ``error`` carries the head of the exception). ``row`` is the UNROUNDED
    journal payload (train/journal.summary_payload) — the form the bitwise
    packed-vs-sequential contract is checked in; ``summary`` is the full
    RunSummary for in-process consumers (None over the wire)."""

    request_id: str
    tenant: str
    label: str
    status: str
    row: Optional[dict] = None
    summary: Optional[Any] = None
    error: Optional[str] = None
    resumed: bool = False  # rehydrated from the tenant's journal, no dispatch


class RequestHandle:
    """The submitter's view of one in-flight request."""

    def __init__(self, request: RunRequest):
        self.request = request
        self._q: "queue_lib.Queue[ServeResult]" = queue_lib.Queue()
        self._result: Optional[ServeResult] = None
        # the tenant-journal identity key, assigned at server intake once
        # the dataset/arrivals are resolved (None = journaling off)
        self.journal_key: Optional[str] = None
        # admission-time ETA quote in simulated seconds, assigned at
        # submit() when the daemon holds a what-if surface
        # (serve/admission.EtaQuoter); None = no surface or no matching
        # feasible row
        self.eta_s: Optional[float] = None
        # deliver-once bookkeeping: a request-timeout watchdog and the
        # dispatch that eventually lands must not both count/reply
        self._delivered = False
        self._deliver_lock = threading.Lock()
        # handles coalesced onto this one by request digest (an
        # idempotent resubmission of an in-flight request): they receive
        # a copy of this handle's result, re-tagged with their own ids
        self._followers: list["RequestHandle"] = []

    @property
    def request_id(self) -> str:
        return self.request.request_id

    def _deliver(self, result: ServeResult) -> bool:
        """Deliver once; later deliveries (a dispatch landing after the
        watchdog already timed the request out) are dropped. Returns
        whether THIS call was the delivery. Followers get a re-tagged
        copy so their submitters see their own request_id/label."""
        with self._deliver_lock:
            if self._delivered:
                return False
            self._delivered = True
            followers = list(self._followers)
        self._q.put(result)
        for f in followers:
            f._deliver(
                dataclasses.replace(
                    result,
                    request_id=f.request_id,
                    label=f.request.label,
                    resumed=True,
                )
            )
        return True

    def _follow(self, follower: "RequestHandle") -> bool:
        """Attach ``follower`` to receive this handle's result (digest
        coalescing). False when this handle already delivered — the
        caller should serve the follower from the journal instead."""
        with self._deliver_lock:
            if self._delivered:
                return False
            self._followers.append(follower)
            return True

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        """Block until this request's result lands (memoized after the
        first call). Raises ``queue.Empty`` on timeout."""
        if self._result is None:
            self._result = self._q.get(timeout=timeout)
        return self._result

    def done(self) -> bool:
        return self._result is not None or not self._q.empty()


#: RunConfig fields a wire payload may set — the plain-JSON subset (enums
#: accept their string values; lr_schedule accepts a number or list).
#: Deliberately absent: input_dir/is_real_data (a remote client must not
#: point the daemon at arbitrary host paths).
CONFIG_PAYLOAD_FIELDS = frozenset(
    {
        "scheme", "model", "n_workers", "n_stragglers", "rounds",
        "num_collect", "add_delay", "delay_mean", "compute_time",
        "worker_speed_spread", "update_rule", "alpha", "lr_schedule",
        "dataset", "n_rows", "n_cols", "partitions_per_worker",
        "compute_mode", "stack_mode", "ring_pipeline", "stack_dtype",
        "donate", "seed", "dtype", "use_pallas", "sparse_lanes",
        "dense_margin_cols", "flat_grad", "margin_flat", "deadline",
        "decode", "layer_coding", "deep_layers",
        # deliberately absent like input_dir: arrival_trace points the
        # daemon at a host path — a remote client must not
        "scan_unroll", "sparse_format", "fields_scatter", "fields_margin",
        # out-of-core streaming: residency + window COUNT are plain wire
        # values (admission charges streamed payloads by the window); the
        # shard-store PATH stays host-side, derived from the dataset
        "stack_residency", "stream_window",
    }
)


class ServeOverloadedError(RuntimeError):
    """Backpressure: the daemon's intake queue crossed its high-water
    mark and this request was REJECTED rather than accepted-then-starved.
    ``retry_after_s`` is the deferral-derived schedule quote (the HTTP
    front's Retry-After header, the socket front's ``rejected`` reply) a
    client's capped-exponential backoff should honor. Nothing was
    enqueued, journaled, or WAL'd — resubmitting is always safe."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


def request_digest(
    tenant: str,
    label: str,
    config: RunConfig,
    data_seed: int = 0,
    target_loss: Optional[float] = None,
) -> str:
    """The request's idempotency key: everything that determines WHAT a
    config-resolvable request computes (tenant, label, full config hash,
    data seed, loss target) — deliberately NOT the request_id, priority
    or retry count, which only say when/how it was asked. The intake WAL
    dedupes on it, and a resubmission after a crash or 429 coalesces
    onto the in-flight original instead of double-dispatching."""
    import hashlib
    import json as json_lib

    from erasurehead_tpu.obs import events as events_lib

    payload = json_lib.dumps(
        {
            "tenant": tenant,
            "label": label,
            "config": events_lib.config_hash(config),
            "data_seed": int(data_seed),
            "target_loss": target_loss,
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


def config_payload(cfg: RunConfig) -> Optional[dict]:
    """RunConfig -> the wire payload that reconstructs it, or None when
    the config sets fields outside :data:`CONFIG_PAYLOAD_FIELDS` (e.g.
    ``input_dir`` — not expressible on the wire, so not WAL-replayable).
    Round-trip contract: ``config_from_payload(config_payload(cfg)) ==
    cfg`` field-for-field, which is what makes a WAL-rehydrated request's
    journal key (events.config_hash over the FULL config) identical to
    the original's."""
    import dataclasses as dc

    payload: dict = {}
    for f in dc.fields(cfg):
        v = getattr(cfg, f.name)
        default = (
            f.default
            if f.default is not dc.MISSING
            else f.default_factory()  # type: ignore[misc]
            if f.default_factory is not dc.MISSING
            else None
        )
        if v == default:
            continue
        if f.name not in CONFIG_PAYLOAD_FIELDS:
            return None
        if hasattr(v, "value") and not isinstance(v, (int, float, bool)):
            v = v.value  # enums serialize as their string values
        elif isinstance(v, tuple):
            v = list(v)
        payload[f.name] = v
    return payload


def config_from_payload(payload: dict) -> RunConfig:
    """Wire JSON -> RunConfig, refusing unknown/unserveable fields loudly
    (a typo'd knob must fail the request, not silently train the default).
    RunConfig.__post_init__ does the semantic validation."""
    if not isinstance(payload, dict):
        raise ValueError(
            f"config payload must be a JSON object, got "
            f"{type(payload).__name__}"
        )
    unknown = sorted(set(payload) - CONFIG_PAYLOAD_FIELDS)
    if unknown:
        raise ValueError(
            f"config payload has unserveable field(s) {unknown}; "
            f"accepted: {sorted(CONFIG_PAYLOAD_FIELDS)}"
        )
    return RunConfig(**payload)
