"""Serve fleet: N replicated daemons, one router, zero-downtime deploys.

The single daemon (serve/server.py) already survives restarts — the
intake WAL replays its accepted working set. This module makes it
survive DEATH and upgrades without a maintenance window:

  - :class:`FleetSupervisor` spawns N ``erasurehead-tpu serve`` replicas
    as same-host subprocesses (stdlib only: ``subprocess`` + the HTTP
    front each replica already has), each with its own journal
    directory + intake WAL, fronted by one :class:`FleetRouter`
    (serve/router.py) that consistent-hashes submissions by
    (tenant, cohort_signature) so packable work keeps landing where its
    compiled lowerings and data stacks are hot.
  - **Membership is evidential**, the same streak discipline the elastic
    controller applies to stragglers (elastic/controller.py,
    :class:`ProbeStreakDetector`): a replica is declared dead only after
    K CONSECUTIVE missed /healthz probes *while actually probing* —
    one timeout is a hiccup, a paused probe is not evidence, and any
    answered probe resets the streak.
  - **On declared death**, the next live replica in the dead one's ring
    order ADOPTS its WAL (``POST /v1/adopt`` -> server.adopt_wal ->
    wal.adopt): O_EXCL sentinel so the adoption race has one winner, a
    final owner-/healthz refusal, dedup by request_digest against the
    adopter's own acceptances. Accepted-never-lost now spans the fleet.
  - **Rolling deploy** (:meth:`FleetSupervisor.rolling_deploy`): each
    replica in turn is drained (out of the hash ring, in-flight work
    finishes), stopped, restarted on the same directories (its WAL
    replays warm against the shared compilation cache), and re-admitted
    once /healthz answers — under load, with zero accepted-then-lost
    rows (`make fleet-smoke` drives this at 2x capacity).

Every transition is a typed ``fleet`` event (obs/events.py): probe
misses surface as ``suspect`` with the live streak, ``declare_dead``
carries streak >= K (the validator REFUSES a death declared early),
``adopt`` carries the replayed record count, ``deploy_phase`` narrates
the drain/stop/ready arc of each bounce.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

from erasurehead_tpu.elastic.controller import ProbeStreakDetector
from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY as _METRICS
from erasurehead_tpu.serve.router import FleetRouter, VNODES
from erasurehead_tpu.serve.wal import WAL_NAME

#: default evidential streak before a replica is declared dead
DEFAULT_K = 3

#: default seconds between membership probe sweeps
DEFAULT_PROBE_INTERVAL_S = 0.5


class Replica:
    """One fleet member: its process, endpoints, and durable state."""

    def __init__(self, name: str, journal_dir: str, cache_dir: str,
                 events_path: Optional[str], log_path: str):
        self.name = name
        self.journal_dir = journal_dir
        self.cache_dir = cache_dir
        self.events_path = events_path
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self.host: Optional[str] = None
        self.port: Optional[int] = None
        self.restarts = 0
        #: log size at the latest spawn — _wait_front must only parse
        #: lines THIS incarnation wrote (the log appends across bounces,
        #: and a bounced replica's first startup line names a dead port)
        self.log_offset = 0

    @property
    def wal_path(self) -> str:
        return os.path.join(self.journal_dir, WAL_NAME)

    @property
    def hostport(self) -> str:
        return f"{self.host}:{self.port}"


def probe_healthz(host: str, port: int,
                  timeout: float = 2.0) -> Optional[dict]:
    """One /healthz probe: the parsed body on a 200, None on ANY
    failure (refused, timeout, non-200, bad JSON) — a probe never
    raises, it just reports what it saw."""
    import http.client

    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            return json.loads(resp.read() or b"{}")
        finally:
            conn.close()
    except (OSError, ValueError):
        return None


class FleetSupervisor:
    """Spawns, probes, and bounces a same-host serve fleet."""

    def __init__(
        self,
        n: int = 3,
        base_dir: Optional[str] = None,
        router_host: str = "127.0.0.1",
        router_port: int = 0,
        k: int = DEFAULT_K,
        probe_interval_s: float = DEFAULT_PROBE_INTERVAL_S,
        window_ms: float = 50.0,
        cache_dir: Optional[str] = None,
        vnodes: int = VNODES,
        chaos: Optional[dict] = None,
        extra_args: tuple = (),
    ):
        self.n = int(n)
        if base_dir is None:
            base_dir = tempfile.mkdtemp(prefix="eh-fleet-")
        self.base_dir = base_dir
        # ONE compilation cache for the whole fleet: a bounced replica
        # (and an adopter re-dispatching a dead peer's work) compiles
        # against what its peers already lowered
        self.cache_dir = cache_dir or os.path.join(base_dir, "cache")
        self.window_ms = float(window_ms)
        self.router = FleetRouter(router_host, router_port, vnodes=vnodes)
        self.detector = ProbeStreakDetector(k=k)
        self.probe_interval_s = float(probe_interval_s)
        #: replica name -> chaos spec armed on ITS process only
        self.chaos = dict(chaos or {})
        self.extra_args = tuple(extra_args)
        self.replicas: dict[str, Replica] = {}
        self._dead_handled: set[str] = set()
        self._deploying: Optional[str] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    # ---- lifecycle -------------------------------------------------------

    def start(self, probe: bool = True) -> None:
        for i in range(self.n):
            self.spawn(f"r{i}")
        if probe:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="eh-fleet-probe",
                daemon=True,
            )
            self._probe_thread.start()

    def spawn(self, name: str) -> Replica:
        """Launch one replica (or relaunch a bounced one on its same
        directories), wait for its HTTP front, and admit it to the
        ring with a clean probe slate."""
        rep = self.replicas.get(name)
        if rep is None:
            rep = Replica(
                name=name,
                journal_dir=os.path.join(self.base_dir, name),
                cache_dir=self.cache_dir,
                events_path=os.path.join(
                    self.base_dir, f"{name}.events.jsonl"
                ),
                log_path=os.path.join(self.base_dir, f"{name}.log"),
            )
            self.replicas[name] = rep
        else:
            rep.restarts += 1
        os.makedirs(rep.journal_dir, exist_ok=True)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("ERASUREHEAD_CHAOS", None)
        if self.chaos.get(name):
            env["ERASUREHEAD_CHAOS"] = self.chaos[name]
        sock = os.path.join(self.base_dir, f"{name}.sock")
        cmd = [
            sys.executable, "-m", "erasurehead_tpu.cli", "serve",
            "--socket", sock,
            "--http", "127.0.0.1:0",
            "--replica-name", name,
            "--journal-dir", rep.journal_dir,
            "--cache-dir", rep.cache_dir,
            "--events", rep.events_path,
            "--window-ms", str(self.window_ms),
            *self.extra_args,
        ]
        rep.log_offset = (
            os.path.getsize(rep.log_path)
            if os.path.exists(rep.log_path) else 0
        )
        rep.host = rep.port = None  # a bounce gets a fresh kernel port
        out = open(rep.log_path, "a")
        rep.proc = subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT
        )
        self._wait_front(rep)
        self.router.add_replica(name, rep.host, rep.port)
        self.detector.add(name)
        self._dead_handled.discard(name)
        events_lib.emit("fleet", action="join", replica=name)
        return rep

    def _wait_front(self, rep: Replica, timeout: float = 600.0) -> None:
        """Parse the replica's own startup line for its kernel-assigned
        HTTP port, then wait until /healthz actually answers."""
        deadline = time.time() + timeout
        marker = "serve: http front on "
        while time.time() < deadline:
            if rep.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {rep.name} exited "
                    f"{rep.proc.returncode} before listening "
                    f"(log: {rep.log_path})"
                )
            try:
                with open(rep.log_path) as f:
                    f.seek(rep.log_offset)
                    for line in f:
                        if marker in line:
                            hostport = (
                                line.split(marker, 1)[1].split()[0]
                            )
                            host, _, port = hostport.rpartition(":")
                            rep.host, rep.port = host, int(port)
                            break
            except OSError:
                pass
            if rep.port is not None and probe_healthz(
                rep.host, rep.port
            ) is not None:
                return
            time.sleep(0.2)
        raise RuntimeError(
            f"replica {rep.name} never brought up its http front "
            f"(log: {rep.log_path})"
        )

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
        for rep in self.replicas.values():
            if rep.proc is not None and rep.proc.poll() is None:
                rep.proc.terminate()
        for rep in self.replicas.values():
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()
                    rep.proc.wait(timeout=10)
        self.router.close()

    # ---- membership ------------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the probe loop must live
                pass
            self._stop.wait(self.probe_interval_s)

    def probe_once(self) -> None:
        """One membership sweep: probe every replica not already dead,
        feed the evidence to the streak detector, and handle any death
        it declares. A replica mid-deploy is probed but the evidence is
        DISCARDED (evidential=False): a deliberate bounce is not
        evidence of death."""
        with self._lock:
            names = [
                n for n in self.replicas
                if n not in self._dead_handled
            ]
            deploying = self._deploying
        for name in names:
            rep = self.replicas[name]
            body = (
                probe_healthz(rep.host, rep.port)
                if rep.port is not None
                else None
            )
            ok = body is not None
            evidential = name != deploying
            streak = self.detector.observe(
                name, ok, evidential=evidential
            )
            if ok:
                self.router.set_alive(
                    name, True, pressure=body.get("admission")
                )
                continue
            if not evidential:
                continue
            if self.detector.is_dead(name):
                self._declare_dead(name, streak)
            else:
                events_lib.emit(
                    "fleet", action="suspect", replica=name,
                    streak=streak, k=self.detector.k,
                )

    def _declare_dead(self, name: str, streak: int) -> None:
        """K consecutive evidential misses: out of the ring, and the
        next live peer in ITS ring order adopts its WAL."""
        with self._lock:
            if name in self._dead_handled:
                return
            self._dead_handled.add(name)
        events_lib.emit(
            "fleet", action="declare_dead", replica=name,
            streak=streak, k=self.detector.k,
        )
        rep = self.replicas[name]
        self.router.set_alive(name, False)
        if rep.proc is not None and rep.proc.poll() is None:
            # unreachable but still running (wedged): make death true
            # before a peer adopts its WAL
            rep.proc.kill()
            rep.proc.wait(timeout=10)
        for peer in self.router.ring.ring_order(name):
            if peer == name or peer in self._dead_handled:
                continue
            if self._command_adoption(peer, rep):
                return
        events_lib.emit(
            "warning",
            kind="fleet_no_adopter",
            message=(
                f"fleet: no live peer could adopt {name}'s WAL "
                f"({rep.wal_path}); its acceptances replay when a "
                f"replica restarts on that directory"
            ),
        )

    def _command_adoption(self, peer: str, dead: Replica) -> bool:
        """POST /v1/adopt to ``peer``: adopt the dead replica's WAL.
        The peer re-checks the owner's /healthz itself before touching
        the file (server.adopt_wal -> wal.adopt)."""
        import http.client

        ep = self.router.endpoint_of(peer)
        if ep is None:
            return False
        body = json.dumps(
            {
                "path": dead.wal_path,
                "replica": dead.name,
                "owner": dead.hostport,
            }
        )
        try:
            conn = http.client.HTTPConnection(ep[0], ep[1], timeout=30.0)
            try:
                conn.request(
                    "POST", "/v1/adopt", body=body,
                    headers={"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                payload = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
        except (OSError, ValueError, http.client.HTTPException):
            return False
        if resp.status == 202:
            self.router.adoptions_total += 1
            _METRICS.counter("fleet.adoptions").inc()
            return True
        if resp.status == 409:
            # already adopted: the race had a winner — that is success
            self.router.adoptions_total += 1
            return True
        return False

    # ---- rolling deploy --------------------------------------------------

    def rolling_deploy(self, drain_timeout_s: float = 120.0) -> dict:
        """Bounce every replica in sequence with zero downtime: drain it
        out of the hash ring (peers absorb new submissions), stop it
        once idle, restart it on its same directories (the WAL replays
        anything a hard stop stranded), and re-admit it once /healthz
        answers. Returns per-replica timing."""
        phases: dict[str, dict] = {}
        for name in sorted(self.replicas):
            if name in self._dead_handled:
                continue
            rep = self.replicas[name]
            t0 = time.monotonic()
            with self._lock:
                self._deploying = name
            try:
                events_lib.emit(
                    "fleet", action="deploy_phase", replica=name,
                    phase="drain",
                )
                self.router.set_alive(name, False)
                self._drain(rep, drain_timeout_s)
                events_lib.emit(
                    "fleet", action="deploy_phase", replica=name,
                    phase="stop",
                )
                if rep.proc is not None and rep.proc.poll() is None:
                    rep.proc.terminate()
                    try:
                        rep.proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        rep.proc.kill()
                        rep.proc.wait(timeout=10)
                rep.host = rep.port = None
                self.spawn(name)  # same dirs: WAL replays, cache warm
                events_lib.emit(
                    "fleet", action="deploy_phase", replica=name,
                    phase="ready",
                )
            finally:
                with self._lock:
                    self._deploying = None
            phases[name] = {
                "bounce_s": round(time.monotonic() - t0, 3),
                "restarts": rep.restarts,
            }
        return phases

    def _drain(self, rep: Replica, timeout_s: float) -> None:
        """Wait until the replica reports an empty queue and no
        in-flight dispatches (bounded): nothing accepted is abandoned
        mid-bounce — and anything that slips through is exactly what
        the WAL replay exists for."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            body = probe_healthz(rep.host, rep.port)
            if body is None:
                return  # already gone; WAL replay covers it
            if not body.get("queued") and not body.get("in_flight"):
                return
            time.sleep(0.2)

    # ---- introspection ---------------------------------------------------

    def endpoints(self) -> dict:
        return {
            "router": f"{self.router.host}:{self.router.port}",
            "replicas": {
                name: rep.hostport
                for name, rep in sorted(self.replicas.items())
                if rep.port is not None
            },
        }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="erasurehead-tpu fleet",
        description="Run N serve replicas behind a consistent-hash "
                    "router with evidential membership, WAL adoption "
                    "on death, and zero-downtime rolling deploys.",
    )
    p.add_argument("--replicas", type=int, default=3,
                   help="fleet size (default 3)")
    p.add_argument("--http", default="127.0.0.1:0", metavar="HOST:PORT",
                   help="router bind address (default 127.0.0.1:0 — "
                        "kernel-assigned port, printed on stdout)")
    p.add_argument("--base-dir", default=None, metavar="DIR",
                   help="fleet state root: per-replica journal dirs + "
                        "WALs, shared compilation cache, logs "
                        "(default: a fresh temp dir)")
    p.add_argument("--k", type=int, default=DEFAULT_K,
                   help="evidential streak before a replica is "
                        f"declared dead (default {DEFAULT_K}; "
                        "a probe that was not attempted never counts)")
    p.add_argument("--probe-interval", type=float,
                   default=DEFAULT_PROBE_INTERVAL_S, metavar="SECONDS",
                   help="seconds between membership probe sweeps "
                        f"(default {DEFAULT_PROBE_INTERVAL_S})")
    p.add_argument("--window-ms", type=float, default=50.0,
                   help="per-replica admission window (default 50)")
    p.add_argument("--events", default=None, metavar="PATH",
                   help="capture the supervisor's fleet events to this "
                        "JSONL file (each replica always journals its "
                        "own under --base-dir)")
    p.add_argument("--rolling-deploy", action="store_true",
                   help="after the fleet is healthy, run one rolling "
                        "deploy drill and exit (for runbooks/CI; the "
                        "default is to serve until interrupted)")
    ns = p.parse_args(argv)

    from erasurehead_tpu.serve.http_front import parse_hostport

    host, port = parse_hostport(ns.http)
    import contextlib

    capture = (
        events_lib.capture(ns.events)
        if ns.events
        else contextlib.nullcontext()
    )
    with capture:
        sup = FleetSupervisor(
            n=ns.replicas,
            base_dir=ns.base_dir,
            router_host=host,
            router_port=port,
            k=ns.k,
            probe_interval_s=ns.probe_interval,
            window_ms=ns.window_ms,
        )
        sup.start()
        eps = sup.endpoints()
        print(
            f"fleet: router on {eps['router']} "
            f"({ns.replicas} replicas, k={ns.k})",
            flush=True,
        )
        for name, hp in eps["replicas"].items():
            print(f"fleet: replica {name} on {hp}", flush=True)
        try:
            if ns.rolling_deploy:
                phases = sup.rolling_deploy()
                print(json.dumps({"rolling_deploy": phases}), flush=True)
            else:
                while True:
                    time.sleep(0.5)
        except KeyboardInterrupt:
            print("fleet: shutting down", flush=True)
        finally:
            sup.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
