"""Closed-loop load generator for the serve daemon's HTTP front.

The measurement harness behind the ``serve_load`` bench extra,
``make serve-load-smoke`` and the backpressure/fairness tests: many
concurrent :class:`~erasurehead_tpu.serve.client.HttpServeClient` tenants
drive a daemon closed-loop (each client keeps a fixed number of requests
in flight, submitting the next as each row lands — offered load tracks
service rate instead of queueing unboundedly), and every accounting
question the robustness contracts ask is answered from the client's own
ledger:

  - **latency** — per-request time-to-first-row (submit accept -> the
    request's first streamed line) and per-tenant time-to-last-row (burst
    start -> final row), reported as p50/p99;
  - **no loss, no dups** — every accepted request_id must produce exactly
    one result line (``lost``/``duplicates`` counters; both must be 0
    even under 2x-capacity offered load — 429'd submissions retry on the
    deterministic capped-exponential schedule and are NOT accepted until
    the daemon says so);
  - **fairness** — :func:`fairness_run` pits one flooding tenant against
    closed-loop victims and compares each victim's goodput to its solo
    baseline (the acceptance bar: >= 0.5x with weighted-fair packing on);
  - **warm restart** — :func:`restart_run` bounces the daemon under a
    cleared in-process cache (the cold-process proxy; the subprocess
    kill variant lives in tools/serve_chaos_smoke.py), resubmits
    everything, and pins bitwise rehydration plus zero new entries in
    the on-disk compilation cache.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence

from erasurehead_tpu.serve.client import (
    HttpServeClient,
    ServeRejectedError,
)


def percentile(values: Sequence[float], p: float) -> Optional[float]:
    """Nearest-rank percentile (p in [0, 100]); None on empty input."""
    if not values:
        return None
    xs = sorted(values)
    k = max(0, min(len(xs) - 1, int(round((p / 100.0) * (len(xs) - 1)))))
    return float(xs[k])


def run_tenant(
    host: str,
    port: int,
    tenant: str,
    jobs: Sequence[tuple],
    token: Optional[str] = None,
    concurrency: int = 4,
    max_retries: int = 8,
    priority: int = 0,
    timeout: float = 600.0,
) -> dict:
    """Drive one tenant's job list closed-loop; returns its ledger.

    ``jobs`` is a sequence of ``(label, config_dict)``; ``concurrency``
    requests stay in flight (the next submits as each result lands).
    Submissions ride the client's capped-exponential retry schedule; a
    job still rejected after ``max_retries`` is counted in
    ``rejected_final`` (never silently dropped)."""
    client = HttpServeClient(host, port, tenant, token=token)
    submit_t: dict[str, float] = {}
    results: dict[str, dict] = {}
    latencies: list[float] = []
    duplicates = 0
    rejected_final = 0
    it = iter(jobs)
    n_jobs = len(jobs)
    outstanding = 0
    t0 = time.monotonic()
    last_row_t: Optional[float] = None
    first_row_t: Optional[float] = None

    def submit_next() -> bool:
        nonlocal outstanding, rejected_final
        while True:
            try:
                label, cfg = next(it)
            except StopIteration:
                return False
            try:
                rid = client.submit(
                    label, cfg, max_retries=max_retries, priority=priority
                )
            except ServeRejectedError:
                rejected_final += 1
                continue  # try the next job; this one is lost to caller
            submit_t[rid] = time.monotonic()
            outstanding += 1
            return True

    for _ in range(max(1, int(concurrency))):
        if not submit_next():
            break
    deadline = time.monotonic() + timeout
    while outstanding and time.monotonic() < deadline:
        try:
            res = client.result(timeout=5.0)
        except Exception:  # noqa: BLE001 — Empty: keep waiting till deadline
            continue
        now = time.monotonic()
        rid = res["request_id"]
        if rid in results:
            duplicates += 1
            continue
        results[rid] = res
        outstanding -= 1
        if rid in submit_t:
            latencies.append(now - submit_t[rid])
        if first_row_t is None:
            first_row_t = now
        last_row_t = now
        submit_next()
    elapsed = (last_row_t or time.monotonic()) - t0
    lost = len(submit_t) - len(results)
    ledger = {
        "tenant": tenant,
        "jobs": n_jobs,
        "accepted": len(submit_t),
        "rows": len(results),
        "lost": lost,
        "duplicates": duplicates,
        "rejected_429s": client.rejected_total,
        "retries": client.retried_total,
        "rejected_final": rejected_final,
        "stream_overflow_dropped": client.overflow_dropped,
        "errors": sum(
            1 for r in results.values() if r.get("status") == "error"
        ),
        "ttfr_s": (
            round(first_row_t - t0, 6) if first_row_t is not None else None
        ),
        "ttlr_s": round(elapsed, 6),
        "latencies_s": [round(x, 6) for x in latencies],
        "goodput_rows_per_s": (
            round(len(results) / elapsed, 4) if elapsed > 0 else None
        ),
        # full result payloads keyed by label (labels are unique per
        # tenant in this harness): what the restart phase compares
        # bitwise across the bounce
        "rows_by_label": {
            r["label"]: {
                "status": r.get("status"),
                "row": r.get("row"),
                "resumed": bool(r.get("resumed")),
            }
            for r in results.values()
        },
    }
    client.close()
    return ledger


def run_fleet(
    host: str,
    port: int,
    tenant_jobs: dict,
    tokens: Optional[dict] = None,
    concurrency: int = 4,
    max_retries: int = 8,
    priorities: Optional[dict] = None,
    timeout: float = 600.0,
) -> dict:
    """Drive several tenants concurrently (one thread each); returns
    {"tenants": {tenant: ledger}, "latency_p50_s", "latency_p99_s",
    "ttlr_p99_s", "lost", "duplicates"} aggregated across the fleet.
    ``tenant_jobs`` maps tenant -> job list; ``tokens`` maps tenant ->
    bearer token (None = auth off); ``concurrency`` is an int for the
    whole fleet or a dict tenant -> in-flight depth (how a flooding
    tenant floods)."""
    ledgers: dict[str, dict] = {}
    threads = []

    def drive(tenant, jobs):
        depth = (
            concurrency.get(tenant, 4)
            if isinstance(concurrency, dict)
            else concurrency
        )
        try:
            ledgers[tenant] = run_tenant(
                host, port, tenant, jobs,
                token=(tokens or {}).get(tenant),
                concurrency=depth,
                max_retries=max_retries,
                priority=(priorities or {}).get(tenant, 0),
                timeout=timeout,
            )
        except Exception as e:  # noqa: BLE001 — a dead client thread
            # must surface in the ledger, never silently vanish from
            # the fleet aggregates (its jobs would read as "not lost")
            ledgers[tenant] = {
                "tenant": tenant, "jobs": len(jobs), "accepted": 0,
                "rows": 0, "lost": len(jobs), "duplicates": 0,
                "rejected_429s": 0, "retries": 0, "rejected_final": 0,
                "stream_overflow_dropped": 0, "errors": 0,
                "ttfr_s": None, "ttlr_s": None, "latencies_s": [],
                "goodput_rows_per_s": None, "rows_by_label": {},
                "client_error": f"{type(e).__name__}: {e}",
            }

    for tenant, jobs in tenant_jobs.items():
        t = threading.Thread(
            target=drive, args=(tenant, jobs),
            name=f"eh-loadgen-{tenant}", daemon=True,
        )
        threads.append(t)
        t.start()
    for t in threads:
        t.join(timeout=timeout + 30)
    all_lat = [
        x for led in ledgers.values() for x in led["latencies_s"]
    ]
    return {
        "tenants": ledgers,
        "latency_p50_s": percentile(all_lat, 50),
        "latency_p99_s": percentile(all_lat, 99),
        "ttlr_p99_s": percentile(
            [
                led["ttlr_s"] for led in ledgers.values()
                if led["ttlr_s"] is not None
            ],
            99,
        ),
        "lost": sum(led["lost"] for led in ledgers.values()),
        "duplicates": sum(led["duplicates"] for led in ledgers.values()),
        "rejected_429s": sum(
            led["rejected_429s"] for led in ledgers.values()
        ),
        "retries": sum(led["retries"] for led in ledgers.values()),
    }


def fairness_run(
    make_front: Callable[[], tuple],
    victim_jobs: dict,
    flood_jobs: Sequence[tuple],
    flood_tenant: str = "flood",
    concurrency: int = 2,
    flood_concurrency: int = 16,
    timeout: float = 600.0,
) -> dict:
    """Goodput fairness under one flooding tenant.

    ``make_front()`` builds a fresh (server, front) pair and returns
    ``(server, front, host, port, close_fn)`` — a fresh daemon per phase
    so the solo baseline and the contended run see identical cold/warm
    state. Phase 1 runs each victim alone (solo goodput); phase 2 runs
    all victims plus the flooder. The acceptance bar: every victim's
    contended goodput >= 0.5x its solo goodput (vs. starvation under
    FIFO packing)."""
    solo: dict[str, dict] = {}
    for tenant, jobs in victim_jobs.items():
        _srv, _front, host, port, close_fn = make_front()
        try:
            solo[tenant] = run_tenant(
                host, port, tenant, jobs,
                concurrency=concurrency, timeout=timeout,
            )
        finally:
            close_fn()
    _srv, _front, host, port, close_fn = make_front()
    try:
        contended = run_fleet(
            host, port,
            {**victim_jobs, flood_tenant: list(flood_jobs)},
            concurrency={
                **dict.fromkeys(victim_jobs, concurrency),
                flood_tenant: flood_concurrency,
            },
            timeout=timeout,
        )
    finally:
        close_fn()
    ratios = {}
    for tenant, led in contended["tenants"].items():
        if tenant == flood_tenant:
            continue
        s = solo[tenant]["goodput_rows_per_s"]
        c = led["goodput_rows_per_s"]
        ratios[tenant] = (
            round(c / s, 4) if (s and c is not None and s > 0) else None
        )
    valid = [r for r in ratios.values() if r is not None]
    return {
        "solo": solo,
        "contended": contended,
        "goodput_ratio": ratios,
        "min_goodput_ratio": min(valid) if valid else None,
        "flood_rows": contended["tenants"][flood_tenant]["rows"],
    }


def restart_run(
    make_front: Callable[[], tuple],
    tenant_jobs: dict,
    cache_dir: str,
    concurrency: int = 4,
    timeout: float = 600.0,
) -> dict:
    """Warm-restart phase: serve the load, bounce the daemon with its
    in-process caches CLEARED (the cold-process proxy — the subprocess
    kill variant is ``make serve-chaos-smoke``), resubmit everything,
    and pin the crash-safety contract:

      - every resubmitted request rehydrates (``resumed=True``) with a
        row byte-identical to the first run's;
      - the on-disk compilation cache gained ZERO entries across the
        restart (the working set re-served with no fresh compiles).

    ``make_front()`` must build its server with ``journal_dir`` and
    ``cache_dir`` pointed at the same directories both times."""
    from erasurehead_tpu.train import cache as cache_lib

    _srv, _front, host, port, close_fn = make_front()
    try:
        before = run_fleet(
            host, port, tenant_jobs,
            concurrency=concurrency, timeout=timeout,
        )
    finally:
        close_fn()
    entries_before = cache_lib.persistent_cache_entries(cache_dir)
    cache_lib.clear()  # drop in-process exec/data caches: cold process
    t0 = time.monotonic()
    _srv, _front, host, port, close_fn = make_front()
    try:
        after = run_fleet(
            host, port, tenant_jobs,
            concurrency=concurrency, timeout=timeout,
        )
    finally:
        close_fn()
    restart_wall = time.monotonic() - t0
    entries_after = cache_lib.persistent_cache_entries(cache_dir)
    import json

    resumed = 0
    bitwise_mismatches = 0
    for tenant, led in after["tenants"].items():
        first_rows = before["tenants"][tenant]["rows_by_label"]
        for label, got in led["rows_by_label"].items():
            if got["resumed"]:
                resumed += 1
            want = first_rows.get(label)
            if want is None or json.dumps(
                got["row"], sort_keys=True
            ) != json.dumps(want["row"], sort_keys=True):
                bitwise_mismatches += 1
    return {
        "first_pass": before,
        "resubmit_pass": after,
        "rows_first": sum(
            led["rows"] for led in before["tenants"].values()
        ),
        "rows_resubmitted": sum(
            led["rows"] for led in after["tenants"].values()
        ),
        "resumed": resumed,
        "bitwise_mismatches": bitwise_mismatches,
        "restart_wall_s": round(restart_wall, 4),
        "new_compile_cache_entries": entries_after - entries_before,
    }
