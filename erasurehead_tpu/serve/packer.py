"""Cohort packer: bin-pack compatible pending requests into shared dispatches.

The multiplier the serve daemon adds over PR 4's per-sweep batching: the
cohort engine (trainer.train_cohort) doesn't care WHOSE trajectories share
a dispatch, only that they share a device data stack and a compiled-scan
lowering. The packing key is therefore exactly the cohort grouping key the
sweep planner uses — ``trainer.cohort_signature`` (static lowering
signature + rounds + workers + ``cache.layout_stack_signature``) — plus
the dataset's identity token: requests from different tenants that agree
on all of it ride ONE compiled scan.

What must NOT pack, packs not: the static signature carries the
memory-system knobs (``stack_dtype``, ``stack_mode``, ``ring_pipeline``,
``donate``, ``stack_residency``, ``stream_window``...), so e.g. an
int8-stack request and an f32-stack request key DIFFERENT data caches and
land in different cohorts (pinned in tests/test_cohort.py's
negative-packing test). Streamed requests pack WITH streamed requests —
same residency, same window → one windowed cohort scan
(trainer._train_cohort_streamed) — and never with resident ones
(tests/test_outofcore.py pins both directions). Arrival schedules are NOT
in the key — train_cohort takes them per trajectory, so tenants keep their
own straggler streams inside a shared dispatch.

Packing changes throughput, never bits: a cohort dispatch's per-trajectory
results are bitwise independent of the cohort's width (a packed request
and the same request dispatched alone produce identical rows — pinned in
tests/test_serve.py), so the packer has no fairness/CORRECTNESS tradeoff
— but it does have a fairness/LATENCY one. FIFO-by-signature let one
chatty tenant fill every dispatch window and starve the rest; the
weighted-fair order (:func:`fair_windows`) interleaves tenants
round-robin per window instead (each tenant's own queue stays FIFO
within a priority class) — work-conserving by construction, since a
lone tenant still fills whole windows — and an optional per-tenant slot
quota HARD-caps how much of one window a single tenant may hold (the
absolute bound for operators who need one, at the cost of short windows
when only over-quota traffic remains).
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import deque
from typing import Optional

from erasurehead_tpu.serve.queue import RunRequest
from erasurehead_tpu.train import cache as cache_lib
from erasurehead_tpu.train import trainer


def pack_key(request: RunRequest) -> Optional[tuple]:
    """The bin-packing key for one request: ``(cohort_signature, dataset
    token)``, or None when the config is cohort-ineligible (measured mode,
    forced pallas — dispatched as its own sequential singleton). The
    request's dataset must already be resolved (server._resolve_dataset)."""
    sig = trainer.cohort_signature(request.config)
    if sig is None:
        return None
    return (sig, cache_lib.dataset_token(request.dataset))


def key_digest(key: Optional[tuple]) -> str:
    """Short stable digest of a pack key for event payloads/logs (the raw
    key embeds assignment bytes — not something to put in a JSON line)."""
    if key is None:
        return "sequential"
    return hashlib.sha256(repr(key).encode()).hexdigest()[:12]


@dataclasses.dataclass
class PackedCohort:
    """One planned dispatch: the requests riding it and their shared key."""

    key: Optional[tuple]
    requests: list  # list[RunRequest], first-submitted first
    batchable: bool  # False = cohort-ineligible singleton

    @property
    def key_digest(self) -> str:
        return key_digest(self.key)

    @property
    def tenants(self) -> list:
        return sorted({r.tenant for r in self.requests})

    @property
    def labels(self) -> list:
        return [r.label for r in self.requests]


def fair_windows(
    reqs: list, max_cohort: int, tenant_quota: Optional[int] = None
) -> list[list]:
    """Split one signature group's requests into dispatch windows of at
    most ``max_cohort``, weighted-fair across tenants:

      - each tenant's requests form their own FIFO queue, ordered by
        priority class first (higher ``RunRequest.priority`` sooner;
        arrival order preserved within a class — the sort is stable);
      - each window drains the tenant queues round-robin (tenants in
        first-arrival order), so W tenants sharing a window get ~1/W of
        its slots each regardless of how deep any one backlog is — this
        alone is work-conserving fairness (a lone tenant still fills
        whole windows);
      - ``tenant_quota`` additionally HARD-caps one tenant's slots per
        window: when every backlogged tenant is at quota the window
        closes short and the overflow waits for the next one. Round-
        robin already equalizes shares under contention; the strict
        quota is the operator's lever when a tenant's share must be
        bounded absolutely (e.g. ``pad_cohorts=False``, where window
        width is real compute, or admission-footprint shaping — the
        weight tables scale with width).
    """
    queues: "dict[str, deque]" = {}
    tenant_order: list[str] = []
    for r in reqs:
        if r.tenant not in queues:
            queues[r.tenant] = deque()
            tenant_order.append(r.tenant)
    for tenant in tenant_order:
        mine = [r for r in reqs if r.tenant == tenant]
        mine.sort(key=lambda r: -r.priority)  # stable: FIFO within class
        queues[tenant].extend(mine)
    windows: list[list] = []
    while any(queues.values()):
        window: list = []
        taken = dict.fromkeys(tenant_order, 0)
        while len(window) < max_cohort:
            progress = False
            for tenant in tenant_order:
                if len(window) >= max_cohort:
                    break
                if not queues[tenant]:
                    continue
                if (
                    tenant_quota is not None
                    and taken[tenant] >= tenant_quota
                ):
                    continue
                window.append(queues[tenant].popleft())
                taken[tenant] += 1
                progress = True
            if not progress:
                break  # every backlogged tenant is at quota (or drained)
        windows.append(window)
    return windows


def plan_packs(
    pending: list,
    max_cohort: int = 64,
    fair: bool = True,
    tenant_quota: Optional[int] = None,
) -> list[PackedCohort]:
    """Group pending requests into dispatch cohorts, first-seen key order.
    Cohorts larger than ``max_cohort`` split into chunks: the per-round
    weight tables scale with cohort width, so an unbounded pack would let
    one burst of traffic balloon a single dispatch's footprint past what
    the admission controller (serve/admission.py) can usefully reason
    about. Within a key, ``fair=True`` (the daemon default) orders each
    chunk weighted-fair across tenants (:func:`fair_windows`);
    ``fair=False`` keeps the historical FIFO-by-arrival order.
    Cohort-ineligible requests come back as their own ``batchable=False``
    singletons."""
    if max_cohort < 1:
        raise ValueError(f"max_cohort must be >= 1, got {max_cohort}")
    if tenant_quota is not None and tenant_quota < 1:
        raise ValueError(
            f"tenant_quota must be >= 1 (or None), got {tenant_quota}"
        )
    groups: dict = {}
    order: list = []
    for req in pending:
        key = pack_key(req)
        gk = ("__sequential__", req.request_id) if key is None else key
        if gk not in groups:
            groups[gk] = (key, [])
            order.append(gk)
        groups[gk][1].append(req)
    out: list[PackedCohort] = []
    for gk in order:
        key, reqs = groups[gk]
        if key is None:
            out.append(PackedCohort(key=None, requests=reqs, batchable=False))
            continue
        if fair:
            chunks = fair_windows(reqs, max_cohort, tenant_quota)
        else:
            chunks = [
                reqs[lo:lo + max_cohort]
                for lo in range(0, len(reqs), max_cohort)
            ]
        for chunk in chunks:
            out.append(
                PackedCohort(key=key, requests=chunk, batchable=True)
            )
    return out
