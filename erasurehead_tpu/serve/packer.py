"""Cohort packer: bin-pack compatible pending requests into shared dispatches.

The multiplier the serve daemon adds over PR 4's per-sweep batching: the
cohort engine (trainer.train_cohort) doesn't care WHOSE trajectories share
a dispatch, only that they share a device data stack and a compiled-scan
lowering. The packing key is therefore exactly the cohort grouping key the
sweep planner uses — ``trainer.cohort_signature`` (static lowering
signature + rounds + workers + ``cache.layout_stack_signature``) — plus
the dataset's identity token: requests from different tenants that agree
on all of it ride ONE compiled scan.

What must NOT pack, packs not: the static signature carries the
memory-system knobs (``stack_dtype``, ``stack_mode``, ``ring_pipeline``,
``donate``...), so e.g. an int8-stack request and an f32-stack request key
DIFFERENT data caches and land in different cohorts (pinned in
tests/test_cohort.py's negative-packing test). Arrival schedules are NOT
in the key — train_cohort takes them per trajectory, so tenants keep their
own straggler streams inside a shared dispatch.

Packing changes throughput, never bits: a cohort dispatch's per-trajectory
results are bitwise independent of the cohort's width (a packed request
and the same request dispatched alone produce identical rows — pinned in
tests/test_serve.py), so the packer needs no fairness/correctness
tradeoff, only a size cap.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional

from erasurehead_tpu.serve.queue import RunRequest
from erasurehead_tpu.train import cache as cache_lib
from erasurehead_tpu.train import trainer


def pack_key(request: RunRequest) -> Optional[tuple]:
    """The bin-packing key for one request: ``(cohort_signature, dataset
    token)``, or None when the config is cohort-ineligible (measured mode,
    forced pallas — dispatched as its own sequential singleton). The
    request's dataset must already be resolved (server._resolve_dataset)."""
    sig = trainer.cohort_signature(request.config)
    if sig is None:
        return None
    return (sig, cache_lib.dataset_token(request.dataset))


def key_digest(key: Optional[tuple]) -> str:
    """Short stable digest of a pack key for event payloads/logs (the raw
    key embeds assignment bytes — not something to put in a JSON line)."""
    if key is None:
        return "sequential"
    return hashlib.sha256(repr(key).encode()).hexdigest()[:12]


@dataclasses.dataclass
class PackedCohort:
    """One planned dispatch: the requests riding it and their shared key."""

    key: Optional[tuple]
    requests: list  # list[RunRequest], first-submitted first
    batchable: bool  # False = cohort-ineligible singleton

    @property
    def key_digest(self) -> str:
        return key_digest(self.key)

    @property
    def tenants(self) -> list:
        return sorted({r.tenant for r in self.requests})

    @property
    def labels(self) -> list:
        return [r.label for r in self.requests]


def plan_packs(
    pending: list, max_cohort: int = 64
) -> list[PackedCohort]:
    """Group pending requests into dispatch cohorts, first-seen key order
    (arrival order within a key is preserved — FIFO per signature).
    Cohorts larger than ``max_cohort`` split into chunks: the per-round
    weight tables scale with cohort width, so an unbounded pack would let
    one burst of traffic balloon a single dispatch's footprint past what
    the admission controller (serve/admission.py) can usefully reason
    about. Cohort-ineligible requests come back as their own
    ``batchable=False`` singletons."""
    if max_cohort < 1:
        raise ValueError(f"max_cohort must be >= 1, got {max_cohort}")
    groups: dict = {}
    order: list = []
    for req in pending:
        key = pack_key(req)
        gk = ("__sequential__", req.request_id) if key is None else key
        if gk not in groups:
            groups[gk] = (key, [])
            order.append(gk)
        groups[gk][1].append(req)
    out: list[PackedCohort] = []
    for gk in order:
        key, reqs = groups[gk]
        if key is None:
            out.append(PackedCohort(key=None, requests=reqs, batchable=False))
            continue
        for lo in range(0, len(reqs), max_cohort):
            out.append(
                PackedCohort(
                    key=key,
                    requests=reqs[lo:lo + max_cohort],
                    batchable=True,
                )
            )
    return out
