"""The serve daemon: multi-tenant sweep-as-a-service over the cohort engine.

``SweepServer`` is a long-running loop that turns CONCURRENT CLIENTS into
the batch dimension the cohort engine already exploits per-sweep (PR 4):
clients submit labeled trajectory requests (in-process ``submit()``, or
the unix-socket front in serve/client.py behind ``erasurehead-tpu
serve``); a packer bin-packs compatible pending requests into shared
cohort dispatches (serve/packer.py — key = cohort signature + dataset
identity); an admission controller bounds the in-flight HBM footprint
(serve/admission.py); and each request's summary row streams back to its
submitter as the dispatch lands, journaled per tenant (train/journal.py)
so PR 5's resume/quarantine machinery becomes per-tenant fault isolation.

Contracts:

  - packing is a pure throughput lever: every request dispatches through
    the cohort engine (singletons included), and a cohort's per-trajectory
    results are bitwise independent of its width — a packed request and
    the same request dispatched alone produce IDENTICAL journal rows
    (pinned in tests/test_serve.py; raced in bench.py's serve_pack extra);
  - fault isolation: a dispatch failure degrades through the sweep
    guard's ladder (retry / bisect / sequential, experiments.
    _dispatch_cohort) and, beyond it, fails only ITS cohort's requests
    (status="error") — the daemon and every other tenant's work continue;
    divergence quarantines the single row (status="diverged"), exactly as
    in a local sweep;
  - per-tenant resume: each tenant's rows journal to
    ``<journal_dir>/<tenant>/sweep_journal.jsonl``; a resubmitted request
    whose (label, config, data, arrivals) key is already journaled is
    REHYDRATED bitwise without a dispatch.

All dispatching happens on a small thread pool; everything JAX-side is
per-dispatch-independent, and the admission controller is what keeps the
concurrency from overcommitting device memory.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import math
import os
import queue as queue_lib
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY as _METRICS
from erasurehead_tpu.serve import admission as admission_lib
from erasurehead_tpu.serve import packer as packer_lib
from erasurehead_tpu.serve import wal as wal_lib
from erasurehead_tpu.serve.queue import (
    RequestHandle,
    RunRequest,
    ServeOverloadedError,
    ServeResult,
    config_payload,
    request_digest,
)
from erasurehead_tpu.train import experiments, trainer
from erasurehead_tpu.train import journal as journal_lib
from erasurehead_tpu.utils import chaos
from erasurehead_tpu.utils.config import RunConfig

#: how long the packing window stays open once a request arrives: the
#: daemon trades this much latency for whatever packs in behind it
DEFAULT_WINDOW_S = 0.02

#: inbox sentinel that wakes the loop for shutdown (None would be
#: indistinguishable from a get() timeout)
_STOP = object()

#: default packed-dispatch width (requests per compiled cohort scan)
DEFAULT_MAX_COHORT = 32
#: concurrent dispatch threads (the admission controller is the real
#: bound; 2 keeps a second cohort compiling/uploading while one runs)
DEFAULT_DISPATCH_WORKERS = 2


def _summarize(
    request: RunRequest, result
) -> "experiments.RunSummary":
    """One dispatch result -> the request's RunSummary row, mirroring
    experiments.compare's per-trajectory completion (eval replay,
    divergence quarantine, request-local time_to_target)."""
    from erasurehead_tpu.train import evaluate

    cfg = request.config
    dataset = request.dataset
    model = trainer.build_model(cfg)
    n = result.n_train
    ev = evaluate.replay(
        model,
        cfg.model,
        result.params_history,
        dataset.X_train[:n],
        dataset.y_train[:n],
        dataset.X_test,
        dataset.y_test,
    )
    diverged = experiments._diverged(result, ev)
    if diverged:
        _METRICS.counter("sweep.diverged").inc()
        events_lib.emit(
            "warning",
            kind="divergence",
            message=(
                f"serve: request {request.request_id!r} (tenant "
                f"{request.tenant!r}, scheme {cfg.scheme.value}) diverged; "
                "row quarantined as status=diverged, daemon continues"
            ),
        )
    summary = experiments.RunSummary(
        label=request.label,
        config=result.config,
        sim_total_time=result.sim_total_time,
        sim_steps_per_sec=(
            result.config.rounds / result.sim_total_time
            if result.sim_total_time > 0
            else float("inf")
        ),
        real_steps_per_sec=result.steps_per_sec,
        final_train_loss=float(ev.training_loss[-1]),
        final_test_loss=float(ev.testing_loss[-1]),
        final_auc=float(ev.auc[-1]),
        time_to_target=None,
        training_loss=ev.training_loss,
        timeset=result.timeset,
        cache=result.cache_info,
        decode_error_mean=(
            float(np.mean(result.decode_error))
            if result.decode_error is not None and len(result.decode_error)
            else None
        ),
        status="diverged" if diverged else "ok",
    )
    if request.target_loss is not None and summary.status == "ok":
        summary.time_to_target = experiments.time_to_target_loss(
            summary.training_loss, summary.timeset, request.target_loss
        )
    return summary


class SweepServer:
    """In-process serve daemon (see module docstring).

    Use as a context manager, or ``start()``/``stop()`` explicitly::

        with SweepServer(budget_bytes=2 << 30,
                         request_timeout_s=120) as srv:
            h = srv.submit(tenant="alice", label="agc", config=cfg,
                           dataset=data)
            row = h.result()

    ``request_timeout_s`` is the server-side result deadline (a config
    knob, not a per-call literal): on expiry the daemon delivers a typed
    timeout error and emits a ``request_timeout`` warning, so a stalled
    dispatch is distinguishable from a client-side queue timeout.
    """

    def __init__(
        self,
        budget_bytes: Optional[int] = None,
        max_cohort: int = DEFAULT_MAX_COHORT,
        window_s: float = DEFAULT_WINDOW_S,
        journal_dir: Optional[str] = None,
        resume: bool = True,
        dispatch_workers: int = DEFAULT_DISPATCH_WORKERS,
        pad_cohorts: bool = True,
        eta_surface=None,
        max_pending: Optional[int] = None,
        request_timeout_s: Optional[float] = None,
        fair: bool = True,
        tenant_quota: Optional[int] = None,
        cache_dir: Optional[str] = None,
        replica_name: Optional[str] = None,
    ):
        self.admission = admission_lib.AdmissionController(budget_bytes)
        # admission-time ETA quotes from a what-if surface
        # (whatif/surface.Surface; None = quoting off): each accepted
        # request learns its simulated expected time-to-target up front
        self.eta = (
            admission_lib.EtaQuoter(eta_surface)
            if eta_surface is not None
            else None
        )
        self.max_cohort = int(max_cohort)
        # fixed-width dispatch: pad every batchable cohort to exactly
        # max_cohort trajectories (replicating the first request's config;
        # pad results are discarded). Two daemon-grade properties follow:
        # (1) ONE compiled executable per signature — without it, every
        # distinct pack width would trace and compile its own scan, and a
        # long-lived daemon's compile cache would bloat with traffic-shape
        # noise; (2) packing-invariant numerics — XLA's reduction order
        # inside the cohort matmul depends on the batch WIDTH (not on the
        # other columns' values), so with the width pinned, a request's
        # row is bitwise identical whether it dispatched alone or packed
        # with 31 strangers. The cost is padded-column compute on light
        # traffic — near-free on the bandwidth-bound TPU path (the padded
        # columns ride the same X stream), real on CPU; pad_cohorts=False
        # trades both properties back for it.
        self.pad_cohorts = bool(pad_cohorts)
        self.window_s = float(window_s)
        self.journal_dir = journal_dir
        self.resume = bool(resume)
        # ---- overload robustness knobs -----------------------------------
        # high-water mark on OUTSTANDING accepted requests (queued +
        # dispatched-but-unfinished): beyond it, submit() REJECTS
        # (ServeOverloadedError / HTTP 429 / socket "rejected") with a
        # deferral-derived retry-after, instead of accepting work it can
        # only starve. None = unbounded (the historical in-process
        # behavior).
        if max_pending is not None and max_pending < 1:
            raise ValueError(
                f"max_pending must be >= 1 (or None), got {max_pending}"
            )
        self.max_pending = max_pending
        # per-request result deadline, measured from intake: on expiry
        # the daemon DELIVERS a typed timeout error (and emits a
        # request_timeout warning) instead of leaving the submitter to an
        # indistinguishable queue.Empty. None = wait forever.
        if request_timeout_s is not None and request_timeout_s <= 0:
            raise ValueError(
                f"request_timeout_s must be positive (or None), got "
                f"{request_timeout_s}"
            )
        self.request_timeout_s = request_timeout_s
        # weighted-fair packing across tenants (packer.fair_windows);
        # tenant_quota hard-caps one tenant's slots per dispatch window
        self.fair = bool(fair)
        self.tenant_quota = tenant_quota
        # warm restarts: route XLA compiles through JAX's on-disk
        # compilation cache so a bounced daemon re-serves its working set
        # with zero fresh backend compiles
        self.cache_dir = cache_dir
        if cache_dir is not None:
            from erasurehead_tpu.train import cache as cache_lib

            cache_lib.enable_persistent_compilation_cache(cache_dir)
        self._inbox: "queue_lib.Queue[Optional[RequestHandle]]" = (
            queue_lib.Queue()
        )
        self._pending: list[RequestHandle] = []
        self._journals: dict[str, journal_lib.SweepJournal] = {}
        self._journal_lock = threading.Lock()
        self._datasets: dict[tuple, object] = {}
        self._executor = ThreadPoolExecutor(
            max_workers=max(1, int(dispatch_workers)),
            thread_name_prefix="eh-serve-dispatch",
        )
        self._dispatch_ids = itertools.count(1)
        self._in_flight = 0
        self._gen = 0  # bumped on arrivals/completions; gates re-packing
        self._state_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._drain = True
        # accepted-but-undispatched depth (inbox + pending) and
        # dispatched-but-unfinished request count: their sum is the
        # outstanding-work depth the max_pending high-water mark bounds
        # (counting only the undispatched half would let work pile up
        # unbounded in the executor's internal queue while the mark
        # reads zero); guarded by _state_lock
        self._queued = 0
        self._in_flight_requests = 0
        # EWMA of dispatch wall seconds — the admission-deferral estimate
        # behind retry-after quotes; guarded by _state_lock
        self._dispatch_ewma_s: Optional[float] = None
        # digest -> in-flight handle: idempotent resubmission coalesces
        # onto the original instead of double-dispatching
        self._by_digest: dict[str, RequestHandle] = {}
        self._digest_lock = threading.Lock()
        # delivered-result listeners (the HTTP front's stream hub).
        # Contract: a listener MUST NOT block — it runs on the dispatch
        # pool; network fronts buffer into bounded per-connection
        # outboxes and shed on overflow (the rows are journaled).
        self._result_listeners: list[Callable[[ServeResult], None]] = []
        # intake WAL (journal_dir only): acceptances persisted before any
        # dispatch work, replayed on start()
        self.wal: Optional[wal_lib.IntakeWAL] = (
            wal_lib.IntakeWAL(journal_dir) if journal_dir else None
        )
        self._watch: dict[str, tuple[RequestHandle, float]] = {}
        self._watch_lock = threading.Lock()
        self._watchdog: Optional[threading.Thread] = None
        # fleet identity (serve/fleet.py): set when this daemon is one
        # replica of a fleet — gossiped on /healthz, stamped onto fleet
        # events, and the name the router's hash ring knows it by
        self.replica_name = replica_name
        # WALs this daemon adopted from dead peers (adopt_wal)
        self.adoptions_total = 0
        # WAL-replay accounting (populated by _replay_wal)
        self._replay_records = 0
        self._replay_outstanding = 0
        self._replay_resubmitted = 0
        self._replay_rehydrated = 0

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "SweepServer":
        if self._thread is not None:
            raise RuntimeError("serve loop already started")
        # Preload the autotune decision cache ONCE, before any request
        # can dispatch: every auto-knob resolution inside a cohort
        # dispatch is then a warm in-memory dict lookup. Races never run
        # in this process — a daemon serving latency-bound tenants
        # resolves from verdicts `erasurehead-tpu tune` persisted, or
        # from the hardcoded fallbacks, never from a measurement taken
        # on the request path.
        from erasurehead_tpu import tune as tune_lib

        tune_lib.get_cache().decisions()
        self._thread = threading.Thread(
            target=self._loop, name="eh-serve-loop", daemon=True
        )
        self._thread.start()
        if self.request_timeout_s is not None:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop, name="eh-serve-watchdog",
                daemon=True,
            )
            self._watchdog.start()
        self._replay_wal()
        return self

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the loop. ``drain=True`` (default) finishes every pending
        and in-flight request first; ``drain=False`` fails pending
        requests with status="error" and returns as soon as in-flight
        dispatches land."""
        if self._thread is None:
            return
        self._drain = drain
        self._stopping = True
        self._inbox.put(_STOP)
        self._thread.join(timeout=timeout)
        self._thread = None
        if self._watchdog is not None:
            self._watchdog.join(timeout=2)
            self._watchdog = None
        self._executor.shutdown(wait=True)
        for j in self._journals.values():
            j.close()
        self._journals.clear()
        if self.wal is not None:
            self.wal.close()

    def __enter__(self) -> "SweepServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- client surface --------------------------------------------------

    def submit(
        self,
        request: Optional[RunRequest] = None,
        *,
        tenant: Optional[str] = None,
        label: Optional[str] = None,
        config: Optional[RunConfig] = None,
        dataset=None,
        arrivals=None,
        target_loss: Optional[float] = None,
        data_seed: int = 0,
        priority: int = 0,
        retry: int = 0,
        _replayed: bool = False,
    ) -> RequestHandle:
        """Submit one trajectory request; returns immediately with the
        handle its result will land on. Thread-safe (any number of client
        threads may submit concurrently). Raises
        :class:`ServeOverloadedError` when ``max_pending`` is set and the
        intake queue is at its high-water mark (``_replayed`` marks WAL
        rehydration traffic, which was accepted before the crash and is
        never re-rejected)."""
        if request is None:
            request = RunRequest(
                tenant=tenant, label=label, config=config, dataset=dataset,
                arrivals=arrivals, target_loss=target_loss,
                data_seed=data_seed, priority=priority, retry=retry,
            )
        if self._thread is None or self._stopping:
            raise RuntimeError("serve loop is not running")
        if (
            self.max_pending is not None
            and not _replayed
            and self.queued_depth() >= self.max_pending
        ):
            retry_after = self.retry_after_s(request.config)
            _METRICS.counter("serve.rejected").inc()
            events_lib.emit(
                "reject",
                tenant=request.tenant,
                reason="overloaded",
                label=request.label,
                retry_after_s=round(retry_after, 3),
                queued=self.queued_depth(),
                max_pending=self.max_pending,
            )
            raise ServeOverloadedError(
                f"serve: intake queue at high-water mark "
                f"({self.max_pending} accepted-but-undispatched); retry "
                f"in {retry_after:.3f}s",
                retry_after_s=retry_after,
            )
        handle = RequestHandle(request)
        handle.replayed = _replayed
        # the digest covers config-resolvable requests only: a live
        # dataset OBJECT has no wire identity, so in-process requests
        # carrying one keep the historical always-dispatch semantics
        handle.digest = None
        if request.dataset is None:
            handle.digest = request_digest(
                request.tenant, request.label, request.config,
                data_seed=request.data_seed,
                target_loss=request.target_loss,
            )
            if self.wal is not None:
                payload = config_payload(request.config)
                if payload is not None:
                    # WAL'd HERE, before the accepted reply goes out:
                    # once a front says "accepted", the acceptance is on
                    # disk — a kill any time after cannot lose it
                    self.wal.append(
                        tenant=request.tenant,
                        request_id=request.request_id,
                        label=request.label,
                        digest=handle.digest,
                        config_payload=payload,
                        data_seed=request.data_seed,
                        target_loss=request.target_loss,
                        priority=request.priority,
                    )
        # crash site: acceptance is on disk, nothing dispatched yet — a
        # kill here must rehydrate this request on restart
        chaos.maybe_fire("serve_intake")
        if self.eta is not None:
            # quoted HERE, before the enqueue, so the submitter (and the
            # socket front's "accepted" reply) reads the ETA immediately
            # rather than racing the intake loop
            handle.eta_s = self.eta.quote(request.config)
        _METRICS.counter("serve.requests").inc()
        with self._state_lock:
            self._queued += 1
        self._inbox.put(handle)
        return handle

    def queued_depth(self) -> int:
        """Outstanding accepted requests — undispatched (inbox +
        pending) plus dispatched-but-unfinished — the quantity the
        ``max_pending`` high-water mark bounds."""
        with self._state_lock:
            return self._queued + self._in_flight_requests

    def retry_after_s(self, config: Optional[RunConfig] = None) -> float:
        """The deferral-derived schedule quote a rejected client's
        backoff honors: (observed EWMA dispatch wall seconds — the
        admission deferral estimate) x (packing windows queued ahead).
        Before any dispatch has been observed, the what-if ETA quoter
        seeds the per-dispatch term (simulated seconds are the only
        cost model the daemon has yet), clamped so a pessimistic surface
        can't quote minutes. Deterministic given daemon state."""
        with self._state_lock:
            queued = self._queued
            ewma = self._dispatch_ewma_s
        per_dispatch = ewma
        if per_dispatch is None and self.eta is not None and (
            config is not None
        ):
            eta = self.eta.quote(config)
            if eta is not None:
                per_dispatch = min(float(eta), 30.0)
        if per_dispatch is None:
            per_dispatch = 1.0
        windows = max(1, math.ceil((queued + 1) / self.max_cohort))
        return float(min(60.0, max(self.window_s, per_dispatch * windows)))

    def add_result_listener(
        self, fn: Callable[[ServeResult], None]
    ) -> None:
        """Subscribe to every delivered result (the network fronts'
        streaming hub). ``fn`` runs on the delivering thread and MUST NOT
        block — buffer into a bounded outbox and shed on overflow (rows
        are journaled; a shed client re-fetches by resubmitting)."""
        self._result_listeners.append(fn)

    # ---- loop internals --------------------------------------------------

    def _journal_for(self, tenant: str) -> Optional[journal_lib.SweepJournal]:
        if self.journal_dir is None:
            return None
        # called from the intake loop AND dispatch executor threads:
        # check-then-insert under a lock, or two concurrent dispatches
        # for a new tenant each open a journal (fd leak + the loser's
        # in-memory resume map silently diverging from the winner's)
        with self._journal_lock:
            j = self._journals.get(tenant)
            if j is None:
                j = journal_lib.SweepJournal(
                    os.path.join(self.journal_dir, tenant),
                    resume=self.resume,
                )
                self._journals[tenant] = j
        return j

    def _resolve_dataset(self, request: RunRequest):
        """The request's dataset: as submitted, or from the daemon's
        memoized pool. The pool key is the config's data-defining fields
        plus ``data_seed`` — NOT the trajectory seed — so same-shape
        requests from different tenants resolve to the same object and
        can pack into one dispatch (packer.pack_key keys on object
        identity via cache.dataset_token)."""
        if request.dataset is not None:
            return request.dataset
        cfg = request.config
        key = (
            cfg.dataset, cfg.n_rows, cfg.n_cols, cfg.n_workers,
            cfg.n_stragglers, cfg.partitions_per_worker, cfg.model.value,
            cfg.is_real_data, cfg.input_dir, request.data_seed,
        )
        ds = self._datasets.get(key)
        if ds is None:
            from erasurehead_tpu.cli import load_dataset

            ds = load_dataset(
                dataclasses.replace(cfg, seed=request.data_seed)
            )
            self._datasets[key] = ds
        return ds

    def _finish(self, handle: RequestHandle, result: ServeResult) -> bool:
        """Single delivery point: deliver once, fan out to any coalesced
        followers, release the digest slot, notify stream listeners.
        Returns whether this call won the delivery (a dispatch landing
        after the watchdog already timed the request out loses)."""
        if not handle._deliver(result):
            return False
        digest = getattr(handle, "digest", None)
        if digest is not None:
            with self._digest_lock:
                if self._by_digest.get(digest) is handle:
                    del self._by_digest[digest]
        _METRICS.counter("serve.results").inc()
        # completion marker (phase="done"): the live-telemetry plane's
        # per-request terminus — the SLO tracker's time-to-last-row and
        # the timeseries reducer's per-tenant goodput both pair this
        # record with the intake "request" line (report counts only
        # intake records, so request totals stay one-per-request)
        events_lib.emit(
            "request",
            tenant=result.tenant,
            request_id=result.request_id,
            label=result.label,
            phase="done",
            status=result.status,
            resumed=result.resumed,
        )
        for fn in self._result_listeners:
            try:
                fn(result)
            except Exception:  # noqa: BLE001 — a front must not kill us
                pass
        return True

    def _dec_queued(self, n: int = 1) -> None:
        with self._state_lock:
            self._queued -= n

    def _fail(self, handle: RequestHandle, error: str) -> None:
        _METRICS.counter("serve.errors").inc()
        req = handle.request
        events_lib.emit(
            "warning",
            kind="serve_error",
            message=(
                f"serve: request {req.request_id!r} (tenant "
                f"{req.tenant!r}) failed: {error.splitlines()[0][:200]}"
            ),
        )
        self._finish(
            handle,
            ServeResult(
                request_id=req.request_id, tenant=req.tenant,
                label=req.label, status="error", error=error,
            ),
        )

    def _intake(self, handle: RequestHandle) -> None:
        """Admit one arriving request into the pending set: emit its
        ``request`` event, coalesce digest duplicates onto the in-flight
        original, resolve its dataset and arrivals, and serve it
        straight from the tenant's journal when resumable. (The WAL
        append happened in ``submit`` — acceptance durability precedes
        the accepted reply.) Every exit path balances the submit-side
        ``_queued`` increment except the pending append (dispatch
        decrements it)."""
        req = handle.request
        events_lib.emit(
            "request",
            tenant=req.tenant,
            request_id=req.request_id,
            label=req.label,
            scheme=req.config.scheme.value,
            eta_s=handle.eta_s,
            priority=req.priority,
            retry=req.retry,
            digest=handle.digest,
        )
        if handle.digest is not None:
            with self._digest_lock:
                live = self._by_digest.get(handle.digest)
                if live is not None and live._follow(handle):
                    # idempotent resubmission: ride the in-flight
                    # original instead of double-dispatching
                    _METRICS.counter("serve.coalesced").inc()
                    self._dec_queued()
                    self._classify_replay(handle, resubmitted=False)
                    return
                self._by_digest[handle.digest] = handle
        try:
            req.dataset = self._resolve_dataset(req)
            if req.arrivals is None:
                req.arrivals = trainer.default_arrivals(req.config)
        except Exception as e:  # noqa: BLE001 — isolate to this request
            self._dec_queued()
            self._classify_replay(handle, resubmitted=True)
            self._fail(handle, f"{type(e).__name__}: {e}")
            return
        journal = self._journal_for(req.tenant)
        if journal is not None:
            key = journal_lib.trajectory_key(
                req.label, req.config, req.dataset, req.arrivals
            )
            handle.journal_key = key
            rec = journal.lookup(key)
            if rec is not None:
                _METRICS.counter("serve.resumed").inc()
                summary = journal_lib.rehydrate_summary(
                    rec["row"], req.config
                )
                self._dec_queued()
                self._classify_replay(handle, resubmitted=False)
                self._finish(
                    handle,
                    ServeResult(
                        request_id=req.request_id, tenant=req.tenant,
                        label=req.label, status=rec.get("status", "ok"),
                        row=rec["row"], summary=summary, resumed=True,
                    ),
                )
                return
        else:
            handle.journal_key = None
        self._classify_replay(handle, resubmitted=True)
        if self.request_timeout_s is not None:
            with self._watch_lock:
                self._watch[req.request_id] = (
                    handle, time.monotonic() + self.request_timeout_s,
                )
        self._pending.append(handle)

    # ---- warm restart: WAL replay ---------------------------------------

    def _classify_replay(self, handle, resubmitted: bool) -> None:
        """Count one replayed handle's intake outcome toward the pending
        ``restart`` event (no-op for ordinary traffic); emits the event
        once the last replayed acceptance is classified."""
        if not getattr(handle, "replayed", False):
            return
        with self._state_lock:
            if resubmitted:
                self._replay_resubmitted += 1
            else:
                self._replay_rehydrated += 1
            self._replay_outstanding -= 1
            done = self._replay_outstanding == 0
            counts = (
                self._replay_records,
                self._replay_resubmitted,
                self._replay_rehydrated,
            )
        if done:
            _METRICS.counter("serve.restarts").inc()
            events_lib.emit(
                "restart",
                wal_records=counts[0],
                resubmitted=counts[1],
                rehydrated=counts[2],
            )

    def _replay_wal(self) -> None:
        """Re-serve the working set a previous daemon accepted but never
        finished: resubmit every WAL acceptance through the normal intake
        path. Records whose rows are already journaled rehydrate with no
        dispatch; the rest re-dispatch — warm against the on-disk
        compilation cache, so a restart costs zero fresh compiles of warm
        signatures. Nobody waits on these handles: the point is that the
        rows land in the per-tenant journals, where the original
        submitters' idempotent resubmissions find them."""
        if self.wal is None:
            return
        records = self.wal.replay()
        with self._state_lock:
            self._replay_records = len(records)
            self._replay_outstanding = len(records)
            self._replay_resubmitted = 0
            self._replay_rehydrated = 0
        if not records:
            return
        self._resubmit_records(records)

    def _resubmit_records(self, records: list) -> None:
        """Resubmit WAL acceptance records through the normal intake
        path (shared by warm-restart replay and fleet adoption). The
        ORIGINAL request_id is preserved: a client holding the accepted
        id sees the replayed result under the same identity, so its
        request_id dedup makes cross-replica delivery exactly-once."""
        from erasurehead_tpu.serve.queue import (
            RunRequest,
            config_from_payload,
        )

        for rec in records:
            try:
                req = RunRequest(
                    tenant=rec["tenant"], label=rec["label"],
                    config=config_from_payload(rec["config"]),
                    target_loss=rec.get("target_loss"),
                    data_seed=int(rec.get("data_seed", 0)),
                    priority=int(rec.get("priority", 0)),
                    request_id=str(rec.get("request_id") or ""),
                )
                self.submit(request=req, _replayed=True)
            except Exception as e:  # noqa: BLE001 — one bad WAL record
                # must not strand the rest of the working set
                events_lib.emit(
                    "warning",
                    kind="wal_replay_error",
                    message=(
                        f"serve: WAL record {rec.get('digest')!r} "
                        f"(tenant {rec.get('tenant')!r}) failed to "
                        f"replay: {type(e).__name__}: {e}"
                    ),
                )
                with self._state_lock:
                    self._replay_outstanding -= 1

    # ---- fleet: adopting a dead peer's WAL -------------------------------

    def adopt_wal(
        self,
        path: str,
        *,
        owner_alive=None,
        dead_replica: str = "unknown",
    ) -> dict:
        """Adopt a DEAD fleet peer's intake WAL and replay its accepted
        working set through this daemon's normal intake (serve/wal.py
        ``adopt``: O_EXCL sentinel lock, refusal while the owner still
        answers /healthz, dedup against this daemon's own acceptances by
        request_digest). Resubmission WALs each record locally, so the
        adopted acceptances now survive THIS daemon's death too; rows
        already journaled per-tenant rehydrate with no dispatch. Returns
        the adoption accounting; raises
        :class:`~erasurehead_tpu.serve.wal.WalAdoptionError` when the
        adoption is refused (already adopted / owner alive)."""
        if self.wal is None:
            raise RuntimeError(
                "adopt_wal needs a journal_dir-backed daemon: adoption "
                "replays acceptances into this daemon's own WAL"
            )
        records = self.wal.adopt(path, owner_alive=owner_alive)
        self.adoptions_total += 1
        _METRICS.counter("serve.adoptions").inc()
        events_lib.emit(
            "fleet",
            action="adopt",
            replica=dead_replica,
            records=len(records),
            adopter=self.replica_name,
        )
        with self._state_lock:
            # adoption reuses the restart accounting: the `restart`
            # event that fires when the last adopted record classifies
            # is the adoption's replay ledger
            self._replay_records = len(records)
            self._replay_outstanding = len(records)
            self._replay_resubmitted = 0
            self._replay_rehydrated = 0
        if records:
            self._resubmit_records(records)
        return {"records": len(records), "wal_path": path}

    # ---- request-timeout watchdog ---------------------------------------

    def _watchdog_loop(self) -> None:
        """Deliver a TYPED timeout error for any request that has not
        produced a result within ``request_timeout_s`` of intake — the
        submitter (and the socket front's relay) gets a distinguishable
        reply instead of an indistinguishable queue.Empty. The late
        dispatch, when it eventually lands, loses the deliver-once race
        and its row still journals (a resubmission rehydrates it)."""
        while True:
            if self._stopping and self._thread is None:
                return
            now = time.monotonic()
            expired: list[RequestHandle] = []
            with self._watch_lock:
                for rid in list(self._watch):
                    h, deadline = self._watch[rid]
                    if h._delivered:
                        del self._watch[rid]
                    elif deadline <= now:
                        del self._watch[rid]
                        expired.append(h)
                empty = not self._watch
            for h in expired:
                req = h.request
                _METRICS.counter("serve.timeouts").inc()
                events_lib.emit(
                    "warning",
                    kind="request_timeout",
                    message=(
                        f"serve: request {req.request_id!r} (tenant "
                        f"{req.tenant!r}, label {req.label!r}) produced "
                        f"no result within request_timeout_s="
                        f"{self.request_timeout_s:g}s; typed timeout "
                        f"error delivered"
                    ),
                )
                self._finish(
                    h,
                    ServeResult(
                        request_id=req.request_id, tenant=req.tenant,
                        label=req.label, status="error",
                        error=(
                            f"RequestTimeout: no result within "
                            f"{self.request_timeout_s:g}s (server "
                            f"request_timeout_s; the dispatch may still "
                            f"land and journal — resubmit to re-fetch)"
                        ),
                    ),
                )
            if self._stopping and empty and not expired:
                return
            time.sleep(0.05)

    def _loop(self) -> None:
        last_packed_gen = -1
        stop_seen = False
        while True:
            # ---- gather: block briefly, then hold the packing window
            # open so a burst of concurrent submissions packs together
            arrivals: list[RequestHandle] = []
            try:
                item = self._inbox.get(timeout=0.05)
                if item is _STOP:
                    stop_seen = True
                else:
                    arrivals.append(item)
            except queue_lib.Empty:
                pass
            if arrivals:
                deadline = time.monotonic() + self.window_s
                while time.monotonic() < deadline:
                    try:
                        nxt = self._inbox.get_nowait()
                    except queue_lib.Empty:
                        time.sleep(self.window_s / 10)
                        continue
                    if nxt is _STOP:
                        stop_seen = True
                    else:
                        arrivals.append(nxt)
            for h in arrivals:
                self._intake(h)
            if arrivals:
                with self._state_lock:
                    self._gen += 1

            # ---- pack + admit whatever the budget allows; deferred
            # cohorts retry when the generation moves (new arrivals, or a
            # dispatch completed and released its admission charge)
            with self._state_lock:
                gen = self._gen
            if self._pending and gen != last_packed_gen:
                last_packed_gen = gen
                self._try_dispatch()

            # ---- exit once stopping and (drained or drain=False)
            if stop_seen:
                if not self._drain and self._pending:
                    for h in self._pending:
                        self._dec_queued()
                        self._fail(h, "server stopped before dispatch")
                    self._pending.clear()
                with self._state_lock:
                    in_flight = self._in_flight
                if (
                    not self._pending
                    and in_flight == 0
                    and self._inbox.empty()
                ):
                    return
                # else keep looping: in-flight dispatches still land, and
                # drain mode keeps packing the remaining pending set

    def _try_dispatch(self) -> None:
        """One packing pass over the pending set: dispatch every cohort
        the admission controller lets through, keep the rest pending."""
        by_id = {h.request.request_id: h for h in self._pending}
        packs = packer_lib.plan_packs(
            [h.request for h in self._pending],
            max_cohort=self.max_cohort,
            fair=self.fair,
            tenant_quota=self.tenant_quota,
        )
        dispatched: set[str] = set()
        for cohort in packs:
            dispatch_id = f"disp-{next(self._dispatch_ids):04d}"
            width = (
                self.max_cohort
                if (cohort.batchable and self.pad_cohorts)
                else len(cohort.requests)
            )
            if not self.admission.try_admit(cohort, dispatch_id, width=width):
                continue  # stays pending; retried on the next generation
            for req in cohort.requests:
                dispatched.add(req.request_id)
            handles = [by_id[r.request_id] for r in cohort.requests]
            events_lib.emit(
                "pack",
                n_trajectories=len(cohort.requests),
                labels=cohort.labels,
                tenants=cohort.tenants,
                cohort=cohort.key_digest,
                dispatch_id=dispatch_id,
                batchable=cohort.batchable,
            )
            _METRICS.counter("serve.dispatches").inc()
            if len(cohort.requests) > 1:
                _METRICS.counter("serve.packed_trajectories").inc(
                    len(cohort.requests)
                )
            with self._state_lock:
                self._in_flight += 1
            self._executor.submit(
                self._run_cohort, cohort, handles, dispatch_id
            )
        if dispatched:
            with self._state_lock:
                self._queued -= len(dispatched)
                self._in_flight_requests += len(dispatched)
            self._pending = [
                h for h in self._pending
                if h.request.request_id not in dispatched
            ]

    def _run_cohort(self, cohort, handles, dispatch_id: str) -> None:
        """Dispatch one admitted cohort (executor thread) and deliver each
        request's result as it is summarized. Failures here are isolated:
        this cohort's requests get status="error", the daemon lives on."""
        t_start = time.monotonic()
        try:
            # crash site: one FLEET REPLICA dies mid-dispatch — a peer
            # must adopt its WAL and replay the accepted working set
            chaos.maybe_fire("fleet_replica")
            # crash site: accepted + WAL'd, rows not yet journaled — the
            # warm-restart working set a kill here leaves behind
            chaos.maybe_fire("serve_dispatch")
            ids = [h.request.request_id for h in handles]
            configs = {h.request.request_id: h.request.config for h in handles}
            arrivals = {
                h.request.request_id: h.request.arrivals for h in handles
            }
            dataset = handles[0].request.dataset
            if cohort.batchable:
                dispatch_ids = list(ids)
                if self.pad_cohorts and len(ids) < self.max_cohort:
                    # fixed-width dispatch (see __init__): fill the empty
                    # seats with the first request's trajectory; the pad
                    # columns' results are computed and dropped
                    first = handles[0].request
                    for i in range(self.max_cohort - len(ids)):
                        pid = f"_pad{i}_{dispatch_id}"
                        configs[pid] = first.config
                        arrivals[pid] = first.arrivals
                        dispatch_ids.append(pid)
                results = experiments._dispatch_cohort(
                    dispatch_ids, configs, dataset, arrivals
                )
            else:
                results = {
                    rid: experiments._train_one_guarded(
                        rid, configs[rid], dataset, arrivals
                    )
                    for rid in ids
                }
            first_info = next(iter(results.values())).cache_info
            self.admission.observe(cohort, first_info)
            for h in handles:
                req = h.request
                summary = _summarize(req, results[req.request_id])
                payload = journal_lib.summary_payload(summary)
                journal = self._journal_for(req.tenant)
                if journal is not None and h.journal_key is not None:
                    journal.record(h.journal_key, req.label, summary)
                events_lib.emit(
                    "sweep_trajectory",
                    key=h.journal_key or req.request_id,
                    label=req.label,
                    status=summary.status,
                    row=payload,
                    tenant=req.tenant,
                    request_id=req.request_id,
                )
                # crash site: row journaled, reply not yet delivered —
                # the submitter re-fetches by resubmitting (rehydrates)
                chaos.maybe_fire("serve_reply")
                self._finish(
                    h,
                    ServeResult(
                        request_id=req.request_id, tenant=req.tenant,
                        label=req.label, status=summary.status,
                        row=payload, summary=summary,
                    ),
                )
        except Exception as e:  # noqa: BLE001 — tenant isolation boundary
            err = f"{type(e).__name__}: {e}"
            for h in handles:
                self._fail(h, err)
        finally:
            wall = time.monotonic() - t_start
            self.admission.release(dispatch_id)
            with self._state_lock:
                # EWMA of dispatch wall seconds: the deferral estimate
                # behind retry_after_s quotes (alpha=0.3 — recent
                # traffic shape wins, one outlier doesn't)
                prev = self._dispatch_ewma_s
                self._dispatch_ewma_s = (
                    wall if prev is None else 0.7 * prev + 0.3 * wall
                )
                self._in_flight -= 1
                self._in_flight_requests -= len(handles)
                self._gen += 1


@contextlib.contextmanager
def serving(**kw):
    """``with serving(...) as srv:`` — a started SweepServer that stops
    (draining) on exit."""
    srv = SweepServer(**kw)
    srv.start()
    try:
        yield srv
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# thin unix-socket front: newline-delimited JSON over AF_UNIX. One line in:
#   {"op": "submit", "tenant": ..., "label": ..., "config": {...},
#    "target_loss"?: float, "data_seed"?: int, "priority"?: int,
#    "retry"?: int}
# lines out (interleaved, tagged by request_id):
#   {"type": "accepted", "request_id": ...}
#   {"type": "rejected", "retry_after_s": float, "message": ...}
#                                            (backpressure — resubmit
#                                             after retry_after_s)
#   {"type": "result", "request_id", "tenant", "label", "status",
#    "row"?: {...}, "error"?: ..., "resumed": bool}
#   {"type": "error", "message": ...}        (malformed request line)
# The protocol is the queue model verbatim (serve/queue.py); RunConfig
# payloads go through config_from_payload, so the socket surface can never
# accept a config the in-process surface would refuse.


def main(argv=None) -> int:
    """``erasurehead-tpu serve``: run the daemon behind a unix socket
    until interrupted. Clients: serve/client.ServeClient, or any tool
    that can write JSON lines to an AF_UNIX stream."""
    import argparse

    from erasurehead_tpu.utils.config import (
        resolve_serve_budget,
        resolve_serve_max_cohort,
    )

    p = argparse.ArgumentParser(
        prog="erasurehead-tpu serve",
        description=(
            "Multi-tenant sweep-as-a-service daemon: packs concurrent "
            "clients' compatible run requests into shared cohort "
            "dispatches under an HBM admission budget"
        ),
    )
    p.add_argument("--socket", default="/tmp/erasurehead-serve.sock",
                   help="unix socket path for the client front")
    p.add_argument("--budget", default=None,
                   help="in-flight HBM admission budget: bytes with an "
                        "optional k/m/g/t suffix (e.g. 2g). Default: "
                        "ERASUREHEAD_SERVE_BUDGET env, else unbounded")
    p.add_argument("--max-cohort", type=int, default=None,
                   help="packed dispatch width. Default: "
                        "ERASUREHEAD_SERVE_MAX_COHORT env, else "
                        f"{DEFAULT_MAX_COHORT}")
    p.add_argument("--no-pad", action="store_true",
                   help="dispatch cohorts at their natural width instead "
                        "of padding to --max-cohort (saves compute on "
                        "light CPU traffic; costs one compiled executable "
                        "per distinct width and makes a row's bits depend "
                        "on how it happened to pack)")
    p.add_argument("--window-ms", type=float, default=20.0,
                   help="packing window: how long a request waits for "
                        "compatible traffic to pack in behind it")
    p.add_argument("--journal-dir", default=None,
                   help="per-tenant sweep journals land under "
                        "DIR/<tenant>/ (train/journal.py); resubmitted "
                        "identical requests rehydrate without a dispatch")
    p.add_argument("--no-resume", action="store_true",
                   help="journal without serving rows back from it")
    p.add_argument("--dispatch-workers", type=int,
                   default=DEFAULT_DISPATCH_WORKERS,
                   help="concurrent dispatch threads (admission bounds "
                        "the memory, this bounds the overlap)")
    p.add_argument("--events", default=None,
                   help="write the daemon's serve/run event log here "
                        "(request/pack/admit/evict records; render with "
                        "`erasurehead-tpu report`)")
    p.add_argument("--eta-surface", default=None, metavar="DIR",
                   help="quote each accepted request an expected "
                        "time-to-target from a what-if surface artifact "
                        "(`erasurehead-tpu whatif --out DIR`); the quote "
                        "rides the socket front's accepted reply and the "
                        "request event as eta_s")
    p.add_argument("--http", default=None, metavar="HOST:PORT",
                   help="also listen on an HTTP/1.1 JSONL front "
                        "(serve/http_front.py): POST /v1/submit, "
                        "chunked-streaming GET /v1/stream, GET /healthz. "
                        "PORT 0 picks a free port (printed)")
    p.add_argument("--auth-tokens", default=None, metavar="FILE",
                   help="JSON {token: tenant} map; when set, the HTTP "
                        "front requires Authorization: Bearer <token> "
                        "and derives the tenant from it (the AF_UNIX "
                        "front stays filesystem-permission trust)")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="persist compiled executables via JAX's on-disk "
                        "compilation cache: a restarted daemon re-serves "
                        "its working set with zero fresh compiles")
    p.add_argument("--max-pending", type=int, default=None,
                   help="backpressure high-water mark on accepted-but-"
                        "undispatched requests; beyond it submissions "
                        "are rejected (HTTP 429 / socket 'rejected') "
                        "with a deferral-derived retry-after. Default: "
                        "unbounded")
    p.add_argument("--request-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="per-request result deadline from intake; on "
                        "expiry the daemon delivers a typed timeout "
                        "error (and emits a request_timeout warning) "
                        "instead of leaving the client to a silent "
                        "queue timeout. Default: wait forever")
    p.add_argument("--tenant-quota", type=int, default=None,
                   help="hard cap on one tenant's slots per packed "
                        "dispatch window (weighted-fair packing already "
                        "round-robins tenants; the quota is the "
                        "absolute bound, closing windows short when "
                        "only over-quota traffic remains)")
    p.add_argument("--no-fair", action="store_true",
                   help="disable weighted-fair packing: windows fill "
                        "FIFO by arrival, letting one chatty tenant "
                        "monopolize dispatches (the pre-PR-13 behavior)")
    p.add_argument("--slo-ttlr", type=float, default=None, metavar="SECONDS",
                   help="arm the per-tenant SLO tracker on the http front "
                        "(obs/exporter.SloTracker): requests whose "
                        "time-to-last-row exceeds this emit burn-rate "
                        "`slo` events and surface on /metrics; needs "
                        "--http")
    p.add_argument("--slo-budget", type=float, default=0.1,
                   help="error budget for --slo-ttlr: tolerated breach "
                        "fraction per window (burn rate 1.0 = breaching "
                        "exactly this often; default 0.1)")
    p.add_argument("--replica-name", default=None, metavar="NAME",
                   help="fleet identity: the name this daemon is known "
                        "by on the router's hash ring (serve/fleet.py); "
                        "gossiped on /healthz and stamped onto fleet "
                        "events")
    ns = p.parse_args(argv)
    budget = resolve_serve_budget(ns.budget)
    max_cohort = resolve_serve_max_cohort(
        ns.max_cohort, default=DEFAULT_MAX_COHORT
    )

    from erasurehead_tpu.parallel.backend import initialize_distributed

    initialize_distributed()
    eta_surface = None
    if ns.eta_surface:
        from erasurehead_tpu.whatif import Surface

        eta_surface = Surface.load(ns.eta_surface)
    # append, never truncate: a bounced daemon (fleet rolling deploy,
    # warm restart) reuses its events path, and the pre-bounce records
    # — adoptions, restart ledgers — are evidence the validators read.
    # validate_lines' seq checking is multi-stream for exactly this.
    capture = (
        events_lib.capture(ns.events, mode="a")
        if ns.events
        else contextlib.nullcontext()
    )
    with capture:
        srv = SweepServer(
            budget_bytes=budget,
            max_cohort=max_cohort,
            window_s=ns.window_ms / 1000.0,
            journal_dir=ns.journal_dir,
            resume=not ns.no_resume,
            dispatch_workers=ns.dispatch_workers,
            pad_cohorts=not ns.no_pad,
            eta_surface=eta_surface,
            max_pending=ns.max_pending,
            request_timeout_s=ns.request_timeout,
            fair=not ns.no_fair,
            tenant_quota=ns.tenant_quota,
            cache_dir=ns.cache_dir,
            replica_name=ns.replica_name,
        )
        srv.start()
        front = SocketFront(srv, ns.socket)
        http_front = None
        if ns.http:
            import json as json_lib

            from erasurehead_tpu.serve.http_front import (
                HttpFront,
                parse_hostport,
            )

            tokens = None
            if ns.auth_tokens:
                with open(ns.auth_tokens) as f:
                    tokens = json_lib.load(f)
            host, port = parse_hostport(ns.http)
            http_front = HttpFront(
                srv, host=host, port=port, tokens=tokens,
                slo_ttlr_s=ns.slo_ttlr, slo_budget=ns.slo_budget,
            )
        budget_str = f"{budget} bytes" if budget is not None else "unbounded"
        print(
            f"serve: listening on {ns.socket} (budget {budget_str}, "
            f"max cohort {max_cohort}, window {ns.window_ms:g} ms)",
            flush=True,
        )
        if http_front is not None:
            print(
                f"serve: http front on {http_front.host}:"
                f"{http_front.port} "
                f"(auth {'on' if ns.auth_tokens else 'off'})",
                flush=True,
            )
        try:
            while True:
                time.sleep(0.5)
        except KeyboardInterrupt:
            print("serve: draining and shutting down", flush=True)
        finally:
            if http_front is not None:
                http_front.close()
            front.close()
            srv.stop()
    return 0


class SocketFront:
    """AF_UNIX listener bridging socket clients onto a SweepServer."""

    def __init__(self, server: SweepServer, path: str):
        import socket as socket_lib

        self.server = server
        self.path = path
        if os.path.exists(path):
            os.unlink(path)
        self._sock = socket_lib.socket(
            socket_lib.AF_UNIX, socket_lib.SOCK_STREAM
        )
        self._sock.bind(path)
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        self._closing = False
        self._threads: list[threading.Thread] = []
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="eh-serve-socket", daemon=True
        )
        self._accept_thread.start()

    def close(self) -> None:
        import socket as socket_lib

        self._closing = True
        self._accept_thread.join(timeout=5)
        self._sock.close()
        # shut down accepted connections so their recv() unblocks and the
        # per-connection threads see _closing and exit
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket_lib.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()
        if os.path.exists(self.path):
            os.unlink(self.path)

    def _accept_loop(self) -> None:
        import socket as socket_lib

        while not self._closing:
            try:
                conn, _ = self._sock.accept()
            except socket_lib.timeout:
                continue
            except OSError:
                return
            # a finite recv timeout is what lets _serve_conn honor
            # _closing between lines instead of blocking forever
            conn.settimeout(0.5)
            with self._conns_lock:
                self._conns.add(conn)
            self._threads = [t for t in self._threads if t.is_alive()]
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn) -> None:
        import json as json_lib
        import socket as socket_lib

        from erasurehead_tpu.serve.queue import config_from_payload

        wlock = threading.Lock()

        def send(obj: dict) -> None:
            line = (json_lib.dumps(obj) + "\n").encode()
            with wlock:
                try:
                    conn.sendall(line)
                except OSError:
                    pass  # client went away; results are still journaled

        def relay(handle: RequestHandle) -> None:
            # poll rather than block forever: a close() mid-dispatch must
            # be able to retire this thread (the row is still journaled)
            while True:
                try:
                    res = handle.result(timeout=0.5)
                    break
                except queue_lib.Empty:
                    if self._closing:
                        return
            send(
                {
                    "type": "result",
                    "request_id": res.request_id,
                    "tenant": res.tenant,
                    "label": res.label,
                    "status": res.status,
                    "row": res.row,
                    "error": res.error,
                    "resumed": res.resumed,
                }
            )

        buf = b""
        try:
            with conn:
                while not self._closing:
                    try:
                        chunk = conn.recv(1 << 16)
                    except socket_lib.timeout:
                        continue  # idle; re-check _closing
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                    while b"\n" in buf:
                        raw, buf = buf.split(b"\n", 1)
                        if not raw.strip():
                            continue
                        try:
                            msg = json_lib.loads(raw)
                            if msg.get("op") != "submit":
                                raise ValueError(
                                    f"unknown op {msg.get('op')!r} "
                                    "(only 'submit')"
                                )
                            cfg = config_from_payload(
                                msg.get("config") or {}
                            )
                            handle = self.server.submit(
                                tenant=msg["tenant"],
                                label=msg["label"],
                                config=cfg,
                                target_loss=msg.get("target_loss"),
                                data_seed=int(msg.get("data_seed", 0)),
                                priority=int(msg.get("priority", 0)),
                                retry=int(msg.get("retry", 0)),
                            )
                        except ServeOverloadedError as e:
                            # backpressure, not failure: the client's
                            # capped-exponential backoff honors the quote
                            send(
                                {
                                    "type": "rejected",
                                    "retry_after_s": e.retry_after_s,
                                    "message": str(e),
                                }
                            )
                            continue
                        except Exception as e:  # noqa: BLE001 — per-line
                            send(
                                {
                                    "type": "error",
                                    "message": f"{type(e).__name__}: {e}",
                                }
                            )
                            continue
                        send(
                            {
                                "type": "accepted",
                                "request_id": handle.request_id,
                                # what-if ETA quote (simulated seconds to
                                # the loss target; None = no surface row)
                                "eta_s": handle.eta_s,
                            }
                        )
                        threading.Thread(
                            target=relay, args=(handle,), daemon=True
                        ).start()
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
