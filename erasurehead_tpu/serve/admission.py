"""Admission controller: bound the serve daemon's in-flight HBM.

A cohort dispatch pins device memory three ways: the shared data stack it
uploads (or reuses from the sweep data cache), the per-round weight tables
that scale with cohort width, and the compiled executable's own working
set. The controller charges each candidate cohort an ESTIMATE of that
footprint against a byte budget before it may dispatch:

  - the estimate starts from the host-side stack arithmetic the
    ``stack_mode="auto"`` gate already uses (trainer.estimate_stack_bytes,
    data/sharding.RING_AUTO_MIN_BYTES machinery) plus the weight-table
    bytes the cohort's width implies;
  - once a signature has actually dispatched, its compiled
    ``memory_analysis`` byte accounting (argument/temp/output) REFINES the
    estimate — later admissions of the same signature charge the measured
    peak when it is larger (estimates may undercount XLA temps);
  - the sweep data cache's device pins (cache.data_cache_bytes) count
    against the budget alongside in-flight charges — they are real HBM;
  - an over-footprint cohort QUEUES: it stays pending and is retried next
    loop, after in-flight dispatches release their charge. It never joins
    a running cohort's HBM — that is the whole point (an admission-control
    OOM would take innocent tenants' dispatches down with it);
  - when dropping the data cache's pins would change the verdict, the
    controller EVICTS the cache (cache.drop_data_cache — the same
    pressure valve the OOM-bisection ladder uses) and re-runs the FULL
    decision, so eviction can admit in the same call and an idle daemon
    can never strand a pending cohort;
  - a cohort too big for the budget even on an idle daemon admits alone
    with a warning (refusing forever would deadlock the tenant; alone, an
    OOM hurts only itself and the bisection ladder still degrades it).

Every decision is observable: ``admit`` events carry the estimate vs the
budget and the verdict, ``evict`` events name what was dropped, and the
``serve.admitted`` / ``serve.deferred`` / ``serve.evictions`` counters
aggregate them.

This module also hosts :class:`EtaQuoter`, the admission-time read side
of the what-if engine (erasurehead_tpu/whatif/): a loaded surface quotes
each arriving request's simulated expected time-to-target, so the daemon
can tell a tenant what its request will cost before dispatching it.
"""

from __future__ import annotations

import threading
from typing import Optional

from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY as _METRICS
from erasurehead_tpu.train import cache as cache_lib
from erasurehead_tpu.train import trainer

#: per-trajectory fixed overhead charged on top of the weight tables —
#: params history [R, F], optimizer state, host<->device staging slack
TRAJECTORY_SLACK_BYTES = 1 << 20


def estimate_cohort_bytes(cohort, width: Optional[int] = None) -> int:
    """Estimated device footprint of one packed cohort: ONE shared data
    stack (the pack key guarantees the cohort shares it) + width-scaled
    per-round weight tables + per-trajectory slack. ``width`` overrides
    the trajectory count (the server's fixed-width padded dispatch really
    allocates ``max_cohort`` table columns).

    ``stack_residency="streamed"`` payloads are charged their resident
    WINDOWS, not the whole stack: trainer.estimate_stack_bytes resolves
    the stream window (explicit ``stream_window`` or the host's
    ERASUREHEAD_STREAM_WINDOW budget) and bounds the stack term at two
    STAGED windows (compute + prefetch double buffer; a ring-transported
    window stages its assignment halo too, a materialized-faithful one
    its slot-group's worker gather — data/sharding.plan_stream_windows).
    Streamed requests pack with streamed requests (one windowed cohort
    scan) and never with resident ones — residency rides the static
    signature, so the pack key separates them by construction
    (tests/test_outofcore.py pins the negative)."""
    first = cohort.requests[0]
    cfg = first.config
    stack = trainer.estimate_stack_bytes(cfg, first.dataset)
    layout = trainer.build_layout(cfg)
    B = width if width is not None else len(cohort.requests)
    from erasurehead_tpu.utils.config import ComputeMode

    if cfg.compute_mode == ComputeMode.FAITHFUL:
        table_cols = layout.n_workers * layout.n_slots
    else:
        table_cols = layout.n_partitions
    tables = cfg.rounds * B * table_cols * 4  # f32 weight tables [R, B, ...]
    return int(stack + tables + B * TRAJECTORY_SLACK_BYTES)


class AdmissionController:
    """Byte-budgeted admission over concurrent cohort dispatches.

    ``budget_bytes=None`` = unbounded (every cohort admits; events still
    record the estimates, so a budget can be sized from a dry run)."""

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is not None and budget_bytes <= 0:
            raise ValueError(
                f"budget_bytes must be positive (or None for unbounded), "
                f"got {budget_bytes}"
            )
        self.budget_bytes = budget_bytes
        self._lock = threading.Lock()
        self._in_flight: dict[str, int] = {}  # key digest -> charged bytes
        self._measured: dict[str, int] = {}  # key digest -> measured bytes
        self._deferred_total = 0  # lifetime defer verdicts (pressure())

    def pressure(self) -> dict:
        """Admission pressure snapshot for operators: what /healthz and
        the retry-after story expose — charged in-flight bytes against
        the budget, live dispatch count, and how often this controller
        has had to defer (the overload trend a load balancer watches)."""
        with self._lock:
            in_flight = sum(self._in_flight.values())
            dispatches = len(self._in_flight)
            deferred = self._deferred_total
        return {
            "budget_bytes": self.budget_bytes,
            "in_flight_bytes": in_flight,
            "in_flight_dispatches": dispatches,
            "deferred_total": deferred,
        }

    @property
    def in_flight_bytes(self) -> int:
        with self._lock:
            return sum(self._in_flight.values())

    def charge_for(self, cohort, width: Optional[int] = None) -> int:
        """The bytes this cohort would be charged: the estimate, raised to
        the signature's measured compiled footprint when known & larger."""
        est = estimate_cohort_bytes(cohort, width=width)
        with self._lock:
            measured = self._measured.get(cohort.key_digest)
        if measured is not None:
            est = max(est, measured)
        return est

    def _decide_locked(self, est: int) -> str:
        """The admission verdict for ``est`` charged bytes (caller holds
        ``self._lock``): ``"admit"``, ``"evict"`` (dropping the data
        cache's pins would change the verdict — re-decide after), or
        ``"defer"``. The data cache's device pins count against the
        budget alongside in-flight charges, so evicting them genuinely
        moves the inequality."""
        budget = self.budget_bytes
        if budget is None:
            return "admit"
        in_flight = sum(self._in_flight.values())
        cached = cache_lib.data_cache_bytes()
        if in_flight + cached + est <= budget:
            return "admit"
        if cached > 0 and (in_flight + est <= budget or in_flight == 0):
            # the data cache's pins are idle capital: dropping them frees
            # real HBM without touching any live dispatch. Evict when
            # that alone closes the gap, or when the daemon is otherwise
            # idle (the admit-alone fallback below wants every byte)
            return "evict"
        if in_flight == 0:
            # nothing to wait for and nothing to evict: admitting alone
            # is the only non-deadlocking move
            return "admit"
        return "defer"

    def try_admit(
        self, cohort, dispatch_id: str, width: Optional[int] = None
    ) -> bool:
        """Admit ``cohort`` (charging its footprint until
        :meth:`release`), or defer it. Emits one ``admit`` event either
        way; eviction of data-cache pins happens here when it is what
        stands between the cohort and the budget."""
        est = self.charge_for(cohort, width=width)
        with self._lock:
            verdict = self._decide_locked(est)
            if verdict == "admit":
                self._in_flight[dispatch_id] = est
        if verdict == "evict":
            released = cache_lib.drop_data_cache()
            _METRICS.counter("serve.evictions").inc()
            events_lib.emit(
                "evict",
                reason="data_cache_pressure",
                cohort=cohort.key_digest,
                released_bytes=released,
            )
            with self._lock:
                # full re-decision with the pins gone, INCLUDING the idle
                # admit-alone fallback — an idle daemon must never strand
                # a pending cohort after dropping its cache for it
                verdict = self._decide_locked(est)
                if verdict == "evict":
                    # a concurrent dispatch repopulated the cache between
                    # the drop and this lock; defer rather than thrash
                    verdict = "defer"
                if verdict == "admit":
                    self._in_flight[dispatch_id] = est
        admitted = verdict == "admit"
        if admitted and self.budget_bytes is not None and (
            est > self.budget_bytes
        ):
            from erasurehead_tpu.obs.metrics import warn_once

            warn_once(
                f"serve_overbudget_{cohort.key_digest}",
                f"serve: cohort {cohort.key_digest} estimate {est}B "
                f"exceeds the whole budget {self.budget_bytes}B; admitted "
                f"ALONE (refusing forever would deadlock the tenant) — "
                f"the OOM-bisection ladder is its safety net",
            )
        if not admitted:
            with self._lock:
                self._deferred_total += 1
        _METRICS.counter(
            "serve.admitted" if admitted else "serve.deferred"
        ).inc()
        events_lib.emit(
            "admit",
            est_bytes=est,
            budget_bytes=self.budget_bytes,
            in_flight_bytes=self.in_flight_bytes,
            admitted=admitted,
            cohort=cohort.key_digest,
            n_trajectories=len(cohort.requests),
        )
        return admitted

    def release(self, dispatch_id: str) -> None:
        """Return a finished (or failed) dispatch's charge to the budget."""
        with self._lock:
            self._in_flight.pop(dispatch_id, None)

    def observe(self, cohort, cache_info: Optional[dict]) -> None:
        """Refine the signature's footprint with the dispatch's compiled
        ``memory_analysis`` accounting (argument + output + temp bytes ~
        the executable's live working set). Estimates only ever RATCHET UP
        — a measured undercount must not talk admission into optimism."""
        ma = (cache_info or {}).get("memory_analysis") or {}
        measured = sum(
            int(ma.get(k) or 0)
            for k in ("argument_bytes", "output_bytes", "temp_bytes")
        )
        if measured <= 0:
            return
        with self._lock:
            prev = self._measured.get(cohort.key_digest, 0)
            if measured > prev:
                self._measured[cohort.key_digest] = measured


class EtaQuoter:
    """Admission-time ETA quotes from a what-if surface.

    The what-if engine's surface rows (whatif/surface.py) carry each
    policy coordinate's SIMULATED expected time-to-target; the quoter is
    the serve daemon's read side: given an arriving request's RunConfig,
    look up the nearest feasible row and quote its expected simulated
    seconds-to-target. The quote rides the ``request`` event and the
    socket front's ``accepted`` reply (``eta_s``), so a tenant knows the
    expected cost of what it just enqueued BEFORE any dispatch runs.

    A quote is a simulation-derived expectation, not a promise: None
    whenever the surface has no feasible row for the policy (the daemon
    serves the request either way). The per-request lookup is a host-side
    list scan over the surface rows — microseconds against a packing
    window of tens of milliseconds.
    """

    def __init__(self, surface):
        if surface is None:
            raise ValueError(
                "EtaQuoter needs a whatif Surface (erasurehead-tpu "
                "whatif --out DIR; Surface.load(DIR))"
            )
        self.surface = surface

    def quote(self, cfg) -> Optional[float]:
        """Expected time-to-target (simulated seconds) for a request's
        policy coordinate, or None when the surface cannot speak for
        it."""
        return self.surface.eta(cfg)
