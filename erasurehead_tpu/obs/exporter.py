"""Prometheus text exposition, SLO burn-rate tracking, and the live
``top`` renderer — the scrape-facing edge of the telemetry plane.

Three surfaces over the same data:

  - :func:`render_prometheus` — text-format (version 0.0.4) exposition
    of a :class:`~erasurehead_tpu.obs.metrics.MetricsRegistry` plus any
    flat gauge map (obs/timeseries.TimeseriesReducer.gauges), served by
    ``GET /metrics`` on the serve HTTP front. Hand-rolled: the
    no-new-deps discipline (serve/http_front.py) applies to exporters
    too. Metric names sanitize to ``[a-zA-Z_:][a-zA-Z0-9_:]*`` under an
    ``erasurehead_`` prefix; label values escape ``\\``, ``"`` and
    newlines per the exposition spec; families and lines render in
    sorted order so two scrapes of the same state are byte-identical.
  - :class:`SloTracker` — per-tenant time-to-last-row SLO scoring over
    the ``request`` intake/done record pairs, emitting typed ``slo``
    events with the window's burn rate (breach fraction over error
    budget; > 1 = the budget is burning faster than allowed).
  - :func:`top_main` — ``erasurehead-tpu top <events.jsonl|url>``: a
    live follow renderer over the timeseries reducer (or a remote
    daemon's /metrics text), refreshing a one-screen summary.

Everything is host-side and read-only over already-emitted records: the
observation-only contract is untouched.
"""

from __future__ import annotations

import json
import re
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs import metrics as metrics_lib

#: the exposition content type GET /metrics answers with
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: prefix every exported metric family carries
PROM_PREFIX = "erasurehead_"

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    """Dotted registry names -> valid Prometheus metric names."""
    out = _NAME_OK.sub("_", str(name))
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(v) -> str:
    """Escape a label value per the text exposition format."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def prom_key(name: str, **labels) -> str:
    """Build a ``name{k="v",...}`` series key with escaped values and
    sorted labels (the convention timeseries gauges use)."""
    base = sanitize_name(name)
    if not labels:
        return base
    inner = ",".join(
        f'{sanitize_name(k)}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items())
    )
    return f"{base}{{{inner}}}"


def _family_of(series_key: str) -> str:
    """The metric family a (possibly labeled) series key belongs to."""
    return series_key.split("{", 1)[0]


def _fmt_value(v) -> str:
    f = float(v)
    if f != f:  # NaN
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(
    registry: Optional[metrics_lib.MetricsRegistry] = None,
    gauges: Optional[dict] = None,
    prefix: str = PROM_PREFIX,
) -> str:
    """Render the registry + extra gauges as Prometheus text exposition.

    ``gauges`` maps series keys (plain names or ``prom_key`` outputs) to
    float values. Histograms export as summaries (quantile series +
    ``_sum``/``_count``). Output order is deterministic: families sorted
    by name, series sorted within each family.
    """
    families: dict = {}  # prefixed family -> (type, [(series_key, value)])

    def add(family: str, kind: str, series_key: str, value) -> None:
        fam = families.setdefault(family, (kind, []))
        fam[1].append((series_key, value))

    if registry is not None:
        for name, kind, exported in registry.export_typed():
            fam = prefix + sanitize_name(name)
            if kind == "histogram":
                if exported.get("count", 0):
                    for q in ("p50", "p90", "p99"):
                        v = exported.get(q)
                        if v is not None:
                            add(
                                fam, "summary",
                                f'{fam}{{quantile="0.{q[1:]}"}}', v,
                            )
                add(fam + "_sum", "counter", fam + "_sum",
                    exported.get("sum", 0.0))
                add(fam + "_count", "counter", fam + "_count",
                    exported.get("count", 0))
            else:
                add(fam, kind, fam, exported)
    for key, value in (gauges or {}).items():
        base = _family_of(key)
        fam = prefix + sanitize_name(base)
        series = fam + key[len(base):]  # re-attach any label block
        add(fam, "gauge", series, value)

    lines = []
    for fam in sorted(families):
        kind, series = families[fam]
        lines.append(f"# TYPE {fam} {kind}")
        for key, value in sorted(series):
            lines.append(f"{key} {_fmt_value(value)}")
    return "\n".join(lines) + "\n"


def fleet_gauges(view: dict) -> dict:
    """The serve fleet's gauge plane from a router membership view
    (serve/router.FleetRouter.fleet_view): how many replicas are
    routable vs merely known, how many proxies had to leave their
    primary, how many dead peers' WALs were adopted, and each live
    replica's admission pressure — series keys ready for
    :func:`render_prometheus`."""
    replicas = view.get("replicas") or {}
    out = {
        prom_key("fleet_replicas_live"): sum(
            1 for r in replicas.values() if r.get("alive")
        ),
        prom_key("fleet_replicas_known"): len(replicas),
        prom_key("fleet_router_redirects_total"): int(
            view.get("redirects_total") or 0
        ),
        prom_key("fleet_adoptions_total"): int(
            view.get("adoptions_total") or 0
        ),
    }
    for name, r in sorted(replicas.items()):
        if r.get("pressure") is not None:
            out[prom_key("fleet_replica_pressure", replica=name)] = (
                float(r["pressure"])
            )
    return out


# ---------------------------------------------------------------------------
# SLO tracking: per-tenant time-to-last-row burn rate


class SloTracker:
    """Score per-tenant time-to-last-row against an SLO and emit typed
    ``slo`` burn-rate events.

    Feed it the event stream (:meth:`observe` accepts every record and
    reads only ``request`` intake/done pairs); call :meth:`evaluate`
    periodically. The burn rate is the classic SRE quantity: the
    window's breach fraction divided by the error budget — 1.0 means
    the tenant is burning budget exactly at the allowed rate, above
    that the ``slo`` event doubles as the warning (consumers alert on
    ``burn_rate > 1``). Bounded memory: at most ``max_open`` open
    requests and one window of completions are retained.
    """

    def __init__(
        self,
        slo_ttlr_s: float,
        *,
        budget: float = 0.1,
        window_s: float = 60.0,
        max_open: int = 4096,
    ):
        if slo_ttlr_s <= 0:
            raise ValueError(f"slo_ttlr_s must be > 0, got {slo_ttlr_s}")
        if not 0 < budget <= 1:
            raise ValueError(f"budget must be in (0, 1], got {budget}")
        self.slo_ttlr_s = float(slo_ttlr_s)
        self.budget = float(budget)
        self.window_s = float(window_s)
        self.max_open = int(max_open)
        self._lock = threading.Lock()
        self._open: OrderedDict = OrderedDict()  # request_id -> (tenant, t)
        self._done: deque = deque()  # (t_done, tenant, ttlr_s)

    def observe(self, rec: dict) -> None:
        if rec.get("type") != "request":
            return
        rid = rec.get("request_id")
        tenant = rec.get("tenant")
        t = rec.get("t")
        if not isinstance(rid, str) or not isinstance(t, (int, float)):
            return
        with self._lock:
            if rec.get("phase") == "done":
                start = self._open.pop(rid, None)
                if start is not None:
                    self._done.append((t, start[0], t - start[1]))
            else:
                self._open[rid] = (tenant or "?", float(t))
                while len(self._open) > self.max_open:
                    self._open.popitem(last=False)

    def observe_submit(self, request_id: str, tenant: str, t=None):
        """Programmatic intake (serve daemons without a capture)."""
        self.observe({
            "type": "request", "request_id": request_id,
            "tenant": tenant, "label": "",
            "t": time.time() if t is None else t,
        })

    def observe_done(self, request_id: str, t=None) -> None:
        with self._lock:
            start = self._open.pop(request_id, None)
            if start is not None:
                now = time.time() if t is None else t
                self._done.append((now, start[0], now - start[1]))

    def evaluate(self, now: Optional[float] = None) -> list:
        """Per-tenant window scores; emits one ``slo`` event per tenant
        that completed requests in the window. Returns the payloads."""
        now = time.time() if now is None else now
        with self._lock:
            while self._done and self._done[0][0] < now - self.window_s:
                self._done.popleft()
            per_tenant: dict = {}
            for _, tenant, ttlr in self._done:
                reqs, breaches, worst = per_tenant.get(
                    tenant, (0, 0, 0.0)
                )
                per_tenant[tenant] = (
                    reqs + 1,
                    breaches + (1 if ttlr > self.slo_ttlr_s else 0),
                    max(worst, ttlr),
                )
        out = []
        for tenant in sorted(per_tenant):
            reqs, breaches, worst = per_tenant[tenant]
            burn = (breaches / reqs) / self.budget if reqs else 0.0
            payload = {
                "tenant": tenant,
                "slo_s": round(self.slo_ttlr_s, 6),
                "window_requests": reqs,
                "breaches": breaches,
                "burn_rate": round(burn, 4),
                "worst_ttlr_s": round(worst, 6),
                "budget": self.budget,
            }
            events_lib.emit("slo", **payload)
            out.append(payload)
        return out


# ---------------------------------------------------------------------------
# the `erasurehead-tpu top` live follow renderer


def _render_frame(snap: dict, source: str, slo_rows: list) -> str:
    """One screenful from a reducer snapshot."""
    lines = [
        f"erasurehead-tpu top — {source}   "
        f"events {snap['consumed']} ({snap['malformed']} malformed)"
    ]
    windows = snap.get("windows") or []
    if windows:
        w = windows[-1]

        def fmt(v, spec="{:.4g}"):
            return spec.format(v) if v is not None else "-"

        arr = w["arrival"]
        lines.append(
            f"rounds/s wall {fmt(w['rounds_per_wall_sec'])} | "
            f"sim {fmt(w['rounds_per_sim_sec'])} | arrival p50/p90/p99 "
            f"{fmt(arr['p50'])}/{fmt(arr['p90'])}/{fmt(arr['p99'])}s"
        )
        lines.append(
            f"decode err {fmt(w['decode_error_mean'], '{:.3e}')} "
            f"(exact {fmt(w['decode_exact_share'])}) | staleness share "
            f"{fmt(w['staleness_share'])} | cache hits exec "
            f"{fmt(w['compile_cache_hit_rate'])} data "
            f"{fmt(w['data_cache_hit_rate'])} | prefetch "
            f"{fmt(w['prefetch_bytes_per_sec'], '{:.3g}')} B/s"
        )
        if w["tenants"]:
            lines.append("tenant            requests  rows_ok  rejects")
            for tenant, tv in w["tenants"].items():
                lines.append(
                    f"  {tenant[:16]:16s} {tv['requests']:>7d} "
                    f"{tv['rows_ok']:>8d} {tv['rejects']:>8d}"
                )
    cp = snap.get("critical_path")
    if cp:
        from erasurehead_tpu.obs import critical_path as cp_lib

        lines.append("critical path:")
        lines.extend(cp_lib.render_lines(cp))
    reg = snap.get("regime")
    if reg:
        shift = (
            f" (shift @ round {reg['shift_round']})"
            if reg.get("shift_round") is not None
            else ""
        )
        lines.append(
            f"regime: {reg.get('kind')} rate={reg.get('rate')}/s "
            f"tail_index={reg.get('tail_index')}{shift}"
        )
    for row in slo_rows:
        state = "BURNING" if row["burn_rate"] > 1.0 else "ok"
        lines.append(
            f"slo[{row['tenant']}]: ttlr<={row['slo_s']}s "
            f"{row['breaches']}/{row['window_requests']} breached, "
            f"burn {row['burn_rate']:.2f} ({state})"
        )
    return "\n".join(lines)


def _top_url(url: str, interval_s: float, follow: bool) -> int:
    """Remote mode: poll a daemon's /metrics and echo the exposition."""
    from urllib.request import urlopen

    target = url.rstrip("/")
    if not target.endswith("/metrics"):
        target += "/metrics"
    while True:
        try:
            with urlopen(target, timeout=10.0) as resp:
                body = resp.read().decode()
        except OSError as e:
            print(f"top: {target}: {e}", file=sys.stderr)
            return 1
        if follow:
            sys.stdout.write("\x1b[2J\x1b[H")
        ts = time.strftime("%H:%M:%S")
        sys.stdout.write(f"# scrape {target} @ {ts}\n{body}")
        sys.stdout.flush()
        if not follow:
            return 0
        time.sleep(interval_s)


def top_main(argv: Optional[list] = None) -> int:
    """``erasurehead-tpu top <events.jsonl|url>``: live telemetry view.

    File mode tails the log through the timeseries reducer (``--follow``
    keeps watching a growing file); URL mode polls a serve daemon's
    ``/metrics``. ``--slo-ttlr SECONDS`` arms the SLO tracker, which
    emits ``slo`` burn-rate events into the current capture (if any)
    and renders per-tenant burn lines."""
    import argparse

    p = argparse.ArgumentParser(
        prog="erasurehead-tpu top",
        description="live telemetry over an events.jsonl or daemon URL",
    )
    p.add_argument("source", help="events.jsonl path or http://host:port")
    p.add_argument(
        "--follow", action="store_true",
        help="keep tailing/polling (default: one frame and exit)",
    )
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument(
        "--window", type=float, default=5.0,
        help="reducer window seconds",
    )
    p.add_argument(
        "--slo-ttlr", type=float, default=None, metavar="SECONDS",
        help="time-to-last-row SLO; emits per-tenant slo burn events",
    )
    p.add_argument(
        "--slo-budget", type=float, default=0.1,
        help="allowed breach fraction behind the burn rate",
    )
    args = p.parse_args(argv)

    if args.source.startswith(("http://", "https://")):
        return _top_url(args.source, args.interval, args.follow)

    from erasurehead_tpu.obs.timeseries import TimeseriesReducer

    red = TimeseriesReducer(window_s=args.window)
    slo = (
        SloTracker(args.slo_ttlr, budget=args.slo_budget)
        if args.slo_ttlr
        else None
    )
    next_frame = 0.0

    def frame():
        rows = slo.evaluate() if slo else []
        out = _render_frame(red.snapshot(), args.source, rows)
        if args.follow:
            sys.stdout.write("\x1b[2J\x1b[H")
        sys.stdout.write(out + "\n")
        sys.stdout.flush()

    try:
        for rec in red.tail(
            args.source, follow=args.follow, poll_s=min(0.2, args.interval)
        ):
            if slo:
                slo.observe(rec)
            if args.follow and time.monotonic() >= next_frame:
                frame()
                next_frame = time.monotonic() + args.interval
    except FileNotFoundError:
        print(f"top: no such file: {args.source}", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        pass
    frame()
    return 0


def load_metrics_json(path: str) -> dict:
    """Read the final ``metrics`` snapshot record out of an events.jsonl
    (the capture's closing registry dump) — a convenience for tools."""
    snap: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("type") == "metrics":
                snap = rec.get("snapshot") or snap
    return snap
