"""Bounded-memory streaming aggregation of the typed event stream.

Everything the system emits is a typed JSONL record (obs/events.SCHEMA);
until now all of it was post-hoc — readable only after the run, by
loading the whole file. This module turns the same stream into *live*
windowed series with O(max_windows) memory, consumed either by tailing
a growing events.jsonl (:meth:`TimeseriesReducer.tail`) or attached
in-process to whatever ``events.capture()`` is emitting
(:meth:`TimeseriesReducer.attach`, via events.add_observer — the serve
daemon's ``/metrics`` loop).

Series maintained per wall-clock window (default 5 s, last 120
windows):

  - training throughput: rounds landed, simulated seconds, rounds/sec
    on both clocks;
  - arrival quantiles (p50/p90/p99/mean) merged from the chunked
    ``rounds`` records' masked summaries;
  - decode health: error mean/max, exact-decode share, and the
    staleness-vs-coding split from ``stale_decode``;
  - prefetch: staged bytes, fetch seconds, effective bytes/s;
  - cache hit rates: executable (``compile``) and device-data
    (``data_upload``);
  - per-tenant serve goodput: intake requests, completed rows
    (``request`` phase="done" markers), rejects.

The reducer also keeps the latest ``critical_path`` ledger, ``regime``
estimate and per-tenant ``slo`` burn rates — the gauges
obs/exporter.py renders at ``GET /metrics``.

Strictly a consumer: it never emits, never blocks a producer (observer
exceptions are swallowed upstream), and drops malformed lines with a
counter instead of raising — a telemetry reader must never take down
the thing it watches.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Iterator, Optional

#: tenants tracked per window before the long tail aggregates as "..."
MAX_TENANTS = 64


def _window_blank() -> dict:
    return {
        "events": {},
        "rounds": 0,
        "sim_time_s": 0.0,
        "arrival": {"p50": [], "p90": [], "p99": [], "mean": []},
        "decode_err_sum": 0.0,
        "decode_err_max": 0.0,
        "decode_n": 0,
        "decode_exact_n": 0,
        "stale_share_sum": 0.0,
        "stale_n": 0,
        "prefetch_bytes": 0,
        "prefetch_fetch_s": 0.0,
        "compile_hits": 0,
        "compile_n": 0,
        "data_hits": 0,
        "data_n": 0,
        "tenants": {},
    }


def _tenant_blank() -> dict:
    return {"requests": 0, "done": 0, "rows_ok": 0, "rejects": 0}


class TimeseriesReducer:
    """Windowed streaming reducer over typed event records."""

    def __init__(self, window_s: float = 5.0, max_windows: int = 120):
        if window_s <= 0 or max_windows < 1:
            raise ValueError(
                f"window_s must be > 0 and max_windows >= 1, got "
                f"{window_s}/{max_windows}"
            )
        self.window_s = float(window_s)
        self.max_windows = int(max_windows)
        self._lock = threading.Lock()
        self._windows: collections.OrderedDict = collections.OrderedDict()
        self._malformed = 0
        self._consumed = 0
        self._last_critical_path: Optional[dict] = None
        self._last_regime: Optional[dict] = None
        self._last_run_end: Optional[dict] = None
        self._slo_by_tenant: dict = {}

    # ---- ingestion -------------------------------------------------------

    def consume_line(self, line: str) -> bool:
        """Parse one JSONL line and consume it; malformed lines are
        counted and dropped (a live tail can race a partial write)."""
        line = line.strip()
        if not line:
            return False
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("not an object")
        except ValueError:
            with self._lock:
                self._malformed += 1
            return False
        self.consume(rec)
        return True

    def consume(self, rec: dict) -> None:
        """Fold one typed record into the windowed series (the
        events.add_observer entry point — must stay cheap and
        non-raising for well-formed records)."""
        rtype = rec.get("type")
        t = rec.get("t")
        if not isinstance(t, (int, float)):
            t = time.time()
        with self._lock:
            self._consumed += 1
            w = self._window_for(t)
            w["events"][rtype] = w["events"].get(rtype, 0) + 1
            if rtype == "rounds":
                w["rounds"] += int(rec.get("n_rounds", 0) or 0)
                w["sim_time_s"] += float(rec.get("sim_time_s", 0.0) or 0.0)
                arr = rec.get("arrival") or {}
                for q in ("p50", "p90", "p99", "mean"):
                    v = arr.get(q)
                    if isinstance(v, (int, float)):
                        w["arrival"][q].append(
                            (float(v), int(arr.get("n_arrivals", 1) or 1))
                        )
            elif rtype == "decode":
                n = int(rec.get("n_rounds", 0) or 0)
                w["decode_n"] += n
                w["decode_err_sum"] += n * float(
                    rec.get("error_mean", 0.0) or 0.0
                )
                w["decode_err_max"] = max(
                    w["decode_err_max"],
                    float(rec.get("error_max", 0.0) or 0.0),
                )
                if rec.get("exact"):
                    w["decode_exact_n"] += n
            elif rtype == "stale_decode":
                w["stale_n"] += 1
                w["stale_share_sum"] += float(
                    rec.get("staleness_share", 0.0) or 0.0
                )
            elif rtype == "prefetch":
                w["prefetch_bytes"] += int(rec.get("bytes", 0) or 0)
                w["prefetch_fetch_s"] += float(
                    rec.get("fetch_s", 0.0) or 0.0
                )
            elif rtype == "compile":
                w["compile_n"] += 1
                if rec.get("cache_hit"):
                    w["compile_hits"] += 1
            elif rtype == "data_upload":
                w["data_n"] += 1
                if rec.get("cache_hit"):
                    w["data_hits"] += 1
            elif rtype == "request":
                ten = self._tenant_slot(w, rec.get("tenant"))
                if rec.get("phase") == "done":
                    ten["done"] += 1
                    if rec.get("status") == "ok":
                        ten["rows_ok"] += 1
                else:
                    ten["requests"] += 1
            elif rtype == "reject":
                self._tenant_slot(w, rec.get("tenant"))["rejects"] += 1
            elif rtype == "critical_path":
                self._last_critical_path = rec
            elif rtype == "regime":
                self._last_regime = rec
            elif rtype == "run_end":
                self._last_run_end = rec
            elif rtype == "slo":
                tenant = rec.get("tenant")
                if isinstance(tenant, str):
                    self._slo_by_tenant[tenant] = rec
                    while len(self._slo_by_tenant) > MAX_TENANTS:
                        self._slo_by_tenant.pop(
                            next(iter(self._slo_by_tenant))
                        )

    def _window_for(self, t: float) -> dict:
        key = int(t // self.window_s)
        w = self._windows.get(key)
        if w is None:
            w = _window_blank()
            self._windows[key] = w
            while len(self._windows) > self.max_windows:
                self._windows.popitem(last=False)
        return w

    @staticmethod
    def _tenant_slot(w: dict, tenant) -> dict:
        name = tenant if isinstance(tenant, str) and tenant else "?"
        tenants = w["tenants"]
        if name not in tenants and len(tenants) >= MAX_TENANTS:
            name = "..."  # bounded memory: the long tail aggregates
        return tenants.setdefault(name, _tenant_blank())

    # ---- attachment ------------------------------------------------------

    def attach(self):
        """Attach in-process to the current event stream
        (events.add_observer); returns a detach callable, and works as a
        context manager via :class:`_Attached`."""
        from erasurehead_tpu.obs import events

        events.add_observer(self.consume)
        return _Attached(self)

    def tail(
        self,
        path: str,
        *,
        follow: bool = False,
        poll_s: float = 0.2,
        stop=None,
    ) -> Iterator[dict]:
        """Tail an events.jsonl through the reducer, yielding each
        consumed record. ``follow=False`` reads to EOF once (a finished
        run); ``follow=True`` keeps polling a growing file until
        ``stop()`` returns True. Partial trailing lines (a writer
        mid-record) are held back until complete."""
        buf = ""
        with open(path, "r") as f:
            while True:
                chunk = f.read(65536)
                if chunk:
                    buf += chunk
                    *lines, buf = buf.split("\n")
                    for line in lines:
                        if not line.strip():
                            continue
                        if self.consume_line(line):
                            yield json.loads(line)
                    continue
                if not follow or (stop is not None and stop()):
                    break
                time.sleep(poll_s)
        if buf.strip() and self.consume_line(buf):
            yield json.loads(buf)

    # ---- querying --------------------------------------------------------

    def snapshot(self) -> dict:
        """Windowed series + latest-record state, JSON-ready."""
        with self._lock:
            windows = [
                {"t0": key * self.window_s, **self._render_window(w)}
                for key, w in self._windows.items()
            ]
            return {
                "window_s": self.window_s,
                "consumed": self._consumed,
                "malformed": self._malformed,
                "windows": windows,
                "critical_path": self._last_critical_path,
                "regime": self._last_regime,
                "run_end": self._last_run_end,
                "slo": dict(self._slo_by_tenant),
            }

    def _render_window(self, w: dict) -> dict:
        def wavg(pairs):
            tot = sum(n for _, n in pairs)
            return (
                sum(v * n for v, n in pairs) / tot if tot > 0 else None
            )

        return {
            "events": dict(w["events"]),
            "rounds": w["rounds"],
            "sim_time_s": round(w["sim_time_s"], 6),
            "rounds_per_wall_sec": round(w["rounds"] / self.window_s, 4),
            "rounds_per_sim_sec": (
                round(w["rounds"] / w["sim_time_s"], 4)
                if w["sim_time_s"] > 0
                else None
            ),
            "arrival": {
                q: (round(v, 6) if v is not None else None)
                for q, v in (
                    (q, wavg(w["arrival"][q]))
                    for q in ("p50", "p90", "p99", "mean")
                )
            },
            "decode_error_mean": (
                round(w["decode_err_sum"] / w["decode_n"], 10)
                if w["decode_n"] > 0
                else None
            ),
            "decode_error_max": round(w["decode_err_max"], 10),
            "decode_exact_share": (
                round(w["decode_exact_n"] / w["decode_n"], 4)
                if w["decode_n"] > 0
                else None
            ),
            "staleness_share": (
                round(w["stale_share_sum"] / w["stale_n"], 4)
                if w["stale_n"] > 0
                else None
            ),
            "prefetch_bytes": w["prefetch_bytes"],
            "prefetch_bytes_per_sec": (
                round(w["prefetch_bytes"] / w["prefetch_fetch_s"], 1)
                if w["prefetch_fetch_s"] > 0
                else None
            ),
            "compile_cache_hit_rate": (
                round(w["compile_hits"] / w["compile_n"], 4)
                if w["compile_n"] > 0
                else None
            ),
            "data_cache_hit_rate": (
                round(w["data_hits"] / w["data_n"], 4)
                if w["data_n"] > 0
                else None
            ),
            "tenants": {
                t: dict(v) for t, v in sorted(w["tenants"].items())
            },
        }

    def gauges(self) -> dict:
        """Flat metric-name -> value map for the Prometheus exporter:
        the most recent window's series plus the latest critical-path
        fractions, regime estimate and per-tenant SLO burn rates.
        Label-carrying names use the exporter's ``name{label="v"}``
        convention."""
        from erasurehead_tpu.obs.exporter import prom_key

        snap = self.snapshot()
        out = {
            "timeseries_consumed_total": float(snap["consumed"]),
            "timeseries_malformed_total": float(snap["malformed"]),
        }
        if snap["windows"]:
            w = snap["windows"][-1]
            out["rounds_per_wall_sec"] = float(w["rounds_per_wall_sec"])
            for key in (
                "rounds_per_sim_sec", "decode_error_mean",
                "decode_exact_share", "staleness_share",
                "compile_cache_hit_rate", "data_cache_hit_rate",
                "prefetch_bytes_per_sec",
            ):
                if w.get(key) is not None:
                    out[key] = float(w[key])
            for q, v in w["arrival"].items():
                if v is not None:
                    out[prom_key("arrival_seconds", quantile=q)] = float(v)
            for tenant, tv in w["tenants"].items():
                for field in ("requests", "rows_ok", "rejects"):
                    out[
                        prom_key(f"tenant_{field}", tenant=tenant)
                    ] = float(tv[field])
        cp = snap.get("critical_path")
        if cp:
            for k, v in (cp.get("fractions") or {}).items():
                if isinstance(v, (int, float)):
                    out[
                        prom_key("critical_path_fraction", bucket=k)
                    ] = float(v)
        reg = snap.get("regime")
        if reg:
            if isinstance(reg.get("rate"), (int, float)):
                out["regime_arrival_rate"] = float(reg["rate"])
            if isinstance(reg.get("tail_index"), (int, float)):
                out["regime_tail_index"] = float(reg["tail_index"])
            out["regime_heavytail"] = (
                1.0 if reg.get("kind") == "heavytail" else 0.0
            )
        for tenant, rec in (snap.get("slo") or {}).items():
            if isinstance(rec.get("burn_rate"), (int, float)):
                out[
                    prom_key("slo_burn_rate", tenant=tenant)
                ] = float(rec["burn_rate"])
        return out


class _Attached:
    """Detach handle/context manager returned by
    :meth:`TimeseriesReducer.attach`."""

    def __init__(self, reducer: TimeseriesReducer):
        self._reducer = reducer

    def __call__(self) -> None:
        self.detach()

    def detach(self) -> None:
        from erasurehead_tpu.obs import events

        events.remove_observer(self._reducer.consume)

    def __enter__(self) -> TimeseriesReducer:
        return self._reducer

    def __exit__(self, *exc) -> bool:
        self.detach()
        return False


def tail_path(
    path: str, *, follow: bool = False, **kw
) -> TimeseriesReducer:
    """Convenience: reduce a whole events.jsonl in one call."""
    red = TimeseriesReducer(**kw)
    if os.path.exists(path):
        for _ in red.tail(path, follow=follow):
            pass
    return red
