"""Labeled metrics registry: counters, gauges, histograms, snapshot export.

Replaces the ad-hoc telemetry plumbing that grew around the sweep caches
(train/cache.py's hand-rolled ``CacheStats`` fields) with one registry any
module can write to under a dotted name ("sweep_cache.exec_hits",
"train.compile_seconds", ...). Everything is plain host-side Python — no
device interaction, so recording a metric can never perturb a run.

The process-default registry is :data:`REGISTRY`; ``snapshot()`` exports
every metric as JSON-ready values (the event log writes one ``metrics``
record per capture from it, obs/events.py).
"""

from __future__ import annotations

import math
import threading
from typing import Optional


class Counter:
    """Monotonically increasing value (int or float increments).

    Increments are lock-guarded: the serve daemon (erasurehead_tpu/serve/)
    bumps counters from its dispatch pool threads, and ``+=`` alone is not
    atomic under free-threaded interleavings."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value

    def reset(self) -> None:
        self._value = 0

    def export(self):
        return self._value


class Gauge:
    """Last-written value (e.g. steps/sec of the most recent run)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0

    def set(self, v) -> None:
        self._value = v

    @property
    def value(self):
        return self._value

    def reset(self) -> None:
        self._value = 0.0

    def export(self):
        return self._value


class Histogram:
    """Streaming distribution summary: count/sum/min/max plus a bounded
    sample reservoir for quantiles (runs observe at most thousands of
    values; the cap only guards long-lived processes)."""

    __slots__ = ("name", "count", "sum", "min", "max", "_sample")

    MAX_SAMPLE = 4096

    def __init__(self, name: str):
        self.name = name
        self.reset()

    def reset(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._sample: list[float] = []

    def observe(self, v) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if len(self._sample) < self.MAX_SAMPLE:
            self._sample.append(v)
        else:
            # deterministic decimation (no RNG: runs must replay exactly):
            # overwrite round-robin so the sample keeps covering the stream
            self._sample[self.count % self.MAX_SAMPLE] = v

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float) -> Optional[float]:
        if not self._sample:
            return None
        s = sorted(self._sample)
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return s[idx]

    def export(self) -> dict:
        if not self.count:
            return {"count": 0, "sum": 0.0}
        return {
            "count": self.count,
            "sum": self.sum,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.quantile(0.5),
            "p90": self.quantile(0.9),
            "p99": self.quantile(0.99),
        }


class MetricsRegistry:
    """Name -> metric, get-or-create per kind; a name registered as one
    kind cannot be re-requested as another (loud, not silently aliased)."""

    def __init__(self):
        self._metrics: dict = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict:
        """Every metric exported as JSON-ready values, sorted by name."""
        return {
            name: m.export() for name, m in sorted(self._metrics.items())
        }

    def export_typed(self) -> list:
        """``[(name, kind, exported_value)]`` sorted by name, with the
        metric SET read in one pass under the registry lock — the scrape
        surface (obs/exporter.py) renders from this so a concurrently
        registering run can never hand it a half-seen dict (each value
        read stays individually consistent via the counters' own
        locks)."""
        with self._lock:
            items = sorted(self._metrics.items())
        kinds = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}
        return [(n, kinds[type(m)], m.export()) for n, m in items]

    def reset(self) -> None:
        """Zero every metric (tests; the names stay registered)."""
        for m in self._metrics.values():
            m.reset()


#: process-default registry (the sweep caches and trainers report here)
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# first-occurrence stderr warnings: telemetry failures and degradations must
# be LOUD once, not silent (the r5 audit found a blanket except swallowing
# them) and not a line per round either

_warned: set = set()


def warn_once(key: str, message: str) -> bool:
    """Print ``message`` to stderr the FIRST time ``key`` is seen in this
    process; later calls are no-ops. Returns whether it printed. Callers
    pair this with a counter so the repeat count stays observable
    (e.g. ``telemetry.emit_errors``) while stderr stays readable."""
    if key in _warned:
        return False
    _warned.add(key)
    import sys

    print(message, file=sys.stderr)
    return True


def reset_warnings() -> None:
    """Forget which one-time warnings fired (tests)."""
    _warned.clear()
