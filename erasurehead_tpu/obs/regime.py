"""Online arrival-regime estimation: what kind of stragglers are these?

Every adaptive organ in the system keys on the arrival regime — the
bandit's priors (adapt/), the what-if surfaces (whatif/), and ROADMAP
item 4's SLO autoscaler is explicitly blocked on "a live arrival-regime
estimate from obs/ telemetry". This module is that estimate: a
bounded-memory online estimator over the -1-sentinel-masked arrival
stream that answers, at any round,

  - **rate**: the rolling exponential rate 1/mean (arrivals per
    simulated second) over the last ``window_rounds`` rounds;
  - **kind**: light vs heavy tail, by a rolling Hill index over the top
    order statistics of the window — exponential-like streams estimate
    well above :attr:`heavy_tail_below`, Pareto-like streams converge to
    their true tail index below it;
  - **shifted**: change-point detection — the short-window mean jumping
    past ``shift_factor`` in either direction against the
    regime-so-far baseline (the same jump rule the adapt controller's
    private detector used, now policy-independent and shared).

Masking discipline: arrivals are masked exactly like
events.arrival_summary — the -1 never-arrived sentinel and non-finite
entries never enter any statistic. Feed the estimator RAW schedule rows
(adapt/driver.py's shift-detection lesson: collected-masked times are
policy-dependent, and a policy-dependent detector reads every arm
switch as a regime change).

The estimator is a passive consumer: it allocates O(window_rounds * W)
floats, runs host-side, and emits a typed ``regime`` event only when a
change-point fires (plus every ``emit_every`` rounds when asked) — the
observation-only contract is untouched.
"""

from __future__ import annotations

import collections
import dataclasses
import math
from typing import Optional

import numpy as np

from erasurehead_tpu.obs import events


@dataclasses.dataclass(frozen=True)
class RegimeEstimate:
    """The queryable answer at one round (immutable snapshot)."""

    round: int  # last round observed
    n: int  # masked arrivals in the rolling window
    mean: Optional[float]  # masked mean arrival (None below min samples)
    rate: Optional[float]  # 1/mean, the rolling exponential rate
    tail_index: Optional[float]  # rolling Hill estimate (None = too few)
    kind: str  # one of events.REGIME_KINDS
    shifted: bool  # change-point fired AT this round
    shift_round: Optional[int]  # most recent change-point round

    def payload(self) -> dict:
        """The ``regime`` event payload (rate 0.0 when unknown: the
        typed field is required, the optional mean carries the None)."""
        out = {
            "round": int(self.round),
            "kind": self.kind,
            "rate": round(self.rate, 6) if self.rate else 0.0,
            "n": int(self.n),
            "shifted": bool(self.shifted),
        }
        if self.mean is not None:
            out["mean"] = round(self.mean, 6)
        if self.tail_index is not None:
            out["tail_index"] = round(self.tail_index, 4)
        if self.shift_round is not None:
            out["shift_round"] = int(self.shift_round)
        return out


def hill_index(samples, top_frac: float = 0.1) -> Optional[float]:
    """Rolling Hill tail-index estimate over the top order statistics.

    alpha_hat = k / sum(log(x_(i) / x_(k+1))) over the k largest
    samples; small alpha = heavy (Pareto-like) tail, exponential streams
    drift well above 2 at this top fraction. None when the window is too
    small (< 4 positive samples above the threshold) to say anything.
    """
    x = np.asarray(samples, dtype=np.float64)
    x = x[np.isfinite(x) & (x > 0.0)]
    if x.size < 5:
        return None
    x = np.sort(x)[::-1]
    k = max(3, int(top_frac * x.size))
    if k + 1 > x.size:
        k = x.size - 1
    threshold = x[k]
    if threshold <= 0.0:
        return None
    logs = np.log(x[:k] / threshold)
    s = float(logs.sum())
    if s <= 0.0:
        return None
    return k / s


class ArrivalRegimeEstimator:
    """Bounded-memory online estimator over masked arrival rounds.

    Feed it per-round raw arrival rows via :meth:`update` (or whole
    chunks via :meth:`update_rounds`); query :meth:`estimate` anytime;
    :meth:`poll_shift` returns True exactly once per detected
    change-point (the adapt controller's flagged shift source).
    """

    def __init__(
        self,
        *,
        window_rounds: int = 32,
        detect_rounds: int = 4,
        min_samples: int = 8,
        shift_factor: float = 2.5,
        heavy_tail_below: float = 2.0,
        top_frac: float = 0.1,
        emit_every: int = 0,
        run_id: Optional[str] = None,
    ):
        if window_rounds < 1 or detect_rounds < 1:
            raise ValueError(
                f"window_rounds/detect_rounds must be >= 1, got "
                f"{window_rounds}/{detect_rounds}"
            )
        if shift_factor <= 1.0:
            raise ValueError(
                f"shift_factor must be > 1, got {shift_factor}"
            )
        self.window_rounds = int(window_rounds)
        self.detect_rounds = int(detect_rounds)
        self.min_samples = int(min_samples)
        self.shift_factor = float(shift_factor)
        self.heavy_tail_below = float(heavy_tail_below)
        self.top_frac = float(top_frac)
        self.emit_every = int(emit_every)
        self.run_id = run_id
        # rolling window of masked per-round sample arrays (rate + tail)
        self._window: collections.deque = collections.deque(
            maxlen=self.window_rounds
        )
        # change-point state: short recent window vs regime-so-far
        # baseline; rounds evicted from the short deque accumulate into
        # the baseline until a shift adopts the new level
        self._short: collections.deque = collections.deque()
        self._base_sum = 0.0
        self._base_n = 0
        self._round = -1
        self._shift_round: Optional[int] = None
        self._pending_shift = False

    # ---- feeding ---------------------------------------------------------

    def update(self, round: int, worker_times_row) -> RegimeEstimate:
        """Observe one round's raw arrival row ([W]; -1 sentinel and
        non-finite entries masked). Returns the post-update estimate."""
        row = np.asarray(worker_times_row, dtype=np.float64).ravel()
        row = row[np.isfinite(row) & (row >= 0.0)]
        self._round = int(round)
        self._window.append(row)
        shifted = self._observe_changepoint(row)
        est = self._estimate(shifted)
        if shifted:
            self._shift_round = self._round
            self._pending_shift = True
            est = self._estimate(shifted)  # shift_round now set
            events.emit("regime", **self._event_fields(est))
        elif self.emit_every > 0 and self._round % self.emit_every == 0:
            events.emit("regime", **self._event_fields(est))
        return est

    def update_rounds(self, start_round: int, rows) -> RegimeEstimate:
        """Observe a [n, W] chunk of raw rounds (adapt/driver chunks)."""
        rows = np.asarray(rows, dtype=np.float64)
        if rows.ndim == 1:
            rows = rows[None, :]
        est = self.estimate()
        for i in range(rows.shape[0]):
            est = self.update(start_round + i, rows[i])
        return est

    # ---- change-point ----------------------------------------------------

    def _observe_changepoint(self, row: np.ndarray) -> bool:
        self._short.append((float(row.sum()), int(row.size)))
        while len(self._short) > self.detect_rounds:
            s, n = self._short.popleft()
            self._base_sum += s
            self._base_n += n
        short_sum = sum(s for s, _ in self._short)
        short_n = sum(n for _, n in self._short)
        if (
            len(self._short) < self.detect_rounds
            or short_n < 1
            or self._base_n < self.min_samples
        ):
            return False
        short_mean = short_sum / short_n
        base_mean = self._base_sum / self._base_n
        lo, hi = sorted((max(short_mean, 1e-12), max(base_mean, 1e-12)))
        if hi / lo < self.shift_factor:
            return False
        # adopt the new level: the short window becomes the baseline of
        # the new regime, so one shift fires once, not every round after
        self._base_sum = short_sum
        self._base_n = short_n
        self._short.clear()
        return True

    def poll_shift(self) -> bool:
        """True exactly once per detected change-point since the last
        poll (the adapt controller's shift_source="regime" signal)."""
        fired = self._pending_shift
        self._pending_shift = False
        return fired

    # ---- querying --------------------------------------------------------

    def estimate(self) -> RegimeEstimate:
        return self._estimate(False)

    def _estimate(self, shifted: bool) -> RegimeEstimate:
        samples = (
            np.concatenate(list(self._window))
            if self._window
            else np.empty(0)
        )
        n = int(samples.size)
        if n < self.min_samples:
            return RegimeEstimate(
                round=self._round, n=n, mean=None, rate=None,
                tail_index=None, kind="unknown", shifted=shifted,
                shift_round=self._shift_round,
            )
        mean = float(samples.mean())
        rate = 1.0 / mean if mean > 0 else math.inf
        tail = hill_index(samples, self.top_frac)
        kind = (
            "heavytail"
            if tail is not None and tail <= self.heavy_tail_below
            else "exp"
        )
        return RegimeEstimate(
            round=self._round, n=n, mean=mean,
            rate=rate if math.isfinite(rate) else None,
            tail_index=tail, kind=kind, shifted=shifted,
            shift_round=self._shift_round,
        )

    def _event_fields(self, est: RegimeEstimate) -> dict:
        fields = est.payload()
        if self.run_id is not None:
            fields["run_id"] = self.run_id
        return fields
