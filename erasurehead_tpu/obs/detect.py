"""Recompile detector: catch sweeps that silently stop sharing executables.

The sweep engine's whole value is that the Nth run of a lowering signature
skips trace+compile (train/cache.py). The failure mode is quiet: a config
knob, mesh assignment, or resolved-lowering default drifts between "the
same" runs, every run recompiles, and nothing says why — a 7-scheme
compare degrades from 1 compile to 7 with identical-looking output.

This module watches executable-cache *misses*. The trainer reports each
compile as a LABELED signature (field name -> value, the same content as
the cache key); when a miss lands in a signature family that was already
compiled in-process, :func:`observe` returns the most similar prior
signature's diff — the names of the key fields that differed — and the
trainer emits a ``warning`` event naming them. Expected-to-vary fields
(chunk length under checkpointing) are excluded so legitimate chunk
compiles don't cry wolf; an empty diff means the identical signature
recompiled (cache disabled or evicted).
"""

from __future__ import annotations

from collections import deque
from typing import Optional

#: signature fields expected to differ between compiles of one logical run
#: (checkpointing compiles one executable per distinct chunk length)
EXPECTED_VARYING = frozenset({"chunk_rounds"})

#: prior signatures kept per family — sweeps cycle over a handful
_MAX_SEEN = 64

_seen: dict = {}  # family (fields["kind"]) -> deque[dict]


def reset() -> None:
    _seen.clear()


def _truncate(v, width: int = 120) -> str:
    s = repr(v)
    return s if len(s) <= width else s[: width - 3] + "..."


def observe(fields: dict) -> Optional[dict]:
    """Record one executable-cache miss; return diff info when this family
    (``fields['kind']``) was already compiled in-process.

    Returns None for the family's first compile, or for misses that differ
    from every prior signature only in :data:`EXPECTED_VARYING` fields.
    Otherwise ``{"changed": [...], "detail": {name: "old -> new"},
    "n_prior": int}`` against the closest prior signature (fewest differing
    fields) — "changed" empty means an exact signature recompiled.
    """
    family = fields.get("kind", "?")
    prior = _seen.setdefault(family, deque(maxlen=_MAX_SEEN))
    best = None
    best_changed = None
    for p in prior:
        keys = set(p) | set(fields)
        changed = sorted(
            k for k in keys if p.get(k) != fields.get(k)
        )
        if best_changed is None or len(changed) < len(best_changed):
            best, best_changed = p, changed
    prior.append(dict(fields))
    if best is None:
        return None
    essential = [k for k in best_changed if k not in EXPECTED_VARYING]
    if best_changed and not essential:
        return None  # only expected-to-vary fields differed
    return {
        "changed": essential,
        "detail": {
            k: f"{_truncate(best.get(k))} -> {_truncate(fields.get(k))}"
            for k in essential
        },
        "n_prior": len(prior) - 1,
    }


def observe_and_warn(fields: dict, run_id: Optional[str] = None) -> None:
    """The trainer-side hook: observe a miss and emit a ``warning`` event
    into the current capture when it looks like an unintended recompile."""
    diff = observe(fields)
    if diff is None:
        return
    from erasurehead_tpu.obs import events

    if diff["changed"]:
        msg = (
            f"executable recompiled: {len(diff['changed'])} signature "
            f"field(s) differ from a prior in-process compile: "
            f"{', '.join(diff['changed'])}"
        )
    else:
        msg = (
            "executable recompiled with an identical signature "
            "(sweep cache disabled or entry evicted)"
        )
    events.emit(
        "warning",
        kind="recompile",
        message=msg,
        run_id=run_id,
        changed=diff["changed"],
        detail=diff["detail"],
    )
