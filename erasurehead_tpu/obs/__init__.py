"""Run-telemetry subsystem: structured event log, metrics, decode error.

The paper's entire claim is a measurement (wall-clock vs. convergence under
straggler delays), so observability is a first-class subsystem, not an
afterthought:

  - :mod:`erasurehead_tpu.obs.events` — structured JSONL event log: typed
    ``run_start`` / ``compile`` / ``data_upload`` / ``rounds`` / ``decode``
    / ``run_end`` records per training run, emitted strictly host-side and
    outside jit (telemetry is observation-only: trajectories are bitwise
    identical with it on or off, pinned in tests/test_telemetry.py);
  - :mod:`erasurehead_tpu.obs.metrics` — labeled counters/gauges/histograms
    with snapshot export (the sweep caches in train/cache.py report
    through it);
  - :mod:`erasurehead_tpu.obs.decode` — the per-round AGC decode-error norm
    (ErasureHead arXiv:1901.09671 / arXiv:2006.09638's central quantity),
    computed host-side from the collection weights the run already built;
  - :mod:`erasurehead_tpu.obs.detect` — recompile detector: warns when an
    executable-cache miss lands on a signature family already compiled
    in-process, naming the key fields that differed;
  - :mod:`erasurehead_tpu.obs.report` — renders an events.jsonl into the
    human summary table behind ``erasurehead-tpu report``;
  - :mod:`erasurehead_tpu.obs.timeseries` — bounded-memory streaming
    reducer over the live event stream (in-process observer attach or
    events.jsonl tail) producing windowed series: rounds/sec, arrival
    quantiles, decode-error split, prefetch throughput, cache hit
    rates, per-tenant serve goodput;
  - :mod:`erasurehead_tpu.obs.critical_path` — per-run wall-clock
    attribution (straggler-wait vs compute vs dispatch-gap on the
    simulated clock; decode+update vs prefetch-stall on the host wall),
    emitted as the typed ``critical_path`` event;
  - :mod:`erasurehead_tpu.obs.regime` — online arrival-regime estimator
    (rolling rate + Hill tail index + change-point detection) consumed
    by the adaptive controller's ``shift_source="regime"`` path;
  - :mod:`erasurehead_tpu.obs.exporter` — Prometheus text exposition of
    the registry + reducer gauges (the serve front's ``GET /metrics``),
    the per-tenant SLO burn-rate tracker, and the ``erasurehead-tpu
    top`` live terminal renderer.
"""

from erasurehead_tpu.obs import events, metrics  # noqa: F401
from erasurehead_tpu.obs.events import capture, current, emit  # noqa: F401
from erasurehead_tpu.obs.metrics import REGISTRY  # noqa: F401
