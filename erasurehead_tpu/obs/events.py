"""Structured JSONL event log: the machine-readable record of a run.

One line per event, every line a JSON object with three envelope fields —
``type`` (one of :data:`SCHEMA`), ``seq`` (monotonic per logger), ``t``
(unix seconds) — plus the type's payload. The reference's observability was
two hand-rolled artifacts (``timeset`` / ``worker_timeset``, SURVEY.md
§5.1); the event log supersedes them as the analysis substrate while the
.dat artifacts stay for reference-script parity (see MIGRATION.md §4).

Contract (pinned in tests/test_telemetry.py): emission is strictly
host-side and outside jit. Telemetry is observation-only — with the log on
or off, ``params_history`` is bitwise identical and the executable cache
records zero extra compiles. The trainers emit into whatever logger
:func:`capture` has installed; with none installed every ``emit`` is a
no-op, so library callers pay nothing.

Validation logic lives here (:func:`validate_lines`) so the CLI wrapper
(tools/validate_events.py), the smoke target (``make telemetry-smoke``)
and the tests all check the same schema.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import itertools
import json
import os
import threading
import time
from typing import IO, Iterable, Optional

import numpy as np

#: record type -> required payload keys (the envelope ``type``/``seq``/``t``
#: is always present). Optional fields may ride along; unknown TYPES are a
#: validation error — add new types here first.
SCHEMA: dict[str, tuple] = {
    # one per run: identity of what was trained and how it lowered
    "run_start": ("run_id", "scheme", "platform", "config_hash", "mesh"),
    # one per AOT chunk compile (hit or miss) of the training executable
    "compile": ("run_id", "seconds", "cache_hit"),
    # one per device-data stacking/upload (hit = stacks reused)
    "data_upload": ("run_id", "bytes", "cache_hit"),
    # chunked per-round telemetry: simulated clock + masked arrival stats
    "rounds": ("run_id", "first_round", "n_rounds", "sim_time_s"),
    # chunked per-round AGC decode-error norms (obs/decode.py). An
    # optional ``layer`` field (non-negative int) tags a per-layer
    # gradient-space series under blockwise coding (obs/decode.
    # block_decode_error): each (run_id, trajectory, layer) triple is its
    # own monotone round stream — the decode-error-vs-depth record
    "decode": ("run_id", "first_round", "n_rounds", "error_mean",
               "error_max", "exact"),
    # eval replay summary (emitted by callers that run the eval, e.g. cli)
    "eval": ("run_id", "final_train_loss", "final_test_loss"),
    # anomaly channel (recompile detector, obs/detect.py)
    "warning": ("kind", "message"),
    # one per trajectory-batched cohort dispatch (trainer.train_cohort):
    # composition (schemes/seeds) and how many compiled dispatches the
    # cohort cost — the record behind report's "7 schemes x 4 seeds = N
    # dispatches" line
    "cohort": ("run_id", "n_trajectories", "schemes", "seeds",
               "dispatches"),
    # one per run: the wall-clock / cache / arrival / decode summary the
    # report command renders (obs/report.py)
    "run_end": ("run_id", "wall_time_s", "steps_per_sec"),
    # registry snapshot written once when a capture closes (obs/metrics.py)
    "metrics": ("snapshot",),
    # sweep-journal record (train/journal.py): one per finished sweep
    # trajectory — its identity key (config signature + data/arrival
    # digest), completion status ("ok" | "diverged"), and the full
    # RunSummary rehydration payload that lets --resume-sweep reproduce the
    # row without re-training. The journal file is an events.jsonl like any
    # other (same envelope, same validator).
    "sweep_trajectory": ("key", "label", "status", "row"),
    # serve daemon (erasurehead_tpu/serve/): one per accepted client
    # request — which tenant asked for which trajectory
    "request": ("tenant", "request_id", "label"),
    # one per packed cohort the packer hands to the dispatch engine:
    # how many pending trajectories (across how many tenants) share this
    # dispatch — the record behind report's packed-dispatch ratio
    "pack": ("n_trajectories", "labels", "tenants"),
    # one per admission decision: the cohort's estimated device footprint
    # against the serve budget ("admitted" rides along as an optional
    # field; admitted=false = the request QUEUES instead of joining)
    "admit": ("est_bytes", "budget_bytes"),
    # one per admission-pressure eviction: the controller dropped the
    # sweep data cache's HBM pins (or timed a request out of the packing
    # window) to make room — "reason" says which
    "evict": ("reason",),
    # one per backpressure rejection (HTTP 429 / socket "rejected" /
    # in-process ServeOverloadedError): which tenant was pushed back and
    # why ("overloaded" when the intake queue crossed its high-water
    # mark, "unauthorized" when an HTTP bearer token failed). The
    # optional ``retry_after_s`` is the deferral-derived schedule quote
    # the client's capped-exponential backoff honors.
    "reject": ("tenant", "reason"),
    # one per result-streaming lifecycle transition on a network front
    # connection: "event" says which ("open" when a reader attaches,
    # "overflow" when a slow reader's bounded outbox dropped journaled
    # rows — the client re-fetches by resubmitting, "close" when the
    # reader detaches). Optional ``dropped`` counts rows shed so far.
    "stream": ("tenant", "event"),
    # one per daemon warm restart (serve/wal.py replay): how many intake
    # WAL records were read, how many re-dispatched because their rows
    # were not yet journaled, and how many rehydrated straight from the
    # per-tenant journals without a dispatch
    "restart": ("wal_records", "resubmitted", "rehydrated"),
    # one per adaptive-controller decision (adapt/driver.py): which
    # (scheme, collect, deadline) arm ran the chunk starting at "round",
    # and why (warmup / exploit / explore / regime_shift). Seeded and
    # telemetry-driven, so a resumed run replays the identical sequence —
    # the event log is the decision journal.
    "adapt": ("round", "arm", "reason"),
    # one per elastic-membership decision or completed chunk
    # (elastic/driver.py): "action" says what happened at chunk-boundary
    # "round" — a worker declared dead from its own telemetry (the -1
    # sentinel persisting / detect_dead tripping), a join accepted, a
    # re-layout onto n_workers workers, a collapsed-arrival probe, or a
    # finished chunk's science row ("chunk" records carry the sim clock,
    # decode-error mean and params digest that make a killed->resumed run
    # rehydrate its rows bitwise from this journal). Deterministic given
    # (config, world, chaos env), so the event log doubles as the
    # membership decision journal.
    "membership": ("round", "action", "n_workers"),
    # one per what-if engine phase (erasurehead_tpu/whatif/): "kind" says
    # which — "grid" after feasibility enumeration (point counts ride
    # along), "point" per reduced surface row (label + feasibility +
    # expected time-to-target), "surface" when the artifact saves,
    # "rehydrate" when an identical spec loads the saved surface instead
    # of re-simulating. Every record carries the grid's spec_hash, so a
    # surface artifact is attributable to its event stream and a
    # rehydrated run is distinguishable from a simulated one.
    "whatif": ("spec_hash", "kind"),
    # one per staged partition window of a streamed run
    # (data/prefetch.Prefetcher): which window index moved how many
    # host→device bytes over which partition ranges. ``ranges`` is the
    # staged span in consume order — a list of [lo, hi) pairs, one when
    # the window is a plain contiguous slice, two when an
    # assignment-aware plan's halo wraps past the partition count
    # (data/sharding.StreamWindowPlan). The optional window-plan fields
    # ``plan_mode`` (:data:`STREAM_PLAN_MODES`), ``halo`` and
    # ``group_workers`` say which body the window serves; ``fetch_s`` /
    # ``partitions`` carry the stage's disk+PCIe seconds and its first
    # range — the per-window record behind the report's prefetch
    # section and the bench extra's overlap-efficiency figure
    "prefetch": ("run_id", "window", "bytes", "ranges"),
    # one per shard-store disk transaction (data/store.py): "kind" says
    # which (:data:`IO_KINDS` — a window read off the mmapped shards, or
    # a store write by data/prepare.py) and ``bytes`` how much moved
    "io": ("kind", "bytes"),
    # one per pipelined run (cfg.pipeline_depth > 0; parallel/pipeline.py):
    # how far ahead of the synchronous round barrier the dispatches ran —
    # mean/max per-round dispatch-ahead seconds and the total overlap the
    # pipeline bought (the simulated-clock win's direct record, emitted
    # host-side from the precomputed schedule: zero compiles)
    "dispatch_ahead": ("run_id", "first_round", "n_rounds",
                      "pipeline_depth", "ahead_mean_s", "ahead_max_s",
                      "overlap_total_s"),
    # one per pipelined run's post-hoc error decomposition (obs/decode.
    # emit_staleness_split, invoked by tools — needs an eval replay, so
    # never emitted from inside train()): mean gradient-space staleness
    # error ||g_stale - g_fresh|| vs coding error ||g_hat - g_full||, and
    # staleness's share of the combined error — the record that says
    # whether tau=1 noise or erasure-coding noise dominates the regime
    "stale_decode": ("run_id", "first_round", "n_rounds",
                     "staleness_error_mean", "coding_error_mean",
                     "staleness_share"),
    # one per run: the wall-clock attribution ledger (obs/critical_path.py)
    # — where the run's measured host wall and simulated master clock
    # actually went. ``components`` attributes the HOST wall (decode+update
    # execution vs prefetch-stall vs compile, real seconds of the timed
    # region); ``sim_components`` attributes the SIMULATED clock
    # (fastest-arrival compute floor vs straggler-wait vs pipelined
    # dispatch-gap). Each ledger's values must sum to its measured total
    # within 5% — the validator enforces the reconciliation, so a ledger
    # that silently drops a bucket is a schema error, not a report footnote
    "critical_path": ("run_id", "wall_s", "sim_total_s", "components",
                      "sim_components", "fractions"),
    # arrival-regime estimator output (obs/regime.py): the rolling
    # exp-rate + heavy-tail classification of the masked arrival stream
    # at round ``round``, and whether a change-point fired there.
    # ``rate`` is 1/mean of the rolling window (arrivals/sim-second);
    # optional ``tail_index`` carries the Hill estimate behind the kind
    "regime": ("round", "kind", "rate", "n", "shifted"),
    # one per SLO tracker evaluation window (obs/exporter.SloTracker):
    # the tenant's time-to-last-row SLO, how many requests the window
    # scored, how many breached, and the burn rate (breach fraction /
    # error budget — > 1 means the budget is burning faster than allowed)
    "slo": ("tenant", "slo_s", "window_requests", "breaches",
            "burn_rate"),
    # one per serve-fleet membership/deploy action (serve/fleet.py,
    # serve/router.py, server.adopt_wal): "action" says what happened to
    # "replica" (:data:`FLEET_ACTIONS`) — a completed health probe, a
    # replica whose evidential miss streak is growing ("suspect" carries
    # ``streak``/``k``), a death declared after K consecutive evidential
    # misses, a peer adopting a dead replica's intake WAL ("adopt"
    # carries ``records``), a rolling-deploy phase transition
    # ("deploy_phase" carries ``phase``), a replica joining the ring, or
    # a router failover redirect ("route" carries ``endpoint``). The
    # fleet's decision journal: zero-downtime drills are attributable
    # record by record.
    "fleet": ("action", "replica"),
    # one per autotune-decision resolution (erasurehead_tpu/tune/):
    # which race's verdict resolved an auto knob, at which shape
    # signature on which device kind, and where the choice came from
    # ("race" = a racer run just measured it, "cache" = the persisted
    # decision cache, "default" = no cached decision — the hardcoded
    # fallback stood). Observation-only and process-deduped: resolution
    # reads the cache, never the event stream, so telemetry on/off
    # cannot change a single lowering choice
    "tune": ("race", "device_kind", "shape", "choice", "source"),
}

#: adapt decision reasons (adapt/controller.AdaptiveController.choose)
ADAPT_REASONS = ("warmup", "exploit", "explore", "regime_shift")

#: arrival-regime classifications (obs/regime.ArrivalRegimeEstimator):
#: "exp" = light (exponential-like) tail, "heavytail" = Pareto-like tail
#: by the rolling Hill index, "unknown" = not enough masked arrivals yet
REGIME_KINDS = ("exp", "heavytail", "unknown")

#: critical-path reconciliation tolerance: each attribution ledger's
#: component sum must land within this fraction of its measured total
#: (the acceptance bar the validator enforces on every critical_path line)
CRITICAL_PATH_TOL = 0.05

#: membership actions (elastic/controller.py): deaths/joins are detector
#: decisions, "relayout" commits them into a fresh W'-worker layout,
#: "probe" marks a collapsed-arrival re-evaluation, "chunk" is a finished
#: chunk's journal row
MEMBERSHIP_ACTIONS = ("death", "join", "relayout", "probe", "chunk")

#: result-stream lifecycle events (serve network fronts): a reader
#: attached, a slow reader's bounded outbox shed journaled rows, a
#: reader detached
STREAM_EVENTS = ("open", "overflow", "close")

#: streamed window-plan modes (data/sharding.plan_stream_windows): the
#: body the staged window serves — partition-major deduped, worker-major
#: materialized faithful, or the ring-transport faithful body
STREAM_PLAN_MODES = ("deduped", "materialized", "ring")

#: backpressure rejection reasons (serve/server.py + serve/http_front.py)
REJECT_REASONS = ("overloaded", "unauthorized")

#: what-if engine phases (whatif/engine.py): "grid" = enumeration +
#: feasibility filter, "point" = one reduced surface row, "surface" =
#: artifact saved, "rehydrate" = identical spec served from its artifact
WHATIF_KINDS = ("grid", "point", "surface", "rehydrate")

#: shard-store io transaction kinds (data/store.py): a windowed read off
#: the mmapped shards, or a store write (data/prepare.py ``--store``)
IO_KINDS = ("shard_read", "store_write")

#: serve-fleet actions (serve/fleet.py + serve/router.py): "probe" = a
#: completed health probe (ok or evidential miss), "suspect" = a growing
#: consecutive-miss streak short of K, "declare_dead" = the K-streak rule
#: fired (never a single timeout), "adopt" = a peer adopted the dead
#: replica's intake WAL, "deploy_phase" = a rolling-deploy transition,
#: "join" = a replica (re)entered the ring, "route" = a router failover
#: redirect away from an unreachable primary
FLEET_ACTIONS = (
    "probe", "suspect", "declare_dead", "adopt", "deploy_phase",
    "join", "route",
)

#: sweep_trajectory completion statuses (train/journal.py); "diverged"
#: rows are quarantined, not retried — divergence is deterministic under
#: the journaled (config, data, arrivals) key
TRAJECTORY_STATUSES = ("ok", "diverged")

#: autotune races (erasurehead_tpu/tune/__init__.TUNE_CHOICES keys):
#: every "tune" event's ``race`` field must name one of these knob pairs
TUNE_RACES = (
    "block_decode", "glm_fused", "layer_coding", "ring_pipeline",
    "stack_mode",
)

#: where a tune decision came from: a just-run race, the persisted
#: decision cache, or the hardcoded fallback (no cached verdict)
TUNE_SOURCES = ("race", "cache", "default")

#: rounds-style chunk size: small runs get one chunk, long runs stay O(R/100)
ROUND_CHUNK = 100


def _jsonable(v):
    """Best-effort JSON coercion for event payload values."""
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if hasattr(v, "value") and not isinstance(v, (int, float, str, bool)):
        return v.value  # enums
    return v


def _checked_payload(type: str, fields: dict) -> dict:
    """Validate ``fields`` against :data:`SCHEMA` and JSON-coerce them —
    the shared gate for file emission and in-process observers."""
    required = SCHEMA.get(type)
    if required is None:
        raise ValueError(
            f"unknown event type {type!r}; known: {sorted(SCHEMA)}"
        )
    missing = [k for k in required if k not in fields]
    if missing:
        raise ValueError(f"event {type!r} missing required {missing}")
    return {k: _jsonable(v) for k, v in fields.items()}


class EventLogger:
    """Append-only JSONL writer with per-line flush (a crashed run keeps
    every event emitted before the crash).

    Concurrency contract (the serve daemon and the sweep journal depend on
    it): ``emit`` is safe under concurrent WRITERS.

      - threads sharing one logger: a lock makes the seq draw + write one
        atomic step, so ``seq`` stays strictly monotonic per logger;
      - processes appending to one FILE (``mode="a"``): the file is opened
        with O_APPEND and every record is ONE unbuffered ``write()`` of a
        complete line, so concurrent appenders' lines land whole — never
        interleaved mid-line (each writer restarts seq at 0, which the
        validator accepts as a new logger run).

    ``mode="w"`` (single-writer run logs) keeps buffered text io with a
    per-line flush.
    """

    def __init__(self, path: str, mode: str = "w"):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._append = "a" in mode
        # append mode: unbuffered binary fd with O_APPEND semantics — one
        # os-level write per record is what makes multi-process journal
        # appends corruption-free (train/journal.py)
        self._f: Optional[IO] = open(
            path, mode + "b", buffering=0
        ) if self._append else open(path, mode)
        self._seq = itertools.count()
        self._lock = threading.Lock()

    def emit(self, type: str, **fields) -> dict:
        payload = _checked_payload(type, fields)
        with self._lock:
            if self._f is None:
                raise ValueError(f"event logger {self.path!r} is closed")
            rec = {
                "type": type, "seq": next(self._seq),
                "t": round(time.time(), 3),
            }
            rec.update(payload)
            line = json.dumps(rec) + "\n"
            if self._append:
                self._f.write(line.encode())  # one write(2); O_APPEND
            else:
                self._f.write(line)
                self._f.flush()
        return rec

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# --------------------------------------------------------------------------
# current-logger plumbing: the trainers emit into whatever capture() set,
# so no training entry point grows a logger parameter

_current: Optional[EventLogger] = None
_run_counter = itertools.count(1)

#: in-process event observers (obs/timeseries.py live attach): callables
#: invoked host-side with each emitted record dict. Observers see the
#: same typed stream a capture writes — with no capture installed they
#: still receive records (the serve daemon's live /metrics loop), stamped
#: with a process-local seq
_observers: list = []
_observer_seq = itertools.count()


def current() -> Optional[EventLogger]:
    return _current


def add_observer(fn) -> None:
    """Attach an in-process event observer. ``fn(record)`` is called
    host-side, synchronously, for every :func:`emit` — the live-telemetry
    attachment point (obs/timeseries.TimeseriesReducer.attach). Observer
    exceptions are swallowed with a warn_once: telemetry consumers must
    never break the producer."""
    _observers.append(fn)


def remove_observer(fn) -> None:
    """Detach a previously added observer (no-op if absent)."""
    try:
        _observers.remove(fn)
    except ValueError:
        pass


def _notify_observers(rec: dict) -> None:
    for fn in list(_observers):
        try:
            fn(rec)
        except Exception as e:  # noqa: BLE001 — observers are passive
            from erasurehead_tpu.obs.metrics import warn_once

            warn_once(
                f"event-observer-{type(e).__name__}",
                f"event observer {fn!r} raised {e!r}; record dropped "
                f"from the live stream (the event log is unaffected)",
            )


def emit(type: str, **fields) -> bool:
    """Emit into the current capture; no-op (False) when none installed.

    In-process observers (:func:`add_observer`) always see the record,
    capture or not — the file is the durable log, observers are the live
    plane."""
    if _current is not None:
        rec = _current.emit(type, **fields)
        _notify_observers(rec)
        return True
    if _observers:
        rec = {
            "type": type, "seq": next(_observer_seq),
            "t": round(time.time(), 3),
        }
        rec.update(_checked_payload(type, fields))
        _notify_observers(rec)
    return False


@contextlib.contextmanager
def capture(path: str, mode: str = "w"):
    """Install an :class:`EventLogger` at ``path`` as the process-current
    event sink for the duration of the block. On exit, a final ``metrics``
    record snapshots the registry (obs/metrics.py) and the file is closed.
    Nested captures stack (inner wins, outer restored)."""
    global _current
    logger = EventLogger(path, mode=mode)
    prev = _current
    _current = logger
    try:
        yield logger
    finally:
        _current = prev
        try:
            from erasurehead_tpu.obs.metrics import REGISTRY

            logger.emit("metrics", snapshot=REGISTRY.snapshot())
        except ValueError:
            pass  # already closed by the caller
        logger.close()


def new_run_id() -> str:
    """Short process-unique run id; the pid suffix keeps ids distinct when
    several processes append to one file (mode='a')."""
    return f"run-{next(_run_counter):03d}-{os.getpid():x}"


def config_hash(cfg) -> str:
    """Stable short hash of a RunConfig's full field set — the run_start
    identity key (the manifest carries the readable form)."""
    d = {
        k: _jsonable(v) for k, v in sorted(dataclasses.asdict(cfg).items())
    }
    blob = json.dumps(d, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


# --------------------------------------------------------------------------
# arrival statistics: THE masking home for the -1 never-arrived sentinel

def arrival_summary(worker_times) -> dict:
    """Masked latency stats over a [.., W] arrival block.

    ``worker_times`` carries the reference's ``-1`` sentinel for workers
    the master never collected (src/coded.py:171-173, parallel/collect.py
    NEVER); averaging it in would silently *lower* every latency stat, so
    this is the single shared masking point for artifacts
    (train/artifacts.py) and event emission. Quantiles are None when no
    worker arrived at all (e.g. an all-dead deadline round)."""
    wt = np.asarray(worker_times, dtype=np.float64)
    arrived = wt[wt >= 0.0]
    n_never = int(wt.size - arrived.size)
    if arrived.size == 0:
        return {
            "p50": None, "p90": None, "p99": None, "mean": None,
            "n_arrivals": 0, "n_never": n_never,
        }
    q50, q90, q99 = np.quantile(arrived, [0.5, 0.9, 0.99])
    return {
        "p50": round(float(q50), 6),
        "p90": round(float(q90), 6),
        "p99": round(float(q99), 6),
        "mean": round(float(arrived.mean()), 6),
        "n_arrivals": int(arrived.size),
        "n_never": n_never,
    }


def emit_round_chunks(
    run_id: str,
    *,
    start_round: int,
    timeset: np.ndarray,
    worker_times: np.ndarray,
    decode_error: Optional[np.ndarray] = None,
    update_norm: Optional[np.ndarray] = None,
    chunk: int = ROUND_CHUNK,
    trajectory: Optional[str] = None,
) -> None:
    """Emit the per-run ``rounds`` (and ``decode``) chunk records into the
    current capture. All inputs are host numpy the run already produced;
    no-op without a capture. ``update_norm`` is the [R-1] per-round
    optimizer-step norm (the host-visible gradient-magnitude proxy — the
    exact grad norm would need extra device programs, which telemetry must
    never add); its round r entry describes the step INTO round r+1.

    ``trajectory`` tags a cohort member's series (trainer.train_cohort
    emits one chunk stream per trajectory under the cohort's single
    run_id): the per-round monotonicity check then applies per (run_id,
    trajectory) stream. Arrival stats flow through
    :func:`arrival_summary`, so the -1 never-arrived sentinel is masked
    in batched emission exactly as in single-run emission."""
    if _current is None:
        return
    rounds = len(timeset)
    traj = {} if trajectory is None else {"trajectory": trajectory}
    for lo in range(start_round, rounds, chunk):
        hi = min(lo + chunk, rounds)
        fields = dict(
            run_id=run_id,
            first_round=lo,
            n_rounds=hi - lo,
            sim_time_s=round(float(np.sum(timeset[lo:hi])), 6),
            arrival=arrival_summary(worker_times[lo:hi]),
            **traj,
        )
        if update_norm is not None and len(update_norm):
            un = update_norm[max(lo - start_round - 1, 0):hi - start_round - 1]
            if len(un):
                fields["update_norm_mean"] = round(float(np.mean(un)), 8)
        emit("rounds", **fields)
        if decode_error is not None:
            err = np.asarray(decode_error[lo:hi], dtype=np.float64)
            emit(
                "decode",
                run_id=run_id,
                first_round=lo,
                n_rounds=hi - lo,
                error_mean=round(float(err.mean()), 10) if err.size else 0.0,
                error_max=round(float(err.max()), 10) if err.size else 0.0,
                exact=bool((err == 0.0).all()),
                **traj,
            )


def emit_layer_decode_chunks(
    run_id: str,
    layer_errors: np.ndarray,
    *,
    start_round: int = 0,
    chunk: int = ROUND_CHUNK,
    trajectory: Optional[str] = None,
) -> None:
    """Emit per-layer ``decode`` chunk streams for a blockwise-coded run:
    ``layer_errors`` is the [R, L] gradient-space table from
    obs/decode.block_decode_error (per_block or cumulative — the caller
    picks the view), and each layer l becomes its own round-chunked
    stream tagged ``layer=l`` — the decode-error-vs-depth series in the
    events log. No-op without a capture, like all emission."""
    if _current is None:
        return
    err_rl = np.asarray(layer_errors, dtype=np.float64)
    rounds = err_rl.shape[0]
    traj = {} if trajectory is None else {"trajectory": trajectory}
    for layer in range(err_rl.shape[1]):
        series = err_rl[:, layer]
        for lo in range(start_round, rounds, chunk):
            hi = min(lo + chunk, rounds)
            seg = series[lo:hi]
            emit(
                "decode",
                run_id=run_id,
                first_round=lo,
                n_rounds=hi - lo,
                error_mean=round(float(seg.mean()), 10) if seg.size else 0.0,
                error_max=round(float(seg.max()), 10) if seg.size else 0.0,
                exact=bool((seg == 0.0).all()),
                layer=layer,
                **traj,
            )


# --------------------------------------------------------------------------
# validation (shared by tools/validate_events.py, make telemetry-smoke,
# and the tests)

def validate_lines(lines: Iterable[str]) -> list[str]:
    """Schema-check an events.jsonl; returns human-readable error strings
    (empty = valid). Checks: every line parses as a JSON object; record
    types are known; required keys are present; ``seq`` is strictly
    monotonic per emitting logger run; chunked ``rounds``/``decode``
    records have strictly increasing ``first_round`` per (run_id,
    trajectory) stream (cohort dispatches emit one tagged stream per
    trajectory); ``cohort`` records are internally consistent
    (n_trajectories matches the seeds list, dispatches >= 1);
    ``sweep_trajectory`` journal records carry a known status, a non-empty
    key, and an object row; serve records are internally consistent
    (``request`` names tenant/request_id/label, ``pack``'s trajectory
    count matches its label list, ``admit`` carries non-negative byte
    figures, ``evict`` names its reason, ``reject`` carries a tenant and
    a known reason (:data:`REJECT_REASONS`) plus an optional
    non-negative retry-after, ``stream`` carries a tenant and a known
    lifecycle event (:data:`STREAM_EVENTS`), ``restart`` carries
    non-negative WAL-replay counts); ``membership`` records carry a
    non-negative round, a known action (:data:`MEMBERSHIP_ACTIONS`), a
    positive worker count and — when present — a list of non-negative
    worker ids; ``fleet`` records carry a known action
    (:data:`FLEET_ACTIONS`), a non-empty replica name, non-negative
    streak/k/records counts when present, and ``declare_dead`` must
    carry ``streak >= k`` (a death declared on fewer than K consecutive
    evidential misses is a schema error, not a policy choice);
    ``whatif`` records carry a non-empty ``spec_hash`` and a
    known ``kind`` (:data:`WHATIF_KINDS`), point records a non-empty
    label and a bool feasibility verdict, grid records non-negative point
    counts; ``prefetch`` records carry a non-negative window index and
    byte count and a ``ranges`` list of well-formed ``[lo, hi)`` int
    pairs (plus, when present, non-negative ``fetch_s`` seconds, a
    known ``plan_mode`` (:data:`STREAM_PLAN_MODES`) and non-negative
    ``halo`` / ``group_workers`` ints);
    ``io`` records carry a known kind (:data:`IO_KINDS`) and a
    non-negative byte count; ``tune`` records carry a known race
    (:data:`TUNE_RACES`), a known source (:data:`TUNE_SOURCES`) and
    non-empty device_kind/shape/choice strings; ``dispatch_ahead`` records carry a positive
    pipeline depth and non-negative overlap seconds; ``stale_decode``
    records carry non-negative error norms and a staleness share in
    [0, 1]; every ``run_start`` has a matching later ``run_end``."""
    errors: list[str] = []
    # seq checking is MULTI-STREAM: a file may interleave several
    # append-mode loggers (concurrent journal writers, the serve daemon
    # next to a local sweep). Each stream is append-only from 0, so every
    # record's seq must either open a stream (0) or continue one; the
    # multiset maps "next expected seq" -> number of streams expecting it.
    seq_streams: dict = {}
    seen_seq = False
    last_round: dict = {}  # (run_id, type) -> last first_round
    started: set = set()
    ended: set = set()
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"line {i}: not JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"line {i}: not a JSON object")
            continue
        rtype = rec.get("type")
        if rtype not in SCHEMA:
            errors.append(f"line {i}: unknown record type {rtype!r}")
            continue
        missing = [k for k in SCHEMA[rtype] if k not in rec]
        if missing:
            errors.append(f"line {i}: {rtype} missing required {missing}")
        seq = rec.get("seq")
        if not isinstance(seq, int):
            errors.append(f"line {i}: missing/invalid seq")
        else:
            if seq == 0 or not seen_seq:
                # a new logger run (seq restarts at 0); the file's very
                # first record may also be the tail of a rotated stream
                seq_streams[seq + 1] = seq_streams.get(seq + 1, 0) + 1
            elif seq_streams.get(seq):
                seq_streams[seq] -= 1
                if not seq_streams[seq]:
                    del seq_streams[seq]
                seq_streams[seq + 1] = seq_streams.get(seq + 1, 0) + 1
            else:
                errors.append(
                    f"line {i}: non-monotonic seq {seq} (continues no "
                    f"logger stream; expected one of "
                    f"{sorted(seq_streams) or [0]})"
                )
            seen_seq = True
        if rtype in ("rounds", "decode"):
            layer = rec.get("layer")
            if layer is not None and (
                not isinstance(layer, int) or layer < 0
            ):
                errors.append(
                    f"line {i}: {rtype} layer must be a non-negative "
                    f"int, got {layer!r}"
                )
                layer = None
            key = (rec.get("run_id"), rtype, rec.get("trajectory"), layer)
            fr = rec.get("first_round")
            if isinstance(fr, int):
                prev = last_round.get(key)
                if prev is not None and fr <= prev:
                    errors.append(
                        f"line {i}: {rtype} first_round {fr} not after "
                        f"{prev} for run {key[0]!r}"
                        + (
                            f" trajectory {key[2]!r}"
                            if key[2] is not None
                            else ""
                        )
                        + (
                            f" layer {key[3]}"
                            if key[3] is not None
                            else ""
                        )
                    )
                last_round[key] = fr
        if rtype == "cohort":
            n = rec.get("n_trajectories")
            seeds = rec.get("seeds")
            if isinstance(seeds, list) and isinstance(n, int) and len(seeds) != n:
                errors.append(
                    f"line {i}: cohort n_trajectories {n} != "
                    f"{len(seeds)} seeds"
                )
            disp = rec.get("dispatches")
            if isinstance(disp, int) and disp < 1:
                errors.append(
                    f"line {i}: cohort dispatches must be >= 1, got {disp}"
                )
        if rtype == "sweep_trajectory":
            status = rec.get("status")
            if status not in TRAJECTORY_STATUSES:
                errors.append(
                    f"line {i}: sweep_trajectory status must be one of "
                    f"{TRAJECTORY_STATUSES}, got {status!r}"
                )
            if "row" in rec and not isinstance(rec.get("row"), dict):
                errors.append(
                    f"line {i}: sweep_trajectory row must be an object "
                    f"(the RunSummary rehydration payload)"
                )
            key = rec.get("key")
            if not isinstance(key, str) or not key:
                errors.append(
                    f"line {i}: sweep_trajectory key must be a non-empty "
                    f"string"
                )
        if rtype == "request":
            for field in ("tenant", "request_id", "label"):
                v = rec.get(field)
                if not isinstance(v, str) or not v:
                    errors.append(
                        f"line {i}: request {field} must be a non-empty "
                        f"string, got {v!r}"
                    )
        if rtype == "pack":
            n = rec.get("n_trajectories")
            labels = rec.get("labels")
            tenants = rec.get("tenants")
            if not isinstance(labels, list):
                errors.append(f"line {i}: pack labels must be a list")
            elif isinstance(n, int) and len(labels) != n:
                errors.append(
                    f"line {i}: pack n_trajectories {n} != "
                    f"{len(labels)} labels"
                )
            if not isinstance(tenants, list) or not tenants:
                errors.append(
                    f"line {i}: pack tenants must be a non-empty list"
                )
        if rtype == "admit":
            for field in ("est_bytes", "budget_bytes"):
                v = rec.get(field)
                # budget_bytes None = unbounded (no budget configured)
                if v is None and field == "budget_bytes":
                    continue
                if not isinstance(v, (int, float)) or v < 0:
                    errors.append(
                        f"line {i}: admit {field} must be a non-negative "
                        f"number, got {v!r}"
                    )
        if rtype == "evict":
            reason = rec.get("reason")
            if not isinstance(reason, str) or not reason:
                errors.append(
                    f"line {i}: evict reason must be a non-empty string, "
                    f"got {reason!r}"
                )
        if rtype == "reject":
            tenant = rec.get("tenant")
            if not isinstance(tenant, str) or not tenant:
                errors.append(
                    f"line {i}: reject tenant must be a non-empty string, "
                    f"got {tenant!r}"
                )
            reason = rec.get("reason")
            if reason not in REJECT_REASONS:
                errors.append(
                    f"line {i}: reject reason must be one of "
                    f"{REJECT_REASONS}, got {reason!r}"
                )
            ra = rec.get("retry_after_s")
            if ra is not None and (
                not isinstance(ra, (int, float)) or ra < 0
            ):
                errors.append(
                    f"line {i}: reject retry_after_s must be a "
                    f"non-negative number, got {ra!r}"
                )
        if rtype == "stream":
            tenant = rec.get("tenant")
            if not isinstance(tenant, str) or not tenant:
                errors.append(
                    f"line {i}: stream tenant must be a non-empty string, "
                    f"got {tenant!r}"
                )
            ev = rec.get("event")
            if ev not in STREAM_EVENTS:
                errors.append(
                    f"line {i}: stream event must be one of "
                    f"{STREAM_EVENTS}, got {ev!r}"
                )
            dropped = rec.get("dropped")
            if dropped is not None and (
                not isinstance(dropped, int) or dropped < 0
            ):
                errors.append(
                    f"line {i}: stream dropped must be a non-negative "
                    f"int, got {dropped!r}"
                )
        if rtype == "restart":
            for field in ("wal_records", "resubmitted", "rehydrated"):
                v = rec.get(field)
                if not isinstance(v, int) or v < 0:
                    errors.append(
                        f"line {i}: restart {field} must be a "
                        f"non-negative int, got {v!r}"
                    )
        if rtype == "adapt":
            rnd = rec.get("round")
            if not isinstance(rnd, int) or rnd < 0:
                errors.append(
                    f"line {i}: adapt round must be a non-negative int, "
                    f"got {rnd!r}"
                )
            arm = rec.get("arm")
            if not isinstance(arm, str) or not arm:
                errors.append(
                    f"line {i}: adapt arm must be a non-empty string, "
                    f"got {arm!r}"
                )
            reason = rec.get("reason")
            if reason not in ADAPT_REASONS:
                errors.append(
                    f"line {i}: adapt reason must be one of "
                    f"{ADAPT_REASONS}, got {reason!r}"
                )
        if rtype == "membership":
            rnd = rec.get("round")
            if not isinstance(rnd, int) or rnd < 0:
                errors.append(
                    f"line {i}: membership round must be a non-negative "
                    f"int, got {rnd!r}"
                )
            action = rec.get("action")
            if action not in MEMBERSHIP_ACTIONS:
                errors.append(
                    f"line {i}: membership action must be one of "
                    f"{MEMBERSHIP_ACTIONS}, got {action!r}"
                )
            nw = rec.get("n_workers")
            if not isinstance(nw, int) or nw < 1:
                errors.append(
                    f"line {i}: membership n_workers must be a positive "
                    f"int, got {nw!r}"
                )
            workers = rec.get("workers")
            if workers is not None and (
                not isinstance(workers, list)
                or any(
                    not isinstance(w, int) or w < 0 for w in workers
                )
            ):
                errors.append(
                    f"line {i}: membership workers must be a list of "
                    f"non-negative worker ids, got {workers!r}"
                )
        if rtype == "fleet":
            action = rec.get("action")
            if action not in FLEET_ACTIONS:
                errors.append(
                    f"line {i}: fleet action must be one of "
                    f"{FLEET_ACTIONS}, got {action!r}"
                )
            replica = rec.get("replica")
            if not isinstance(replica, str) or not replica:
                errors.append(
                    f"line {i}: fleet replica must be a non-empty "
                    f"string, got {replica!r}"
                )
            for field in ("streak", "k", "records", "replayed"):
                v = rec.get(field)
                if v is not None and (
                    not isinstance(v, int) or v < 0
                ):
                    errors.append(
                        f"line {i}: fleet {field} must be a non-negative "
                        f"int, got {v!r}"
                    )
            if action == "declare_dead":
                streak, k = rec.get("streak"), rec.get("k")
                if (
                    isinstance(streak, int)
                    and isinstance(k, int)
                    and streak < k
                ):
                    errors.append(
                        f"line {i}: fleet declare_dead with streak "
                        f"{streak} < k {k} — death must follow K "
                        "consecutive evidential misses, never fewer"
                    )
        if rtype == "whatif":
            kind = rec.get("kind")
            if kind not in WHATIF_KINDS:
                errors.append(
                    f"line {i}: whatif kind must be one of "
                    f"{WHATIF_KINDS}, got {kind!r}"
                )
            sh = rec.get("spec_hash")
            if not isinstance(sh, str) or not sh:
                errors.append(
                    f"line {i}: whatif spec_hash must be a non-empty "
                    f"string, got {sh!r}"
                )
            if kind == "point":
                if not isinstance(rec.get("label"), str) or not rec.get(
                    "label"
                ):
                    errors.append(
                        f"line {i}: whatif point record must carry a "
                        f"non-empty label, got {rec.get('label')!r}"
                    )
                if not isinstance(rec.get("feasible"), bool):
                    errors.append(
                        f"line {i}: whatif point record must carry a "
                        f"bool feasible, got {rec.get('feasible')!r}"
                    )
            if kind == "grid":
                for field in ("n_points", "n_feasible", "n_infeasible"):
                    v = rec.get(field)
                    if v is not None and (
                        not isinstance(v, int) or v < 0
                    ):
                        errors.append(
                            f"line {i}: whatif grid {field} must be a "
                            f"non-negative int, got {v!r}"
                        )
        if rtype == "prefetch":
            for field in ("window", "bytes"):
                v = rec.get(field)
                if not isinstance(v, int) or v < 0:
                    errors.append(
                        f"line {i}: prefetch {field} must be a "
                        f"non-negative int, got {v!r}"
                    )
            rngs = rec.get("ranges")
            ok_ranges = isinstance(rngs, list) and all(
                isinstance(r, list)
                and len(r) == 2
                and all(isinstance(v, int) and v >= 0 for v in r)
                and r[0] < r[1]
                for r in rngs
            ) and len(rngs) >= 1
            if "ranges" in rec and not ok_ranges:
                errors.append(
                    f"line {i}: prefetch ranges must be a non-empty "
                    f"list of [lo, hi) non-negative int pairs with "
                    f"lo < hi, got {rngs!r}"
                )
            pm = rec.get("plan_mode")
            if pm is not None and pm not in STREAM_PLAN_MODES:
                errors.append(
                    f"line {i}: prefetch plan_mode must be one of "
                    f"{STREAM_PLAN_MODES}, got {pm!r}"
                )
            for field in ("halo", "group_workers"):
                v = rec.get(field)
                if v is not None and (not isinstance(v, int) or v < 0):
                    errors.append(
                        f"line {i}: prefetch {field} must be a "
                        f"non-negative int, got {v!r}"
                    )
            fs = rec.get("fetch_s")
            if fs is not None and (
                not isinstance(fs, (int, float)) or fs < 0
            ):
                errors.append(
                    f"line {i}: prefetch fetch_s must be a non-negative "
                    f"number, got {fs!r}"
                )
        if rtype == "dispatch_ahead":
            pd = rec.get("pipeline_depth")
            if not isinstance(pd, int) or pd < 1:
                errors.append(
                    f"line {i}: dispatch_ahead pipeline_depth must be a "
                    f"positive int (the event only exists for pipelined "
                    f"runs), got {pd!r}"
                )
            for field in ("ahead_mean_s", "ahead_max_s", "overlap_total_s"):
                v = rec.get(field)
                if not isinstance(v, (int, float)) or v < 0:
                    errors.append(
                        f"line {i}: dispatch_ahead {field} must be a "
                        f"non-negative number, got {v!r}"
                    )
        if rtype == "stale_decode":
            for field in ("staleness_error_mean", "coding_error_mean"):
                v = rec.get(field)
                if not isinstance(v, (int, float)) or v < 0:
                    errors.append(
                        f"line {i}: stale_decode {field} must be a "
                        f"non-negative number, got {v!r}"
                    )
            share = rec.get("staleness_share")
            if not isinstance(share, (int, float)) or not 0 <= share <= 1:
                errors.append(
                    f"line {i}: stale_decode staleness_share must be a "
                    f"number in [0, 1], got {share!r}"
                )
        if rtype == "critical_path":
            for total_field, comp_field in (
                ("wall_s", "components"),
                ("sim_total_s", "sim_components"),
            ):
                total = rec.get(total_field)
                comps = rec.get(comp_field)
                if not isinstance(total, (int, float)) or total < 0:
                    errors.append(
                        f"line {i}: critical_path {total_field} must be a "
                        f"non-negative number, got {total!r}"
                    )
                    continue
                if not isinstance(comps, dict) or not all(
                    isinstance(v, (int, float)) and v >= 0
                    for v in comps.values()
                ):
                    errors.append(
                        f"line {i}: critical_path {comp_field} must map "
                        f"bucket names to non-negative seconds, got "
                        f"{comps!r}"
                    )
                    continue
                # the reconciliation contract: the ledger sums to its
                # measured total within CRITICAL_PATH_TOL — an attribution
                # that loses (or invents) wall-clock is a schema error
                s = sum(comps.values())
                if abs(s - total) > CRITICAL_PATH_TOL * total + 1e-9:
                    errors.append(
                        f"line {i}: critical_path {comp_field} sum "
                        f"{s:.6f}s does not reconcile with {total_field} "
                        f"{total:.6f}s within {CRITICAL_PATH_TOL:.0%}"
                    )
            fractions = rec.get("fractions")
            if not isinstance(fractions, dict) or not all(
                isinstance(v, (int, float)) and 0 <= v <= 1
                for v in fractions.values()
            ):
                errors.append(
                    f"line {i}: critical_path fractions must map bucket "
                    f"names to numbers in [0, 1], got {fractions!r}"
                )
        if rtype == "regime":
            kind = rec.get("kind")
            if kind not in REGIME_KINDS:
                errors.append(
                    f"line {i}: regime kind must be one of "
                    f"{REGIME_KINDS}, got {kind!r}"
                )
            rate = rec.get("rate")
            if not isinstance(rate, (int, float)) or rate < 0:
                errors.append(
                    f"line {i}: regime rate must be a non-negative "
                    f"number, got {rate!r}"
                )
            rnd = rec.get("round")
            if not isinstance(rnd, int) or rnd < 0:
                errors.append(
                    f"line {i}: regime round must be a non-negative int, "
                    f"got {rnd!r}"
                )
            n = rec.get("n")
            if not isinstance(n, int) or n < 0:
                errors.append(
                    f"line {i}: regime n must be a non-negative int, "
                    f"got {n!r}"
                )
            if not isinstance(rec.get("shifted"), bool):
                errors.append(
                    f"line {i}: regime shifted must be a bool, got "
                    f"{rec.get('shifted')!r}"
                )
        if rtype == "slo":
            tenant = rec.get("tenant")
            if not isinstance(tenant, str) or not tenant:
                errors.append(
                    f"line {i}: slo tenant must be a non-empty string, "
                    f"got {tenant!r}"
                )
            slo_s = rec.get("slo_s")
            if not isinstance(slo_s, (int, float)) or slo_s <= 0:
                errors.append(
                    f"line {i}: slo slo_s must be a positive number, "
                    f"got {slo_s!r}"
                )
            burn = rec.get("burn_rate")
            if not isinstance(burn, (int, float)) or burn < 0:
                errors.append(
                    f"line {i}: slo burn_rate must be a non-negative "
                    f"number, got {burn!r}"
                )
            reqs = rec.get("window_requests")
            breaches = rec.get("breaches")
            if not isinstance(reqs, int) or reqs < 0:
                errors.append(
                    f"line {i}: slo window_requests must be a "
                    f"non-negative int, got {reqs!r}"
                )
            elif (
                not isinstance(breaches, int)
                or not 0 <= breaches <= reqs
            ):
                errors.append(
                    f"line {i}: slo breaches must be an int in "
                    f"[0, window_requests], got {breaches!r}"
                )
        if rtype == "tune":
            race = rec.get("race")
            if race not in TUNE_RACES:
                errors.append(
                    f"line {i}: tune race must be one of {TUNE_RACES}, "
                    f"got {race!r}"
                )
            source = rec.get("source")
            if source not in TUNE_SOURCES:
                errors.append(
                    f"line {i}: tune source must be one of "
                    f"{TUNE_SOURCES}, got {source!r}"
                )
            for field in ("device_kind", "shape", "choice"):
                v = rec.get(field)
                if not isinstance(v, str) or not v:
                    errors.append(
                        f"line {i}: tune {field} must be a non-empty "
                        f"string, got {v!r}"
                    )
        if rtype == "io":
            kind = rec.get("kind")
            if kind not in IO_KINDS:
                errors.append(
                    f"line {i}: io kind must be one of {IO_KINDS}, "
                    f"got {kind!r}"
                )
            v = rec.get("bytes")
            if not isinstance(v, int) or v < 0:
                errors.append(
                    f"line {i}: io bytes must be a non-negative int, "
                    f"got {v!r}"
                )
        if rtype == "run_start":
            started.add(rec.get("run_id"))
        if rtype == "run_end":
            ended.add(rec.get("run_id"))
    for rid in sorted(started - ended, key=str):
        errors.append(f"run {rid!r}: run_start without run_end")
    return errors


def validate_file(path: str) -> list[str]:
    with open(path) as f:
        return validate_lines(f)
