"""Render an events.jsonl into the human run summary table.

``erasurehead-tpu report <events.jsonl> [more.jsonl ...]`` — one row per
run: scheme, real steps/sec, compile vs run seconds, exec/data cache hits,
straggler-arrival p50/p90/p99 (sentinel-masked, obs/events.arrival_summary)
and the mean AGC decode-error norm (obs/decode.py; exact schemes read 0).
"""

from __future__ import annotations

import json
from typing import Optional, Sequence


def load_runs(paths: Sequence[str]) -> list[dict]:
    """Group event records by run_id across files, in first-seen order.

    Returns one dict per run: {"run_id", "start": run_start|None,
    "end": run_end|None, "compiles": [...], "uploads": [...],
    "rounds": [...], "decode": [...], "cohort": cohort|None,
    "warnings": [...], "prefetch": [...],
    "dispatch_ahead": dispatch_ahead|None,
    "stale_decode": stale_decode|None,
    "critical_path": critical_path|None, "regime": [...]}. A trailing
    run_id=None entry carries stray warnings, shard-store ``io`` records
    (out-of-core byte accounting), any ``sweep_trajectory`` journal
    records (a sweep journal is an events.jsonl like any other —
    `report` renders its rows, diverged ones flagged), the serve
    daemon's request/pack/admit/evict stream (rendered as the per-tenant
    serving section), un-run-tagged ``regime`` snapshots, the SLO
    tracker's ``slo`` burn-rate records, and the autotune plane's
    ``tune`` decision records (rendered as the tuned-defaults section).
    Unparseable lines are skipped (the validator's job is strictness;
    the report renders what it can)."""
    runs: dict = {}
    order: list = []
    warnings: list = []
    trajectories: list = []
    adapt: list = []
    membership: list = []
    fleet: list = []
    io: list = []
    regime: list = []
    slo: list = []
    tune: list = []
    serve: dict = {
        "requests": [], "packs": [], "admits": [], "evicts": [],
        "rejects": [], "streams": [], "restarts": [],
    }

    def run(rid):
        if rid not in runs:
            runs[rid] = {
                "run_id": rid, "start": None, "end": None, "compiles": [],
                "uploads": [], "rounds": [], "decode": [], "cohort": None,
                "warnings": [], "prefetch": [],
                "dispatch_ahead": None, "stale_decode": None,
                "critical_path": None, "regime": [],
            }
            order.append(rid)
        return runs[rid]

    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                rtype = rec.get("type")
                rid = rec.get("run_id")
                if rtype == "run_start":
                    run(rid)["start"] = rec
                elif rtype == "run_end":
                    run(rid)["end"] = rec
                elif rtype == "compile":
                    run(rid)["compiles"].append(rec)
                elif rtype == "data_upload":
                    run(rid)["uploads"].append(rec)
                elif rtype == "rounds":
                    run(rid)["rounds"].append(rec)
                elif rtype == "decode":
                    run(rid)["decode"].append(rec)
                elif rtype == "cohort":
                    run(rid)["cohort"] = rec
                elif rtype == "warning":
                    (run(rid)["warnings"] if rid else warnings).append(rec)
                elif rtype == "sweep_trajectory":
                    trajectories.append(rec)
                elif rtype == "adapt":
                    adapt.append(rec)
                elif rtype == "membership":
                    membership.append(rec)
                elif rtype == "fleet":
                    fleet.append(rec)
                elif rtype == "request":
                    serve["requests"].append(rec)
                elif rtype == "pack":
                    serve["packs"].append(rec)
                elif rtype == "admit":
                    serve["admits"].append(rec)
                elif rtype == "evict":
                    serve["evicts"].append(rec)
                elif rtype == "reject":
                    serve["rejects"].append(rec)
                elif rtype == "stream":
                    serve["streams"].append(rec)
                elif rtype == "restart":
                    serve["restarts"].append(rec)
                elif rtype == "prefetch":
                    run(rid)["prefetch"].append(rec)
                elif rtype == "dispatch_ahead":
                    run(rid)["dispatch_ahead"] = rec
                elif rtype == "stale_decode":
                    run(rid)["stale_decode"] = rec
                elif rtype == "critical_path":
                    run(rid)["critical_path"] = rec
                elif rtype == "regime":
                    (run(rid)["regime"] if rid else regime).append(rec)
                elif rtype == "slo":
                    slo.append(rec)
                elif rtype == "io":
                    io.append(rec)
                elif rtype == "tune":
                    tune.append(rec)
    out = [runs[rid] for rid in order]
    if (
        warnings or trajectories or adapt or membership or fleet or io
        or regime or slo or tune or any(serve.values())
    ):
        out.append({
            "run_id": None, "warnings": warnings,
            "trajectories": trajectories, "serve": serve,
            "adapt": adapt, "membership": membership, "fleet": fleet,
            "io": io, "regime": regime, "slo": slo, "tune": tune,
        })
    return out


def _adapt_section(stray: list) -> list[str]:
    """The adaptive-controller section: one line per decision (chunk
    start round, chosen arm, reason), plus a switch/shift summary — a
    run's policy trajectory, reconstructed from its `adapt` events."""
    decisions: list = []
    for g in stray:
        decisions.extend(g.get("adapt", []))
    if not decisions:
        return []
    switches = sum(
        1
        for a, b in zip(decisions, decisions[1:])
        if a.get("arm") != b.get("arm")
    )
    shifts = sum(1 for d in decisions if d.get("regime_shift"))
    lines = [
        f"\nadaptive controller: {len(decisions)} decision(s), "
        f"{switches} arm switch(es)"
        + (f", {shifts} regime shift(s) detected" if shifts else "")
    ]
    for d in decisions:
        err = d.get("decode_error_mean")
        lines.append(
            f"  round {d.get('round', '?'):>5} -> "
            f"{str(d.get('arm', '?'))[:24]:24s} [{d.get('reason', '?')}]"
            f"  sim/round={_fmt(d.get('sim_per_round'), '.4f')}"
            f"  decode_err={_fmt(err, '.6f')}"
            + ("  REGIME SHIFT" if d.get("regime_shift") else "")
        )
    return lines


def _membership_section(stray: list) -> list[str]:
    """The elastic-membership section: the run's membership timeline
    (deaths, joins, re-layouts, probes) plus a per-chunk row summary —
    the controller's trajectory, reconstructed from its `membership`
    events (elastic/driver.py)."""
    recs: list = []
    for g in stray:
        recs.extend(g.get("membership", []))
    if not recs:
        return []
    decisions = [r for r in recs if r.get("action") != "chunk"]
    chunks = [r for r in recs if r.get("action") == "chunk"]
    relayouts = [r for r in decisions if r.get("action") == "relayout"]
    deaths = [w for r in decisions if r.get("action") == "death"
              for w in (r.get("workers") or [])]
    joins = [w for r in decisions if r.get("action") == "join"
             for w in (r.get("workers") or [])]
    lines = [
        f"\nelastic membership: {len(chunks)} chunk(s), "
        f"{len(relayouts)} re-layout(s)"
        + (f", {len(deaths)} death(s) {sorted(set(deaths))}" if deaths
           else "")
        + (f", {len(joins)} join(s) {sorted(set(joins))}" if joins else "")
    ]
    for r in decisions:
        action = r.get("action", "?")
        detail = ""
        if r.get("workers"):
            detail = f" workers={r['workers']}"
        if action == "relayout":
            detail += (
                f"  {r.get('n_workers_before', '?')} -> "
                f"{r.get('n_workers', '?')} workers"
            )
        lines.append(
            f"  round {r.get('round', '?'):>5} {action:10s}{detail}"
        )
    for r in chunks:
        arm = r.get("arm")
        lines.append(
            f"  round {r.get('round', '?'):>5} chunk      "
            f"W={r.get('n_workers', '?'):<3} "
            f"sim={_fmt(r.get('sim_time'), '.3f'):>8s} "
            f"decode_err={_fmt(r.get('decode_error_mean'), '.6f')}"
            + (f" arm={arm}" if arm else "")
        )
    return lines


def _fleet_section(stray: list) -> list[str]:
    """The serve-fleet section: the fleet's membership and deploy
    timeline — joins, probe-miss streaks, deaths declared (with the
    evidential streak that earned them), WAL adoptions (and how many
    acceptances each replayed), routing redirects, and the deploy
    phases of each rolling bounce — from the typed `fleet` events
    (serve/fleet.py, serve/router.py)."""
    recs: list = []
    for g in stray:
        recs.extend(g.get("fleet", []))
    if not recs:
        return []
    by = {a: [r for r in recs if r.get("action") == a]
          for a in ("join", "suspect", "declare_dead", "adopt",
                    "route", "deploy_phase")}
    replayed = sum(int(r.get("records") or 0) for r in by["adopt"])
    lines = [
        f"\nserve fleet: {len(by['join'])} join(s), "
        f"{len(by['declare_dead'])} death(s) declared, "
        f"{len(by['adopt'])} adoption(s)"
        + (f" ({replayed} acceptance(s) replayed)" if by["adopt"]
           else "")
        + (f", {len(by['route'])} redirect(s)" if by["route"] else "")
    ]
    for r in recs:
        action = r.get("action", "?")
        if action == "probe":
            continue  # per-probe records are too chatty for the table
        detail = ""
        if action in ("suspect", "declare_dead"):
            detail = f" streak={r.get('streak', '?')}/{r.get('k', '?')}"
        elif action == "adopt":
            detail = (
                f" records={r.get('records', '?')}"
                + (f" adopter={r['adopter']}" if r.get("adopter")
                   else "")
            )
        elif action == "deploy_phase":
            detail = f" phase={r.get('phase', '?')}"
        elif action == "route":
            detail = f" hop={r.get('hop', '?')}"
        lines.append(
            f"  {action:13s} {str(r.get('replica', '?'))[:16]:16s}"
            f"{detail}"
        )
    return lines


def _pipeline_section(groups: list) -> list[str]:
    """The pipelined-training section: per pipelined run, how far ahead of
    the synchronous round barrier its dispatches ran (the overlap the
    pipeline bought on the simulated clock) and — when a tool emitted the
    post-run decomposition — whether staleness noise or erasure-coding
    noise dominated its decode error. From the ``dispatch_ahead`` and
    ``stale_decode`` records (parallel/pipeline.py, obs/decode.py)."""
    pipelined = [
        g for g in groups if g.get("dispatch_ahead") or g.get("stale_decode")
    ]
    if not pipelined:
        return []
    lines = ["\npipelined training (bounded staleness):"]
    for g in pipelined:
        da = g.get("dispatch_ahead") or {}
        sd = g.get("stale_decode") or {}
        line = f"  {str(g['run_id'])[:16]:16s}"
        if da:
            line += (
                f" depth={da.get('pipeline_depth', '?')}"
                f" ahead mean/max "
                f"{_fmt(da.get('ahead_mean_s'), '.4f')}/"
                f"{_fmt(da.get('ahead_max_s'), '.4f')}s"
                f" overlap {_fmt(da.get('overlap_total_s'), '.3f')}s"
            )
        if sd:
            line += (
                f" | staleness err {_fmt(sd.get('staleness_error_mean'), '.6f')}"
                f" vs coding err {_fmt(sd.get('coding_error_mean'), '.6f')}"
                f" (staleness share {_fmt(sd.get('staleness_share'), '.3f')})"
            )
        lines.append(line)
    return lines


def _critical_path_section(groups: list) -> list[str]:
    """The wall-clock attribution section: per run carrying a
    ``critical_path`` record, both ledgers rendered by
    obs/critical_path.render_lines (simulated-clock straggler
    decomposition + host-wall decode/prefetch split)."""
    from erasurehead_tpu.obs import critical_path as cpath_lib

    attributed = [g for g in groups if g.get("critical_path")]
    if not attributed:
        return []
    lines = ["\ncritical path (wall-clock attribution):"]
    for g in attributed:
        lines.append(f"  {str(g['run_id'])[:16]}:")
        lines.extend(
            "  " + ln for ln in cpath_lib.render_lines(g["critical_path"])
        )
    return lines


def _regime_section(groups: list, stray: list) -> list[str]:
    """The arrival-regime section: the estimator's emitted snapshots
    (obs/regime.py) — change-points flagged, latest rate/kind last."""
    recs = [r for g in groups for r in g.get("regime", [])]
    recs += [r for g in stray for r in g.get("regime", [])]
    if not recs:
        return []
    lines = ["\narrival regime (online estimate):"]
    for r in recs:
        flag = " <- SHIFT" if r.get("shifted") else ""
        lines.append(
            f"  round {r.get('round', '?'):>4} kind={r.get('kind', '?'):9s}"
            f" rate {_fmt(r.get('rate'), '.3f')}/s"
            f" tail {_fmt(r.get('tail_index'), '.2f')}"
            f" (n={r.get('n', 0)}){flag}"
        )
    return lines


def _slo_section(stray: list) -> list[str]:
    """The SLO burn-rate section: per-tenant time-to-last-row objective
    windows from the tracker's ``slo`` records (obs/exporter.py)."""
    recs = [r for g in stray for r in g.get("slo", [])]
    if not recs:
        return []
    latest: dict = {}
    for r in recs:
        latest[r.get("tenant")] = r
    lines = ["\nslo burn rate (time-to-last-row):"]
    for tenant in sorted(latest):
        r = latest[tenant]
        burn = float(r.get("burn_rate", 0.0))
        flag = " <- BURNING" if burn > 1.0 else ""
        lines.append(
            f"  {str(tenant):12s} slo {_fmt(r.get('slo_s'), '.2f')}s: "
            f"{r.get('breaches', 0)}/{r.get('window_requests', 0)} breached,"
            f" burn {burn:.2f}x budget{flag}"
        )
    return lines


def _tune_section(stray: list) -> list[str]:
    """The autotuned-defaults section: one line per distinct auto-knob
    resolution from the ``tune`` records — which race, on which device
    kind at which shape, what it chose and where the choice came from
    (a just-run race, the persisted decision cache, or the hardcoded
    fallback). The section that answers "which measured verdicts did
    this run actually lower under?"."""
    recs = [r for g in stray for r in g.get("tune", [])]
    if not recs:
        return []
    latest: dict = {}
    for r in recs:
        latest[(r.get("race"), r.get("device_kind"), r.get("shape"))] = r
    n_measured = sum(
        1 for r in latest.values() if r.get("source") in ("race", "cache")
    )
    lines = [
        f"\nautotuned defaults: {len(latest)} resolution(s), "
        f"{n_measured} from measured verdicts"
    ]
    for key in sorted(latest, key=lambda k: tuple(str(x) for x in k)):
        r = latest[key]
        lines.append(
            f"  {str(r.get('race', '?')):13s} -> "
            f"{str(r.get('choice', '?')):12s} "
            f"[{r.get('source', '?')}]  {r.get('device_kind', '?')}  "
            f"{r.get('shape', '?')}"
        )
    return lines


def _prefetch_section(groups: list, stray: list) -> list[str]:
    """The out-of-core streaming section: per streamed run, how many
    partition windows moved how many host→device bytes and how much of
    the transfer time compute hid; plus the shard-store disk totals —
    from the ``prefetch`` (per-run) and ``io`` (stray) records."""
    streamed = [g for g in groups if g.get("prefetch")]
    io = [r for g in stray for r in g.get("io", [])]
    if not streamed and not io:
        return []
    lines = ["\nout-of-core streaming (shard store + prefetch):"]
    for g in streamed:
        pf = g["prefetch"]
        total = sum(p.get("bytes", 0) for p in pf)
        fetch = sum(p.get("fetch_s") or 0.0 for p in pf)
        lines.append(
            f"  {str(g['run_id'])[:16]:16s} {len(pf)} window(s), "
            f"{total / (1 << 20):.1f} MiB staged, "
            f"fetch {fetch:.3f}s"
        )
    reads = [r for r in io if r.get("kind") == "shard_read"]
    writes = [r for r in io if r.get("kind") == "store_write"]
    if reads or writes:
        rb = sum(r.get("bytes", 0) for r in reads)
        wb = sum(r.get("bytes", 0) for r in writes)
        lines.append(
            f"  shard io: {len(reads)} read(s) {rb / (1 << 20):.1f} MiB, "
            f"{len(writes)} write(s) {wb / (1 << 20):.1f} MiB"
        )
    return lines


def _serve_section(stray: list) -> list[str]:
    """The per-tenant serving section: requests, packed-dispatch ratio,
    admission pressure, backpressure (rejects + retried-after-429
    acceptances), stream overflow drops, warm restarts, and
    quarantined/diverged rows — from the serve daemon's request/pack/
    admit/evict/reject/stream/restart + sweep_trajectory records."""
    serve = {
        "requests": [], "packs": [], "admits": [], "evicts": [],
        "rejects": [], "streams": [], "restarts": [],
    }
    trajectories: list = []
    for g in stray:
        for k in serve:
            serve[k].extend((g.get("serve") or {}).get(k, []))
        trajectories.extend(g.get("trajectories", []))
    # completion markers (phase="done", server._finish) pair with intake
    # records for the live SLO/goodput plane; request totals here count
    # each request once, at intake
    serve["requests"] = [
        r for r in serve["requests"] if r.get("phase") != "done"
    ]
    if not serve["requests"] and not serve["packs"] and not (
        serve["rejects"] or serve["restarts"]
    ):
        return []
    packs = serve["packs"]
    n_packed_traj = sum(p.get("n_trajectories", 0) for p in packs)
    ratio = n_packed_traj / len(packs) if packs else 0.0
    deferred = sum(
        1 for a in serve["admits"] if a.get("admitted") is False
    )
    overflow_dropped = sum(
        s.get("dropped") or 0
        for s in serve["streams"]
        if s.get("event") == "overflow"
    )
    lines = [
        f"\nserve (multi-tenant cohort packing): "
        f"{len(serve['requests'])} request(s) -> {len(packs)} "
        f"dispatch(es), {ratio:.1f} trajectories/dispatch"
        + (f", {deferred} deferred by admission" if deferred else "")
        + (f", {len(serve['evicts'])} eviction(s)" if serve["evicts"]
           else "")
        + (f", {len(serve['rejects'])} rejected (429)"
           if serve["rejects"] else "")
    ]
    def _blank():
        return {
            "requests": 0, "rows": 0, "diverged": 0, "errors": 0,
            "rejects": 0, "retried": 0,
        }

    by_tenant: dict = {}
    for r in serve["requests"]:
        t = by_tenant.setdefault(r.get("tenant", "?"), _blank())
        t["requests"] += 1
        if r.get("retry"):
            # an acceptance whose submit attempt number is > 0: the
            # client's backoff schedule worked — count it as a retried
            # request that eventually got in
            t["retried"] += 1
    for r in serve["rejects"]:
        t = by_tenant.setdefault(r.get("tenant", "?"), _blank())
        t["rejects"] += 1
    for rec in trajectories:
        tenant = rec.get("tenant")
        if tenant is None:
            continue  # a local sweep journal row, not a serve row
        t = by_tenant.setdefault(tenant, _blank())
        t["rows"] += 1
        if rec.get("status") == "diverged":
            t["diverged"] += 1
    for w in (g2 for g in stray for g2 in g.get("warnings", [])):
        if w.get("kind") != "serve_error":
            continue
        msg = w.get("message", "")
        for tenant, t in by_tenant.items():
            if f"(tenant '{tenant}')" in msg:
                t["errors"] += 1
    header = (
        f"  {'tenant':16s} {'requests':>9s} {'rows':>6s} "
        f"{'diverged':>9s} {'errors':>7s} {'rejects':>8s} {'retried':>8s}"
    )
    lines += [header, "  " + "-" * (len(header) - 2)]
    for tenant in sorted(by_tenant):
        t = by_tenant[tenant]
        lines.append(
            f"  {tenant[:16]:16s} {t['requests']:>9d} {t['rows']:>6d} "
            f"{t['diverged']:>9d} {t['errors']:>7d} {t['rejects']:>8d} "
            f"{t['retried']:>8d}"
        )
    for r in serve["restarts"]:
        lines.append(
            f"  warm restart: {r.get('wal_records', 0)} WAL record(s) -> "
            f"{r.get('resubmitted', 0)} re-dispatched, "
            f"{r.get('rehydrated', 0)} rehydrated from journal"
        )
    if overflow_dropped:
        lines.append(
            f"  stream backpressure: {overflow_dropped} row(s) shed to "
            f"slow readers (journaled; re-fetchable by resubmission)"
        )
    return lines


def _fmt(v, spec: str, none: str = "-") -> str:
    return format(v, spec) if v is not None else none


def _arrival_cell(end: Optional[dict]) -> str:
    arr = (end or {}).get("arrival") or {}
    if arr.get("n_arrivals"):
        cell = (
            f"{_fmt(arr.get('p50'), '.3f')}/{_fmt(arr.get('p90'), '.3f')}"
            f"/{_fmt(arr.get('p99'), '.3f')}"
        )
        if arr.get("n_never"):
            cell += f" ({arr['n_never']} never)"
        return cell
    return "-"


def render(paths: Sequence[str]) -> str:
    """The summary table for one or more event logs."""
    loaded = load_runs(paths)
    groups = [g for g in loaded if g["run_id"] is not None]
    stray = [g for g in loaded if g["run_id"] is None]
    header = (
        f"{'run':16s} {'scheme':16s} {'steps/s':>9s} {'compile_s':>10s} "
        f"{'run_s':>8s} {'exec h/m':>9s} {'data':>5s} "
        f"{'arrival p50/p90/p99':>22s} {'decode err':>11s}"
    )
    lines = [header, "-" * len(header)]
    for g in groups:
        start, end = g["start"] or {}, g["end"] or {}
        scheme = start.get("scheme", "?")
        compile_s = sum(
            c.get("seconds", 0.0) for c in g["compiles"]
            if not c.get("cache_hit")
        )
        hits = end.get("exec_hits")
        misses = end.get("exec_misses")
        hm = f"{hits}/{misses}" if hits is not None else "-"
        data = "-"
        if g["uploads"]:
            data = "hit" if all(
                u.get("cache_hit") for u in g["uploads"]
            ) else "miss"
        err = end.get("decode_error_mean")
        if err is None and g["decode"]:
            # layer-tagged records are per-layer gradient-space series
            # (blockwise coding), not the run-level weight-space norm —
            # averaging them in would mix the two metrics
            untagged = [d for d in g["decode"] if d.get("layer") is None]
            n = sum(d.get("n_rounds", 0) for d in untagged)
            if n:
                err = sum(
                    d.get("error_mean", 0.0) * d.get("n_rounds", 0)
                    for d in untagged
                ) / n
        lines.append(
            f"{str(g['run_id'])[:16]:16s} {str(scheme)[:16]:16s} "
            f"{_fmt(end.get('steps_per_sec'), '9.1f'):>9s} "
            f"{compile_s:10.3f} "
            f"{_fmt(end.get('wall_time_s'), '8.3f'):>8s} {hm:>9s} "
            f"{data:>5s} {_arrival_cell(end):>22s} "
            f"{_fmt(err, '11.6f'):>11s}"
        )
    cohorts = [g for g in groups if g.get("cohort")]
    if cohorts:
        lines.append("\ncohort dispatches (trajectory-batched sweeps):")
        for g in cohorts:
            c = g["cohort"]
            schemes = c.get("schemes") or []
            seeds = c.get("seeds") or []
            disp = c.get("dispatches", 1)
            lines.append(
                f"  {str(g['run_id'])[:16]:16s} "
                f"{len(schemes)} scheme(s) x {len(set(seeds))} seed(s) = "
                f"{c.get('n_trajectories', len(seeds))} trajectories in "
                f"{disp} dispatch(es) [{c.get('lowering', '?')}]"
            )
    lines.extend(_critical_path_section(groups))
    lines.extend(_pipeline_section(groups))
    lines.extend(_prefetch_section(groups, stray))
    lines.extend(_regime_section(groups, stray))
    lines.extend(_serve_section(stray))
    lines.extend(_slo_section(stray))
    lines.extend(_tune_section(stray))
    lines.extend(_adapt_section(stray))
    lines.extend(_membership_section(stray))
    lines.extend(_fleet_section(stray))
    # serve rows (tenant-tagged) render in the serving section above; the
    # journal listing keeps the local-sweep rows
    trajectories = [
        t
        for g in stray
        for t in g.get("trajectories", [])
        if t.get("tenant") is None
    ]
    if trajectories:
        n_div = sum(1 for t in trajectories if t.get("status") == "diverged")
        lines.append(
            f"\nsweep journal: {len(trajectories)} trajectory record(s)"
            + (f", {n_div} DIVERGED" if n_div else "")
        )
        for t in trajectories:
            row = t.get("row") or {}
            loss = row.get("final_train_loss")
            status = t.get("status", "?")
            lines.append(
                f"  {str(t.get('label', '?'))[:24]:24s} "
                f"{status:>9s} "
                f"final_train_loss={_fmt(loss, '.6f') if isinstance(loss, (int, float)) else '-'}"
            )
    n_warn = sum(len(g["warnings"]) for g in groups) + sum(
        len(g["warnings"]) for g in stray
    )
    if n_warn:
        lines.append(f"\n{n_warn} warning(s):")
        for g in groups + stray:
            for w in g["warnings"]:
                lines.append(
                    f"  [{w.get('kind', '?')}] {w.get('message', '')}"
                )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """`erasurehead-tpu report` / `python -m erasurehead_tpu.obs.report`."""
    import argparse

    p = argparse.ArgumentParser(
        prog="erasurehead-tpu report",
        description="Render events.jsonl run telemetry into a summary table",
    )
    p.add_argument("events", nargs="+", help="events.jsonl path(s)")
    p.add_argument("--validate", action="store_true",
                   help="schema-check the files first (exit 1 on errors)")
    ns = p.parse_args(argv)
    if ns.validate:
        from erasurehead_tpu.obs import events as events_lib

        errors = [
            f"{path}: {e}"
            for path in ns.events
            for e in events_lib.validate_file(path)
        ]
        if errors:
            for e in errors:
                print(e)
            return 1
    print(render(ns.events))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
