"""Per-round wall-clock attribution: where a run's time actually went.

ErasureHead's whole argument (arXiv:1901.09671) is a wall-clock
decomposition — how much of a round the master spends *waiting on
stragglers* versus *doing work* — and this module makes that
decomposition a first-class measured quantity instead of something a
human re-derives from raw events.jsonl. Two ledgers, because the system
runs two clocks:

  - the **simulated master clock** (``timeset``, the paper's quantity):
    each round's close time decomposes into the fastest-arrival compute
    floor (``compute_s`` — nothing can close before the first needed
    gradient lands), the straggler wait (``straggler_wait_s`` — the tail
    between the first usable arrival and the stop rule closing, including
    deadline idling when a cutoff scheme waits out its deadline), and the
    pipelined dispatch gap (``dispatch_gap_s`` — master idle between a
    round's dispatch gate opening and the previous round's close, only
    nonzero when the depth-lagged gate stalls). Pipelined overlap that
    *hid* arrival time behind the previous round rides along as
    ``overlap_hidden_s`` — it is the win, not a cost, so it is reported
    but excluded from the ledger.
  - the **host wall** (``wall_s``, the timed scan region): decode+update
    execution (``decode_update_s`` — the device scan; under ring
    transport the ppermute hops are fused into the same executable, so
    transport rides inside this bucket, tagged via ``transport``) versus
    the prefetch stall (``prefetch_stall_s`` — streamed-residency staging
    waits the double buffer failed to hide, data/prefetch.py
    ``blocked_s``).

Each ledger sums to its measured total *by construction*, and the event
validator (obs/events.py ``critical_path`` checks) re-verifies the
reconciliation within :data:`events.CRITICAL_PATH_TOL` on every line —
an attribution that loses wall-clock is a schema error.

Everything here is host-side float64 arithmetic over arrays the run
already produced; emission happens after the timed region like every
other event, so the PR 3 observation-only contract holds untouched.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from erasurehead_tpu.obs import events

#: sim-ledger bucket names, in render order
SIM_BUCKETS = ("compute_s", "straggler_wait_s", "dispatch_gap_s")

#: host-ledger bucket names, in render order
HOST_BUCKETS = ("decode_update_s", "prefetch_stall_s")


@dataclasses.dataclass(frozen=True)
class CriticalPath:
    """One run's attribution: totals, ledgers, and per-round arrays."""

    wall_s: float  # measured host wall of the timed region
    sim_total_s: float  # measured simulated master clock (timeset sum)
    components: dict  # host ledger, sums to wall_s
    sim_components: dict  # sim ledger, sums to sim_total_s
    overlap_hidden_s: float  # pipelined overlap (a win; outside ledgers)
    transport: str  # "ring" | "none" — where decode_update_s ran
    per_round: dict  # {"compute_s","straggler_wait_s","dispatch_gap_s"}

    def fractions(self) -> dict:
        """Both ledgers normalized by their own measured totals, keyed
        without the ``_s`` suffix (the typed event's ``fractions``
        payload). Values are clamped to [0, 1] against float dust."""
        out = {}
        for comps, total in (
            (self.components, self.wall_s),
            (self.sim_components, self.sim_total_s),
        ):
            for k, v in comps.items():
                frac = v / total if total > 0 else 0.0
                out[k[:-2] if k.endswith("_s") else k] = round(
                    min(max(frac, 0.0), 1.0), 6
                )
        return out

    def payload(self) -> dict:
        """The ``critical_path`` event payload (everything but run_id)."""
        return {
            "wall_s": round(self.wall_s, 6),
            "sim_total_s": round(self.sim_total_s, 6),
            "components": {
                k: round(v, 6) for k, v in self.components.items()
            },
            "sim_components": {
                k: round(v, 6) for k, v in self.sim_components.items()
            },
            "fractions": self.fractions(),
            "overlap_hidden_s": round(self.overlap_hidden_s, 6),
            "transport": self.transport,
        }


def attribute(
    timeset,
    worker_times,
    collected,
    *,
    wall_s: float,
    prefetch_stall_s: float = 0.0,
    dispatch=None,
    done=None,
    transport: str = "none",
) -> CriticalPath:
    """Build both attribution ledgers from a run's schedule arrays.

    ``timeset``/``worker_times``/``collected`` are the usual [R]/[R, W]
    schedule artifacts (worker_times carries the -1 never-arrived
    sentinel; masking happens here, same discipline as
    events.arrival_summary). ``dispatch``/``done`` are the pipelined
    schedule's absolute clocks when available (parallel/pipeline.
    PipelinedSchedule) — without them the dispatch-gap bucket is zero,
    which is exact for every synchronous schedule.
    """
    t = np.asarray(timeset, dtype=np.float64)
    wt = np.asarray(worker_times, dtype=np.float64)
    coll = np.asarray(collected, dtype=bool)
    R = t.shape[0]

    # masked first/last collected arrival per round (relative clock)
    ok = coll & (wt >= 0.0) & np.isfinite(wt)
    has_any = ok.any(axis=1)
    first = np.where(
        has_any, np.where(ok, wt, np.inf).min(axis=1), 0.0
    )
    stop_rel = np.where(
        has_any, np.where(ok, wt, -np.inf).max(axis=1), 0.0
    )

    # pipelined overlap: the part of the round's relative close that the
    # previous round's drain already covered (sim_time < stop_rel).
    # Exactly zero for synchronous schedules, where timeset IS the
    # relative stop (deadline cutoffs have timeset >= stop_rel).
    hidden = np.maximum(stop_rel - t, 0.0)

    # dispatch gap: master idle between the previous close and this
    # round's dispatch gate opening (depth-lagged gate stalls only)
    gap = np.zeros(R)
    if dispatch is not None and done is not None:
        disp = np.asarray(dispatch, dtype=np.float64)
        dn = np.asarray(done, dtype=np.float64)
        prev_done = np.concatenate(([0.0], dn[:-1]))
        gap = np.maximum(disp - prev_done, 0.0)

    # the ledger closes exactly: compute (overlap-adjusted fastest
    # arrival) + gap + wait == timeset per round, each bucket >= 0
    compute = np.clip(np.where(has_any, first, 0.0) - hidden, 0.0, t)
    gap = np.minimum(gap, t - compute)
    wait = t - compute - gap

    wall = max(float(wall_s), 0.0)
    stall = min(max(float(prefetch_stall_s), 0.0), wall)
    return CriticalPath(
        wall_s=wall,
        sim_total_s=float(t.sum()),
        components={
            "decode_update_s": wall - stall,
            "prefetch_stall_s": stall,
        },
        sim_components={
            "compute_s": float(compute.sum()),
            "straggler_wait_s": float(wait.sum()),
            "dispatch_gap_s": float(gap.sum()),
        },
        overlap_hidden_s=float(hidden.sum()),
        transport=transport,
        per_round={
            "compute_s": compute,
            "straggler_wait_s": wait,
            "dispatch_gap_s": gap,
        },
    )


def attribute_result(res, *, prefetch_stall_s: Optional[float] = None):
    """Attribution straight from a TrainResult (synchronous runs; the
    pipelined trainer passes its schedule's dispatch/done clocks to
    :func:`attribute` directly). The prefetch stall defaults to the
    streamed run's own ``cache_info["prefetch"]["blocked_s"]``."""
    if prefetch_stall_s is None:
        pf = (res.cache_info or {}).get("prefetch") or {}
        prefetch_stall_s = float(pf.get("blocked_s", 0.0))
    mode = (res.cache_info or {}).get("stack_mode")
    return attribute(
        res.timeset,
        res.worker_times,
        res.collected,
        wall_s=float(res.wall_time),
        prefetch_stall_s=prefetch_stall_s,
        transport="ring" if mode == "ring" else "none",
    )


def emit_event(run_id: str, cp: CriticalPath) -> bool:
    """Emit the run's typed ``critical_path`` record into the current
    capture (host-side, after the timed region — observation-only)."""
    return events.emit("critical_path", run_id=run_id, **cp.payload())


def from_events(records) -> dict:
    """run_id -> critical_path payload, from parsed event record dicts
    (the report/top side: renders whatever the run attributed)."""
    out = {}
    for rec in records:
        if rec.get("type") == "critical_path":
            out[rec.get("run_id")] = rec
    return out


def render_lines(payload: dict) -> list:
    """Human lines for one run's attribution (report section body)."""
    lines = []
    wall = float(payload.get("wall_s", 0.0))
    sim = float(payload.get("sim_total_s", 0.0))
    fr = payload.get("fractions", {})

    def pct(key):
        return f"{100.0 * float(fr.get(key, 0.0)):5.1f}%"

    sim_c = payload.get("sim_components", {})
    host_c = payload.get("components", {})
    lines.append(
        f"  simulated clock {sim:.3f}s: "
        f"compute {sim_c.get('compute_s', 0.0):.3f}s ({pct('compute')}) | "
        f"straggler-wait {sim_c.get('straggler_wait_s', 0.0):.3f}s "
        f"({pct('straggler_wait')}) | dispatch-gap "
        f"{sim_c.get('dispatch_gap_s', 0.0):.3f}s ({pct('dispatch_gap')})"
    )
    hidden = float(payload.get("overlap_hidden_s", 0.0))
    if hidden > 0:
        lines.append(
            f"  pipelined overlap hid {hidden:.3f}s of arrival time"
        )
    transport = payload.get("transport", "none")
    decode_label = (
        "decode+update (incl. ring transport)"
        if transport == "ring"
        else "decode+update"
    )
    lines.append(
        f"  host wall {wall:.3f}s: {decode_label} "
        f"{host_c.get('decode_update_s', 0.0):.3f}s ({pct('decode_update')})"
        f" | prefetch-stall {host_c.get('prefetch_stall_s', 0.0):.3f}s "
        f"({pct('prefetch_stall')})"
    )
    return lines
