"""AGC decode error: the quantity the source papers actually bound.

ErasureHead (arXiv:1901.09671) and "Approximate Gradient Coding with
Optimal Decoding" (arXiv:2006.09638) both characterize approximate schemes
by their *decoding error* — how far the decoded gradient sits from the
exact full gradient. Every run computes this implicitly: the decoded
gradient is ``sum_p pw[p] * g_p`` where ``pw`` is the per-partition fold of
the collection weights (CodingLayout.fold_slot_weights), and the exact
gradient is the same sum with ``pw == 1`` everywhere. The per-round
decode-error norm surfaced here is therefore the weight-space residual

    err[r] = || pw[r] - 1 ||_2 / || 1 ||_2        (= ||w^T B - 1|| / sqrt(P))

— exactly the papers' decoding-error objective, and equal to
``||decoded - exact|| / ||exact||`` under isotropic partition gradients.
Computing the gradient-space norm directly would need extra device
programs per round; telemetry must add zero compiles (tests pin this), so
the weight-space form — exact host float64, from arrays the control plane
already built — is the honest choice.

Exact schemes (cyclic MDS, FRC with every group covered, naive) decode to
``pw == 1`` identically; the MDS lstsq solve leaves ~1e-13 float noise, so
residuals below :data:`EXACT_TOL` snap to exactly 0.0 — the test-pinned
"exact schemes read 0" contract. Approximate schemes (AGC group erasures,
avoidstragg/deadline rescales, randreg's lstsq-optimal combination over an
insufficient arrival set) are genuinely > 0 under nonzero straggling.

Pipelined runs (cfg.pipeline_depth; parallel/pipeline.py) add a SECOND
error source the weight-space norm cannot see: the gradient was taken at
a tau-stale iterate. :func:`staleness_error_series` measures that half
directly in gradient space (a post-run replay — it costs a compile, which
train()'s zero-compile telemetry pin forbids inline), and
:func:`emit_staleness_split` packages both halves as the "stale_decode"
typed event — the record that says whether staleness noise or
erasure-coding noise dominates a regime.
"""

from __future__ import annotations

import numpy as np

#: residuals below this are decode-exact up to lstsq float noise (measured
#: ~1e-13 for the cyclic MDS solve at W=30) and snap to exactly 0.0
EXACT_TOL = 1e-9


def decode_error_series(layout, message_weights: np.ndarray) -> np.ndarray:
    """[R] per-round decode-error norms for a run's collection weights.

    ``message_weights`` is the CollectionSchedule's [R, W] per-message
    decode weight table (parallel/collect.py); the slot expansion and
    partition fold reuse the exact step/trainer code paths
    (parallel.step.expand_slot_weights, CodingLayout.fold_slot_weights) so
    the surfaced error describes precisely the decode the run performed.
    Host-side float64; O(R * W * S) — microseconds at paper scale.
    """
    from erasurehead_tpu.parallel import step as step_lib

    mw = np.asarray(message_weights, dtype=np.float64)
    slot_w = np.asarray(
        step_lib.expand_slot_weights(
            mw, np.asarray(layout.coeffs), np.asarray(layout.slot_is_coded)
        )
    )  # [R, W, S]
    pw = layout.fold_slot_weights(slot_w)  # [R, P]
    P = layout.n_partitions
    err = np.linalg.norm(pw - 1.0, axis=-1) / np.sqrt(P)
    err[err < EXACT_TOL] = 0.0
    return err


def block_decode_error(
    layout, message_weights: np.ndarray, block_table: np.ndarray
) -> dict:
    """Per-layer (gradient-space) decode error: the decode-error-vs-depth
    series of the approximate-coding-limits analysis (arXiv:1901.08166),
    measured against a model's actual per-partition gradient blocks.

    ``block_table`` is the host [P, L, width] table of per-partition
    gradient blocks at a reference parameter point
    (ops/blocks.partition_block_table). The decoded gradient of block l
    in round r is ``pw[r] @ block_table[:, l]`` and the exact full
    gradient is the same contraction with ``pw == 1``, so

        per_block[r, l] = ||(pw[r] - 1) @ G_l|| / max(||1 @ G_l||, eps)

    is the per-layer relative decode error the weight-space norm
    (:func:`decode_error_series`) aggregates away, and

        cumulative[r, l] = || (pw[r] - 1) @ G_{0..l} ||_F

    — the unnormalized error over the first l+1 blocks — is monotone
    non-decreasing in depth l for every round (appending coordinates
    cannot shrink an L2 norm): the depth-sanity invariant
    tests/test_deep_coding.py pins. Host float64; exact rounds snap to
    0.0 like the weight-space series."""
    from erasurehead_tpu.parallel import step as step_lib

    mw = np.asarray(message_weights, dtype=np.float64)
    slot_w = np.asarray(
        step_lib.expand_slot_weights(
            mw, np.asarray(layout.coeffs), np.asarray(layout.slot_is_coded)
        )
    )
    pw = layout.fold_slot_weights(slot_w)  # [R, P]
    G = np.asarray(block_table, dtype=np.float64)  # [P, L, K]
    resid = np.einsum("rp,plk->rlk", pw - 1.0, G)  # decoded - exact
    exact = G.sum(axis=0)  # [L, K] — the pw == 1 contraction
    exact_norm = np.linalg.norm(exact, axis=-1)  # [L]
    num = np.linalg.norm(resid, axis=-1)  # [R, L]
    per_block = num / np.maximum(exact_norm[None, :], 1e-30)
    per_block[per_block < EXACT_TOL] = 0.0
    cumulative = np.sqrt(np.cumsum(num**2, axis=1))
    cumulative[cumulative < EXACT_TOL] = 0.0
    return {
        "per_block": per_block,
        "cumulative": cumulative,
        "exact_block_norms": exact_norm,
    }


def staleness_error_series(
    model, params_history, staleness, X, y, initial_params
) -> np.ndarray:
    """[R] per-round gradient-space STALENESS error of a pipelined run:

        s[r] = || g(p_stale[r]) - g(p_fresh[r]) || / max(||g(p_fresh[r])||, eps)

    where ``p_fresh[r]`` is the iterate ENTERING round r (``history[r-1]``,
    or ``initial_params`` for round 0), ``p_stale[r]`` is the iterate the
    pipelined scan actually differentiated at (the one entering round
    ``r - staleness[r]``), and g is the model's full-batch gradient. Zero
    exactly where ``staleness[r] == 0`` (the warm-up rounds and every
    round of a tau=0 run) — staleness error is DEFINED as the gradient
    displacement the stale slot introduced, nothing else.

    This is the half of the pipelined error decomposition the weight-space
    coding error (:func:`decode_error_series`) cannot see, and it needs a
    gradient replay — one vmapped full-batch grad over the entering
    iterates, a real device compile. Train() must stay zero-extra-compile
    (the telemetry pin), so this runs POST-run, from tools (the
    "stale_decode" event via :func:`emit_staleness_split`, the bench
    pipeline extra, obs report assembly) — never inside the trainer.

    ``X``/``y`` are the full training arrays (dense or TPU-native; scipy
    sparse callers convert first, as evaluate.replay does);
    ``staleness`` is the [R] tau schedule
    (parallel.pipeline.staleness_schedule or PipelinedSchedule.staleness).
    """
    import jax
    import jax.numpy as jnp

    tau = np.asarray(staleness, dtype=np.int64)
    R = int(tau.shape[0])
    # entering[r] = iterate entering round r: [p0, h[0], ..., h[R-2]]
    entering = jax.tree.map(
        lambda p0, h: jnp.concatenate(
            [jnp.asarray(p0, h.dtype)[None], h[: R - 1]]
        ),
        initial_params,
        params_history,
    )
    grads = jax.jit(
        jax.vmap(model.grad_sum, in_axes=(0, None, None))
    )(entering, X, y)
    g = np.stack(
        [
            np.asarray(l, dtype=np.float64).reshape(R, -1)
            for l in jax.tree.leaves(grads)
        ],
        axis=-1,
    ).reshape(R, -1)  # [R, n_params]
    idx = np.arange(R)
    diff = g[idx - tau] - g[idx]
    fresh_norm = np.linalg.norm(g, axis=-1)
    err = np.linalg.norm(diff, axis=-1) / np.maximum(fresh_norm, 1e-30)
    err[tau == 0] = 0.0
    err[err < EXACT_TOL] = 0.0
    return err


def emit_staleness_split(run_id, result, dataset) -> dict:
    """Compute a finished pipelined run's staleness-vs-coding error
    decomposition and emit it as ONE "stale_decode" event (obs/events.py
    schema): mean gradient-space staleness error, mean coding error (the
    run's weight-space decode-error series — the quantity the papers
    bound), and staleness's share of their sum. Returns the payload dict
    (also the bench extra's record) whether or not an event capture is
    active.

    Tool-side by design: costs one vmapped gradient replay compile, which
    train() is forbidden (zero-compile telemetry pin) — see
    :func:`staleness_error_series`.
    """
    from erasurehead_tpu.obs import events as events_lib
    from erasurehead_tpu.parallel.pipeline import staleness_schedule
    from erasurehead_tpu.train import trainer as trainer_lib

    cfg = result.config
    model = trainer_lib.build_model(cfg)
    p0 = trainer_lib._init_params_f32(cfg, model, dataset.n_features)
    n = result.n_train
    tau = staleness_schedule(cfg.rounds, cfg.pipeline_depth)[
        result.start_round:
    ]
    s_err = staleness_error_series(
        model, result.params_history, tau,
        dataset.X_train[:n], dataset.y_train[:n], p0,
    )
    c_err = np.asarray(result.decode_error, dtype=np.float64)[
        result.start_round:
    ]
    s_mean = float(s_err.mean()) if s_err.size else 0.0
    c_mean = float(c_err.mean()) if c_err.size else 0.0
    total = s_mean + c_mean
    payload = {
        "run_id": run_id,
        "first_round": int(result.start_round),
        "n_rounds": int(s_err.shape[0]),
        "staleness_error_mean": round(s_mean, 10),
        "coding_error_mean": round(c_mean, 10),
        # which noise source dominates the regime: 0 = pure coding error
        # (tau=0 runs land here exactly), 1 = pure staleness
        "staleness_share": round(s_mean / total, 10) if total > 0 else 0.0,
    }
    if events_lib.current():
        events_lib.emit("stale_decode", **payload)
    return payload


def summarize(decode_error) -> dict:
    """Mean/max summary of a [R] error series (run_end / bench fields)."""
    if decode_error is None:
        return {"decode_error_mean": None, "decode_error_max": None}
    err = np.asarray(decode_error, dtype=np.float64)
    if err.size == 0:
        return {"decode_error_mean": 0.0, "decode_error_max": 0.0}
    return {
        "decode_error_mean": round(float(err.mean()), 10),
        "decode_error_max": round(float(err.max()), 10),
    }
