"""Determinism & replay audit — the SPMD answer to race detection.

The reference has no race detection (SURVEY.md §5.2); its concurrency
correctness rests on MPI tag discipline (tag = iteration index, a band
reserved for partial schemes' second messages) and stale-send cancellation.
In this framework those hazards cannot exist by construction — there are no
tags, no mailboxes, no cancellation: the device program is a single jitted
scan whose only cross-chip op is a deterministic psum, and the control
plane is precomputed host float64. What CAN silently break reproducibility
is (a) an unseeded source entering the control plane, (b) nondeterministic
reduction order if a backend reassociates, (c) accidental recompilation
changing fusion between "identical" runs.

This module makes those checkable: run the same config twice (and the
control plane twice) and demand bitwise equality. It doubles as the
replayability guarantee the reference gets from iteration-seeded delays
(src/naive.py:141-147) — the whole run, not just the delay schedule, must
replay exactly.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class AuditResult:
    bitwise_equal: bool
    max_abs_diff: float
    what: str

    def __bool__(self) -> bool:
        return self.bitwise_equal


def _compare(a, b, what: str) -> AuditResult:
    a, b = np.asarray(a), np.asarray(b)
    if a.shape != b.shape:
        return AuditResult(False, np.inf, f"{what}: shape {a.shape} vs {b.shape}")
    equal = bool(np.array_equal(a, b))
    diff = 0.0 if equal else float(np.max(np.abs(a - b)))
    return AuditResult(equal, diff, what)


def audit_schedule_determinism(cfg) -> AuditResult:
    """The control plane (arrivals -> collection weights) must replay
    bit-for-bit — the analogue of the reference's seeded delay replay."""
    from erasurehead_tpu.parallel import collect
    from erasurehead_tpu.train.trainer import build_layout, default_arrivals

    outs = []
    for _ in range(2):
        layout = build_layout(cfg)
        # same arrival construction train() uses — a heterogeneous-cluster
        # config must audit the schedule it actually runs
        t = default_arrivals(cfg)
        s = collect.build_schedule(
            cfg.scheme, t, layout, num_collect=cfg.num_collect,
            deadline=cfg.deadline,
        )
        outs.append(
            np.concatenate(
                [s.message_weights.ravel(), s.sim_time.ravel(),
                 s.worker_times.ravel()]
            )
        )
    return _compare(outs[0], outs[1], "collection schedule")


def audit_training_determinism(cfg, dataset, mesh=None) -> AuditResult:
    """Two full runs of the jitted training scan must produce bitwise
    identical iterate histories — catches nondeterministic reductions or
    state leaking between runs."""
    from erasurehead_tpu.train import trainer

    hists = []
    for _ in range(2):
        res = trainer.train(cfg, dataset, mesh=mesh, measure=False)
        import jax

        hists.append(
            np.concatenate(
                [np.asarray(leaf).ravel()
                 for leaf in jax.tree.leaves(res.params_history)]
            )
        )
    return _compare(hists[0], hists[1], "iterate history")


def audit(cfg, dataset, mesh=None) -> dict[str, AuditResult]:
    """Full audit; all values must be truthy for a reproducible setup."""
    return {
        "schedule": audit_schedule_determinism(cfg),
        "training": audit_training_determinism(cfg, dataset, mesh=mesh),
    }
