"""jax version compatibility shims.

The codebase targets the modern jax surface (``jax.shard_map`` with the
``check_vma`` knob, jax >= 0.6); this image ships jax 0.4.x where the same
primitive lives at ``jax.experimental.shard_map.shard_map`` and the
replication checker is spelled ``check_rep``. One wrapper here keeps every
call site on the modern spelling.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # jax < 0.6
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

#: Does this jax implicitly psum the cotangent of a replicated operand when
#: jax.grad runs INSIDE a shard_map body? True under the >= 0.6 vma system
#: (an unvarying primal's cotangent is the mesh-wide sum); False on 0.4.x,
#: where jax.grad in the body yields the LOCAL partial gradient and the
#: caller must psum explicitly (parallel/step._weighted_loss_grad).
IMPLICIT_REPLICATED_GRAD_PSUM = _CHECK_KW == "check_vma"


def axis_size(axis_name):
    """``lax.axis_size`` across jax versions (0.4.x lacks it; the psum of
    a non-tracer 1 is the documented size idiom there — constant-folded,
    no collective)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def make_array_from_callback(shape, sharding, data_callback, dtype=None):
    """``jax.make_array_from_callback`` across versions: 0.4.x has no
    ``dtype`` kwarg (the callback's outputs carry it; the explicit kwarg
    only matters to newer jax when a process owns zero shards)."""
    import inspect

    import jax

    fn = jax.make_array_from_callback
    if "dtype" in inspect.signature(fn).parameters:
        return fn(shape, sharding, data_callback, dtype=dtype)
    return fn(shape, sharding, data_callback)


def pcast(x, axis_name, *, to="varying"):
    """``lax.pcast`` across jax versions: a vma-type cast under the >= 0.6
    varying-manual-axes system, and (correctly) a no-op on 0.4.x, which
    has no vma tracking to satisfy."""
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to=to)
    return x


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """``jax.shard_map`` across jax versions.

    ``check_vma`` maps onto the installed version's checker kwarg
    (``check_rep`` on jax < 0.6); None leaves the version default.
    """
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
