"""Tracing & profiling: the reference's wall-clock artifacts + XLA profiler.

The reference's observability is two hand-rolled artifacts — per-iteration
``timeset`` and the per-worker arrival matrix ``worker_timeset``
(src/naive.py:95,106,126; SURVEY.md §5.1) — which this framework preserves as
the *simulated* clock (they ARE the benchmark metric). On top, this module
wraps ``jax.profiler`` so a real device trace (XLA ops, HBM, fusion view in
TensorBoard/Perfetto) can be captured around any training run, something the
reference had no equivalent for.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def device_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None).

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    if not log_dir:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region in BOTH trace surfaces.

    - ``jax.named_scope``: pushes ``name`` onto the op-name stack during
      tracing, so every HLO op emitted inside carries it — this is what
      makes the jitted scan's phases (broadcast, ring fill,
      partial-gradient contraction, decode, update; parallel/step.py)
      navigable in a ``--trace-dir`` Perfetto/TensorBoard device capture.
    - ``jax.profiler.TraceAnnotation``: a host-timeline span for eager
      regions (the measured-arrival trainer's per-worker dispatches).

    Safe under jit (tests/test_tracing.py pins the round-trip) and always
    on: op names never change the compiled math, so annotating
    unconditionally keeps telemetry-on and -off lowerings identical.
    """
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield


class StepTimer:
    """Host-side wall-clock accumulator for non-scan paths.

    The in-scan training path times itself (trainer.py); this helper is for
    ad-hoc loops (eval sweeps, data prep) where the reference would have
    sprinkled time.time() pairs (src/naive.py:85,95)."""

    def __init__(self):
        self.laps: list[float] = []
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.laps.append(time.perf_counter() - self._t0)
        self._t0 = None
        return False

    @property
    def total(self) -> float:
        return sum(self.laps)

    @property
    def mean(self) -> float:
        return self.total / len(self.laps) if self.laps else 0.0
