"""Typed run configuration replacing the reference's three ad-hoc config tiers.

The reference configures a run through (a) 13 positional CLI args
(main.py:20-27), (b) hyperparameters hardcoded in source with per-dataset
variants left as comments (main.py:31-46), and (c) launcher variable blocks
(Makefile:1-20, run_approx_coding.sh:1-36). This module folds all three into
one dataclass with per-dataset presets.
"""

from __future__ import annotations

import dataclasses
import enum
import os
from typing import Optional, Sequence

import numpy as np


class Scheme(str, enum.Enum):
    """The seven collection/coding strategies of the reference (SURVEY.md
    §2.1) plus the two beyond-reference builtins.

    The enum is the BUILTIN subset of the scheme registry
    (erasurehead_tpu/schemes/): behavior — layout builder, collection
    rule, capability flags — lives in each scheme's SchemeDescriptor, and
    third-party schemes registered via the ``erasurehead_tpu.schemes``
    entry-point group are equally valid ``RunConfig.scheme`` values (they
    resolve to :class:`ExtensionScheme` tags instead of enum members).
    """

    NAIVE = "naive"  # wait for all workers               (src/naive.py)
    CYCLIC_MDS = "cyccoded"  # exact coding, cyclic MDS code      (src/coded.py)
    FRC = "repcoded"  # exact coding, fractional repetition (src/replication.py)
    APPROX = "approx"  # approximate gradient coding (AGC)  (src/approximate_coding.py)
    AVOID_STRAGGLERS = "avoidstragg"  # ignore-stragglers baseline (src/avoidstragg.py)
    PARTIAL_CYCLIC = "partialcyccoded"  # two-part coded   (src/partial_coded.py)
    PARTIAL_FRC = "partialrepcoded"  # two-part replicated (src/partial_replication.py)
    # beyond the reference: sparse random-graph AGC with optimal (lstsq)
    # decoding — arXiv 1711.06771 + 2006.09638 (PAPERS.md); same s+1
    # storage overhead as FRC/cyclic, lower erasure error at equal budget
    RANDOM_REGULAR = "randreg"
    # beyond the reference: deadline-based collection — the master takes
    # whatever arrived by a fixed per-round deadline and rescales for
    # unbiasedness; inherently failure-tolerant (a dead worker just never
    # arrives) and the practical form async-SGD systems deploy
    DEADLINE = "deadline"
    # beyond the reference: sparse random BIPARTITE-graph code (arXiv
    # 1711.06771's random-graph family next to randreg's d-regular form):
    # each partition lands on exactly s+1 uniformly-drawn workers, worker
    # loads ragged; first-k collection with lstsq-optimal decoding
    SPARSE_GRAPH = "sparsegraph"
    # beyond the reference: deterministic circulant expander-style code
    # (arXiv 1707.03858's cyclic/expander constructions): worker w holds
    # partitions w + floor(j*W/(s+1)) mod W — evenly spread chords, one
    # seed-independent layout; first-k collection with lstsq decoding
    EXPANDER = "expander"


class ExtensionScheme(str):
    """A registry-registered scheme name outside the builtin enum.

    Quacks like a :class:`Scheme` member everywhere the framework reads
    one — ``.value`` returns the name, string equality/hashing follow the
    name — so third-party schemes flow through configs, cache keys, event
    payloads and journal hashes without special-casing. Constructed only
    by :func:`as_scheme` after a registry membership check."""

    __slots__ = ()

    @property
    def value(self) -> str:
        return str(self)

    def __repr__(self) -> str:  # mirrors the enum's debugging shape
        return f"<ExtensionScheme {str(self)!r}>"


def as_scheme(name) -> "Scheme | ExtensionScheme":
    """Resolve a scheme value: builtin names map to :class:`Scheme`
    members, registry-registered third-party names to
    :class:`ExtensionScheme` tags; anything else raises a ValueError
    naming the registered schemes (builtins AND entry-point extensions —
    the registry is the single source of the valid set)."""
    if isinstance(name, (Scheme, ExtensionScheme)):
        return name
    try:
        return Scheme(name)
    except ValueError:
        pass
    from erasurehead_tpu import schemes

    if schemes.is_registered(str(name)):
        return ExtensionScheme(name)
    raise ValueError(
        f"unknown scheme {name!r}; registered schemes: {schemes.names()}"
    )


class PipelineRefusal(ValueError):
    """Typed refusal for scheme x mode combinations where bounded-staleness
    pipelining (``pipeline_depth=1``) is unsound or unproven.

    A ``ValueError`` subclass so every existing feasibility filter — the
    what-if enumerator's infeasible-point recording, serve admission's
    config rejection, the CLI's error path — classifies it exactly like any
    other config refusal, while callers that care WHY (the refusal matrix
    in README/MIGRATION) can catch the specific type and read ``reason``.
    """

    def __init__(self, reason: str, message: str):
        #: machine-readable refusal tag ("exact_decode", "agd_momentum",
        #: "measured_arrivals", ...) — stable across message rewording
        self.reason = reason
        super().__init__(message)


class UpdateRule(str, enum.Enum):
    GD = "GD"
    AGD = "AGD"  # Nesterov-style accelerated GD (src/naive.py:116-122)
    # beyond the reference (GD/AGD are its only rules): Adam on the mean
    # gradient + l2, for the MLP stretch family
    ADAM = "ADAM"


class ModelKind(str, enum.Enum):
    LOGISTIC = "logistic"
    LINEAR = "linear"
    MLP = "mlp"  # 2-layer MLP stretch config (BASELINE.json configs[4])
    ATTENTION = "attention"  # single-block attention classifier (models/attention.py)
    DEEPMLP = "deepmlp"  # n-layer MLP, the pipeline-parallel family (models/deep_mlp.py)
    MOE = "moe"  # mixture-of-experts classifier, the expert-parallel family (models/moe.py)


class ComputeMode(str, enum.Enum):
    """How worker messages are materialized on the mesh.

    FAITHFUL replicates the reference's cost model: every worker (chip shard)
    computes the gradient of each of its (possibly redundant) partitions, so
    coded schemes really do (s+1)x the FLOPs, like the reference cluster did.

    DEDUPED computes each partition gradient exactly once and folds the
    decode x coding coefficients into per-partition weights
    (CodingLayout.fold_slot_weights) — numerically identical decoded gradient
    at 1/(s+1) the FLOPs. This mode has no reference counterpart; it exists
    because on a lockstep SPMD machine redundant compute buys nothing unless
    you are modeling per-chip failures.
    """

    FAITHFUL = "faithful"
    DEDUPED = "deduped"


# Learning-rate schedules the reference keeps in comments (main.py:36-46).
def constant_schedule(value: float, rounds: int) -> np.ndarray:
    return value * np.ones(rounds)


def inverse_time_schedule(eta0: float, t0: float, rounds: int) -> np.ndarray:
    return np.array([eta0 * t0 / (i + t0) for i in range(1, rounds + 1)])


def exponential_decay_schedule(eta0: float, decay: float, rounds: int) -> np.ndarray:
    return np.array([eta0 * decay**i for i in range(1, rounds + 1)])


#: Per-dataset presets recorded in the reference (main.py:36-46 for the lr
#: schedules; run_approx_coding.sh:26-36 for shapes).
DATASET_PRESETS = {
    "amazon": dict(lr=("constant", 10.0), n_rows=26210, n_cols=241915, model=ModelKind.LOGISTIC),
    "covtype": dict(lr=("constant", 0.1), n_rows=396112, n_cols=15509, model=ModelKind.LOGISTIC),
    "kc_house_data": dict(lr=("exp", 0.1, 0.98), n_rows=17290, n_cols=27654, model=ModelKind.LINEAR),
    "dna": dict(lr=("constant", 0.1), n_rows=400000, n_cols=6890, model=ModelKind.LOGISTIC),
    "artificial": dict(lr=("constant", 10.0), n_rows=4096, n_cols=100, model=ModelKind.LOGISTIC),
}
# the reference's on-disk directory names for the same datasets
# (arrange_real_data.py:34,93): accepted everywhere a dataset name is
DATASET_PRESETS["amazon-dataset"] = DATASET_PRESETS["amazon"]
DATASET_PRESETS["dna-dataset"] = DATASET_PRESETS["dna"]


@dataclasses.dataclass
class RunConfig:
    """Everything needed to reproduce one training run.

    Mirrors main.py's 13 positional args plus the hardcoded hyperparameters,
    with the reference's implicit conventions made explicit.
    """

    scheme: Scheme = Scheme.NAIVE
    model: ModelKind = ModelKind.LOGISTIC
    n_workers: int = 8  # reference: n_procs - 1 (the master is rank 0)
    n_stragglers: int = 1
    rounds: int = 100  # num_itrs, main.py:32
    num_collect: Optional[int] = None  # AGC stop count; None => n_workers
    add_delay: bool = True  # inject the seeded exponential straggler delays
    delay_mean: float = 0.5  # seconds; src/naive.py:146
    # heterogeneous-cluster arrival model (straggler.ArrivalModel): a base
    # per-round compute time and a seeded uniform per-worker speed spread
    # in [1-s, 1+s] multiplying it. 0/0 = the reference's pure-delay regime.
    compute_time: float = 0.0
    worker_speed_spread: float = 0.0
    update_rule: UpdateRule = UpdateRule.AGD
    alpha: Optional[float] = None  # l2 coeff; None => 1/n_samples (main.py:34)
    lr_schedule: Optional[Sequence[float]] = None  # None => dataset preset
    dataset: str = "artificial"
    n_rows: int = 4096
    n_cols: int = 100
    input_dir: Optional[str] = None  # on-disk data; None => generate in-memory
    is_real_data: bool = False
    partitions_per_worker: int = 0  # >0 selects partial schemes' slot count
    compute_mode: ComputeMode = ComputeMode.FAITHFUL
    # how FAITHFUL mode materializes its (s+1)x-redundant worker stack:
    #   "materialized" — the worker-major [W, S, rows, F] stack is real HBM
    #                    (the redundancy is real memory, as it was real
    #                    disk+RAM in the reference);
    #   "ring"         — only the partition-major [P, rows, F] stack is
    #                    resident; each device rebuilds its workers' slot
    #                    buffer per step over lax.ppermute ring hops
    #                    (data/sharding.plan_ring_transport,
    #                    parallel/step.make_ring_faithful_grad_fn) —
    #                    bitwise-identical trajectories, (s+1)x less
    #                    device data;
    #   "auto"         — ring once the materialized stack's footprint
    #                    estimate crosses sharding.RING_AUTO_MIN_BYTES.
    # Deduped mode has no redundancy to stream and ignores/refuses it.
    stack_mode: str = "materialized"
    # ring-transport scheduling (parallel/step._ring_fill): how the per-step
    # ppermute hops interleave with the slot-buffer fills under
    # stack_mode="ring"/"auto"->ring:
    #   "off"  — sequential: each hop's fill consumes that hop's transfer,
    #            serializing ICI behind compute (the original transport);
    #   "on"   — double-buffered: the hop t+1 ppermute is issued in the
    #            scan carry while hop t's block fills, so XLA can overlap
    #            the transfer with the fill. Same hop count, same bytes,
    #            same fill order — trajectories are BITWISE identical;
    #   "auto" — step.RING_PIPELINE_DEFAULT (off pending the
    #            dense_f32_ringpipe race).
    # Ignored (harmless) when the run doesn't resolve to ring transport.
    ring_pipeline: str = "auto"
    # feature-stack STORAGE dtype (data/sharding.shard_run_data):
    #   "auto"     — follow `dtype` (today's behavior);
    #   "float32"/"bfloat16" — force the stored float dtype; for the
    #            training stacks this is equivalent to setting `dtype`
    #            (labels ride along), kept explicit so sweeps can tag
    #            the stack lever independently;
    #   "int8"     — quantize the partition-major stack at upload to an
    #            int8 payload + per-partition-per-feature f32 scale table
    #            (ops/features.QuantizedStack), dequantized inside the
    #            per-device grad body — ~4x fewer streamed bytes on the
    #            bandwidth-bound pass, LOSSY (fidelity measured, not
    #            assumed: bench.py fidelity extra, decode-error columns).
    #            Dense stacks only; composes with stack_mode=ring and the
    #            cohort dispatch.
    stack_dtype: str = "auto"
    # partition-stack RESIDENCY (train/trainer.py + data/store.py):
    #   "resident" — the whole [P, rows, F] stack is device-resident
    #                before round 0 (today's behavior; HBM bounds the
    #                dataset);
    #   "streamed" — partitions live in an on-disk shard store
    #                (data/store.ShardStore; one is written to a temp dir
    #                when the dataset is in-memory) and only a window of
    #                them is device-resident at a time, double-buffered
    #                host→device by data/prefetch.Prefetcher. A window
    #                covering every partition takes the ordinary resident
    #                pipeline over the store's rows (bitwise-identical
    #                trajectories); a smaller window streams the deduped
    #                dense path window-per-scan-chunk;
    #   "auto"     — streamed exactly when a stream budget is armed
    #                (ERASUREHEAD_STREAM_WINDOW), else resident.
    stack_residency: str = "resident"
    # partitions per streamed window (stack_residency="streamed"/auto):
    # None resolves from the ERASUREHEAD_STREAM_WINDOW byte budget
    # (utils/config.resolve_stream_budget; two windows in flight), else
    # to the full partition count (the bitwise single-window path).
    stream_window: Optional[int] = None
    # buffer donation (jax donate_argnums) for the training scan's carry
    # (params + optimizer state) and per-round weight tables: the donated
    # HBM is reused in place instead of held as a duplicate across the
    # dispatch. "auto" = on (trainer.DONATE_DEFAULT — bitwise-identical
    # math; the cached device DATA stacks are never donated, test-pinned
    # in tests/test_donation.py); "off" for debugging / before-after
    # measurement.
    donate: str = "auto"
    seed: int = 0  # model init + generator matrix (reference: unseeded)
    # DATA dtype: bfloat16 halves HBM traffic on the gradient pass; model
    # params and optimizer updates always run in float32 (mixed precision)
    dtype: str = "float32"
    # fused pallas gradient kernel (ops/kernels.py): "on" forces it
    # (interpret mode off-TPU), "off" disables, "auto" lets
    # kernels.supports_fused decide per platform/model/shape
    use_pallas: str = "auto"
    # "simulated": the default precomputed-schedule scan trainer.
    # "measured": time each worker's real gradient compute per round and
    # feed those arrivals to the collection rule (trainer.train_measured —
    # worker_timeset becomes a measurement, like src/naive.py:106).
    arrival_mode: str = "simulated"
    # Sparse margin-gather lane width (ops/features.set_sparse_lanes):
    # None = scalar lowering; a power of two widens each margin lookup
    # (PaddedRows value gather, or FieldOnehot pair-table gather) to an
    # L-lane row — the TPU workaround for ~7ns/element scalar gathers.
    sparse_lanes: Optional[int] = None
    # dense margin-matvec lowering width (ops/features.set_dense_margin_cols):
    # None = direct matvec; C in [2,128] replicates beta to [F, C] behind a
    # barrier so the margin lowers as a tileable matmul (the profile_dense
    # margin_cols candidate for the measured cross-lane-reduction bound)
    dense_margin_cols: Optional[int] = None
    # flat-stack closed-form GLM gradient (parallel/step.make_flat_grad_fn):
    # flattens the [slots, rows, F] stack so the margin lowers as one 2-D
    # matmul and the decode weights fold into the residual. "on" forces it
    # (errors off the closed-form dense path), "off" keeps the per-slot
    # vmap, "auto" resolves per stack kind (step.resolve_flat_grad):
    # flat for FieldOnehot (per-slot measured catastrophic on v5e), else
    # step.FLAT_GRAD_DEFAULT pending the dense/PaddedRows races.
    flat_grad: str = "auto"
    # hybrid dense margin lowering (parallel/step._hybrid_margin_flat_grad):
    # the margin as one flat 2-D matmul (the measured margin winner) while
    # the transpose stays the batched per-slot contraction (the measured
    # transpose winner). "auto" resolves to step.MARGIN_FLAT_DEFAULT
    # pending the dense_f32_marginflat race; closed-form dense GLMs only.
    margin_flat: str = "auto"
    # per-layer (blockwise) gradient coding (parallel/step.
    # make_layer_block_grad_fn): code each layer's flattened gradient
    # block independently against the same layout matrix, so decode is a
    # batched [k,P]x[P,block] einsum per block (ops/blocks.py) instead of
    # a per-leaf gather-and-combine over the full pytree. DeepMLP layers
    # and MoE expert shards are individual coded blocks
    # (model.block_split_leaves). Bitwise-identical decode to the
    # treewise form (tests/test_deep_coding.py) — a pure lowering knob.
    # "on" forces it (errors where unsupported: forced pallas/flat
    # lowerings, model-internal mesh axes, measured mode); "auto"
    # resolves via step.LAYER_CODING_DEFAULT (off pending its race).
    layer_coding: str = "auto"
    # blockwise-decode LOWERING inside layer coding (parallel/step.
    # resolve_block_decode): "treewise" packs every slot's grad pytree
    # into the padded [M, L, width] block table and einsum-decodes it;
    # "fused" contracts each leaf's [M, D_leaf] slot view directly
    # (ops/kernels.fused_block_decode — no materialized grad table, the
    # PR 9 0.57x cause). Bitwise-identical outputs (tests/
    # test_deep_coding.py) — a pure lowering knob. "auto" resolves
    # env ERASUREHEAD_BLOCK_DECODE > cached tune decision
    # (erasurehead_tpu/tune/) > step.BLOCK_DECODE_FUSED_DEFAULT. Inert
    # unless the run decodes blockwise.
    block_decode: str = "auto"
    # hidden-layer count for the deepmlp family (models/deep_mlp.py);
    # 0 = the model's default (4). The decode-error-vs-depth series
    # sweeps this knob (bench.py deep_cohort extra).
    deep_layers: int = 0
    # replay a recorded per-round arrival-time trace instead of drawing
    # i.i.d. exponential delays (parallel/straggler.load_arrival_trace:
    # .npy/.npz/.csv/.txt, shape [R?, W], tiled over rounds). CLI
    # --arrival-trace; ERASUREHEAD_ARRIVAL_TRACE overrides when unset.
    # cfg.worker_speed_spread composes as a per-worker multiplier ON the
    # trace rows (heterogeneous replay); simulated-arrival trainer only.
    arrival_trace: Optional[str] = None
    # per-round collection deadline in simulated seconds (scheme="deadline")
    deadline: Optional[float] = None
    # decode-weight policy (schemes registry / arXiv:2006.09638):
    #   "fixed"   — the scheme's own collection weights (the reference's
    #               behavior; bitwise-unchanged default);
    #   "optimal" — per-round least-squares weights refit to the ACTUAL
    #               arrival set over the layout's effective coding matrix
    #               (a tiny host-side [k, P] solve, batchable across a
    #               cohort). On exact schemes the refit reproduces zero
    #               decode error; on approximate schemes it is the
    #               minimum-weight-space-error decode (obs/decode.py
    #               proves the per-round improvement). Host control plane
    #               only: train_dynamic refuses it (weights live on
    #               device there). Schemes without an optimal_decode hook
    #               (partial two-part layouts) keep their fixed weights.
    decode: str = "fixed"
    # lax.scan unroll factor for the training scans (train/train_dynamic):
    # >1 lets XLA fuse and overlap consecutive rounds, amortizing the
    # per-iteration scan overhead the in-scan bandwidth probes showed
    # (BASELINE.md round-3 window 2: 126 GB/s in-scan vs 819 peak).
    # Identical math at any value (scan semantics); a lowering knob like
    # dtype/flat_grad — raced on silicon before becoming a default.
    scan_unroll: int = 1
    # bounded-staleness pipelined training (parallel/pipeline.py): 0 keeps
    # the strictly synchronous round barrier (bitwise today's trainer); 1
    # dispatches round t+1's worker compute against params from round t-1
    # while round t's arrivals drain (staleness tau=1). The trainer's scan
    # carry grows a second params slot; the collection schedule becomes the
    # deterministic pipelined recurrence over the SAME drawn arrival matrix
    # (journal/replay identity is preserved — the staleness schedule is a
    # pure function of the run signature). Refused (PipelineRefusal) on
    # exact-decode schemes (staleness breaks the exactness contract), AGD
    # (momentum unproven under tau=1), and measured arrivals.
    pipeline_depth: int = 0
    # sequence-parallel shards for the attention family: >1 builds a 2-D
    # (workers, seq) mesh; each row's token axis splits over seq and
    # attention spans it (parallel/ring.py, models/attention._predict_seq)
    seq_shards: int = 1
    # which canonical SP form carries the attention: "ring" (ppermute ring,
    # long-T friendly) or "ulysses" (two all_to_alls, head-sharded; needs
    # n_heads divisible by seq_shards)
    sp_form: str = "ring"
    # tensor-parallel shards for the MLP family: >1 builds a 2-D
    # (workers, model) mesh; the hidden dimension splits over the model
    # axis (Megatron column/row split, models/mlp._predict_tp)
    tp_shards: int = 1
    # pipeline-parallel stages for the deepmlp family: >1 builds a 2-D
    # (workers, pipe) mesh; layers split contiguously across stages and a
    # GPipe microbatch schedule streams the rows through them
    # (models/deep_mlp._predict_pp)
    pp_shards: int = 1
    # expert-parallel shards for the moe family: >1 builds a 2-D
    # (workers, expert) mesh; experts split contiguously across it
    # (models/moe._predict_ep)
    ep_shards: int = 1
    # sparse training-stack representation (ops/features.py):
    #   "padded" — generic PaddedRows gather/scatter (default);
    #   "fields" — FieldOnehot fused pair-table lowering (requires
    #              exactly-one-hot-per-field data; errors otherwise);
    #   "auto"   — FieldOnehot when the data's structure allows, else padded.
    sparse_format: str = "padded"
    # FieldOnehot gradient-scatter lowering (ops/features.set_fields_scatter):
    #   "pairs"  — scatter-add into fused pair accumulators (default);
    #   "onehot" — segment-sum as per-field one-hot MXU matmuls, the
    #              candidate attacking the serialized scatter-add bound.
    fields_scatter: str = "pairs"
    # FieldOnehot margin lowering (ops/features.set_fields_margin):
    #   "tables" — fused pair-table gathers (default; composes with
    #              sparse_lanes);
    #   "onehot" — per-field one-hot MXU matmuls (no gathers at all;
    #              sparse_lanes is ignored in this mode).
    fields_margin: str = "tables"

    @classmethod
    def for_dataset(cls, dataset: str, **overrides) -> "RunConfig":
        """Build a config with the dataset preset's shape and model applied."""
        preset = DATASET_PRESETS[dataset]
        base = dict(
            dataset=dataset,
            n_rows=preset["n_rows"],
            n_cols=preset["n_cols"],
            model=preset["model"],
        )
        base.update(overrides)
        return cls(**base)

    def __post_init__(self):
        self.scheme = as_scheme(self.scheme)
        self.model = ModelKind(self.model)
        self.update_rule = UpdateRule(self.update_rule)
        self.compute_mode = ComputeMode(self.compute_mode)
        if self.use_pallas not in ("auto", "on", "off"):
            raise ValueError(
                f"use_pallas must be auto/on/off, got {self.use_pallas!r}"
            )
        if self.flat_grad not in ("auto", "on", "off"):
            raise ValueError(
                f"flat_grad must be auto/on/off, got {self.flat_grad!r}"
            )
        if self.layer_coding not in ("auto", "on", "off"):
            raise ValueError(
                f"layer_coding must be auto/on/off, got {self.layer_coding!r}"
            )
        if self.layer_coding == "on":
            for knob, name in (
                (self.flat_grad, "flat_grad"),
                (self.margin_flat, "margin_flat"),
                (self.use_pallas, "use_pallas"),
            ):
                if knob == "on":
                    raise ValueError(
                        f"layer_coding='on' and {name}='on' both force a "
                        "gradient lowering; force at most one"
                    )
            if self.arrival_mode == "measured":
                raise ValueError(
                    "arrival_mode='measured' decodes each worker's own "
                    "timed message through the per-slot tree contraction; "
                    "the blockwise decode only exists inside the SPMD "
                    "step — use layer_coding='auto' or 'off' with "
                    "measured mode"
                )
        if self.block_decode not in ("auto", "fused", "treewise"):
            raise ValueError(
                f"block_decode must be auto/fused/treewise, got "
                f"{self.block_decode!r}"
            )
        if self.deep_layers < 0:
            raise ValueError(
                f"deep_layers must be >= 0, got {self.deep_layers}"
            )
        if self.arrival_trace is not None and self.arrival_mode != "simulated":
            raise ValueError(
                "arrival_trace replays a recorded schedule through the "
                "simulated-arrival trainer; arrival_mode='measured' times "
                "real arrivals — drop one of the two"
            )
        if self.scan_unroll < 1:
            raise ValueError(
                f"scan_unroll must be >= 1, got {self.scan_unroll}"
            )
        if self.arrival_mode not in ("simulated", "measured"):
            raise ValueError(
                f"arrival_mode must be simulated/measured, got "
                f"{self.arrival_mode!r}"
            )
        if self.stack_mode not in ("materialized", "ring", "auto"):
            raise ValueError(
                f"stack_mode must be materialized/ring/auto, got "
                f"{self.stack_mode!r}"
            )
        if self.ring_pipeline not in ("auto", "on", "off"):
            raise ValueError(
                f"ring_pipeline must be auto/on/off, got "
                f"{self.ring_pipeline!r}"
            )
        if self.stack_dtype not in ("auto", "float32", "bfloat16", "int8"):
            raise ValueError(
                f"stack_dtype must be auto/float32/bfloat16/int8, got "
                f"{self.stack_dtype!r}"
            )
        if self.donate not in ("auto", "on", "off"):
            raise ValueError(
                f"donate must be auto/on/off, got {self.donate!r}"
            )
        if self.stack_dtype == "int8":
            if self.arrival_mode == "measured":
                raise ValueError(
                    "arrival_mode='measured' dispatches each worker's own "
                    "grad_sum on its resident slot stack; the int8 "
                    "compressed stack only dequantizes inside the SPMD "
                    "step body — use stack_dtype float32/bfloat16 (or "
                    "auto) with measured mode"
                )
            if self.use_pallas == "on":
                raise ValueError(
                    "use_pallas='on' forces the fused kernel, which "
                    "streams a plain dense float stack and has no "
                    "dequantizing body; force at most one of "
                    "stack_dtype='int8' / use_pallas='on'"
                )
        if self.stack_mode == "ring":
            if self.compute_mode != ComputeMode.FAITHFUL:
                raise ValueError(
                    "stack_mode='ring' streams the faithful mode's "
                    "redundant worker stack; deduped mode has no "
                    "redundancy to stream — drop one of the two"
                )
            if self.arrival_mode == "measured":
                raise ValueError(
                    "arrival_mode='measured' times each worker's own "
                    "resident slot stack per dispatch; the ring transport "
                    "only exists inside the SPMD step — use "
                    "stack_mode='materialized' (or 'auto') with measured "
                    "mode"
                )
            if self.use_pallas == "on":
                raise ValueError(
                    "use_pallas='on' forces the fused kernel, which has no "
                    "ring-transport body; force at most one of "
                    "stack_mode='ring' / use_pallas='on'"
                )
        if self.stack_residency not in ("resident", "streamed", "auto"):
            raise ValueError(
                f"stack_residency must be resident/streamed/auto, got "
                f"{self.stack_residency!r}"
            )
        if self.stack_residency == "streamed":
            if self.arrival_mode == "measured":
                raise ValueError(
                    "arrival_mode='measured' dispatches per-worker on "
                    "resident slot stacks; the streamed window only "
                    "exists in the simulated-arrival scan trainer — use "
                    "stack_residency='resident' (or 'auto') with "
                    "measured mode"
                )
        if self.stream_window is not None:
            if self.stack_residency == "resident":
                raise ValueError(
                    "stream_window sizes the streamed partition window; "
                    "it has no effect under stack_residency='resident' — "
                    "drop it or set stack_residency='streamed'/'auto'"
                )
            if self.stream_window < 1:
                raise ValueError(
                    f"stream_window must be >= 1, got {self.stream_window}"
                )
        from erasurehead_tpu.ops.features import validate_lanes

        self.sparse_lanes = validate_lanes(self.sparse_lanes)
        from erasurehead_tpu.ops.features import validate_margin_cols

        self.dense_margin_cols = validate_margin_cols(self.dense_margin_cols)
        if self.seq_shards < 1:
            raise ValueError(f"seq_shards must be >= 1, got {self.seq_shards}")
        axes_over_one = sum(
            v > 1
            for v in (
                self.seq_shards, self.tp_shards, self.pp_shards,
                self.ep_shards,
            )
        )
        if axes_over_one > 1:
            raise ValueError(
                "at most one of seq_shards/tp_shards/pp_shards/ep_shards "
                "may exceed 1 (each belongs to a different model family)"
            )
        if self.sp_form not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_form must be ring/ulysses, got {self.sp_form!r}"
            )
        if self.seq_shards > 1:
            if self.model != ModelKind.ATTENTION:
                raise ValueError(
                    "seq_shards > 1 requires model='attention' (the only "
                    "family with a sequence axis to shard)"
                )
            if self.arrival_mode != "simulated":
                raise ValueError(
                    "seq_shards > 1 runs under the simulated-arrival "
                    "trainer only (measured mode dispatches per-worker on "
                    "single devices)"
                )
        if self.tp_shards < 1:
            raise ValueError(f"tp_shards must be >= 1, got {self.tp_shards}")
        if self.tp_shards > 1:
            if self.model != ModelKind.MLP:
                raise ValueError(
                    "tp_shards > 1 requires model='mlp' (the only family "
                    "with a hidden dimension to split)"
                )
            if self.arrival_mode != "simulated":
                raise ValueError(
                    "tp_shards > 1 runs under the simulated-arrival "
                    "trainer only"
                )
        if self.pp_shards < 1:
            raise ValueError(f"pp_shards must be >= 1, got {self.pp_shards}")
        if self.pp_shards > 1:
            if self.model != ModelKind.DEEPMLP:
                raise ValueError(
                    "pp_shards > 1 requires model='deepmlp' (the only "
                    "family with a layer pipeline)"
                )
            if self.arrival_mode != "simulated":
                raise ValueError(
                    "pp_shards > 1 runs under the simulated-arrival "
                    "trainer only"
                )
        if self.ep_shards < 1:
            raise ValueError(f"ep_shards must be >= 1, got {self.ep_shards}")
        if self.ep_shards > 1:
            if self.model != ModelKind.MOE:
                raise ValueError(
                    "ep_shards > 1 requires model='moe' (the only family "
                    "with experts to shard)"
                )
            if self.arrival_mode != "simulated":
                raise ValueError(
                    "ep_shards > 1 runs under the simulated-arrival "
                    "trainer only"
                )
        if self.sparse_format not in ("padded", "fields", "auto"):
            raise ValueError(
                f"sparse_format must be padded/fields/auto, got "
                f"{self.sparse_format!r}"
            )
        if self.fields_scatter not in ("pairs", "onehot"):
            raise ValueError(
                f"fields_scatter must be pairs/onehot, got "
                f"{self.fields_scatter!r}"
            )
        if self.margin_flat not in ("auto", "on", "off"):
            raise ValueError(
                f"margin_flat must be auto/on/off, got {self.margin_flat!r}"
            )
        if self.margin_flat == "on" and self.flat_grad == "on":
            raise ValueError(
                "margin_flat='on' and flat_grad='on' both force a margin "
                "lowering; force at most one"
            )
        if self.margin_flat == "on" and self.use_pallas == "on":
            raise ValueError(
                "margin_flat='on' and use_pallas='on' both force a grad "
                "lowering; force at most one"
            )
        if self.fields_margin not in ("tables", "onehot"):
            raise ValueError(
                f"fields_margin must be tables/onehot, got "
                f"{self.fields_margin!r}"
            )
        if (
            self.sparse_format == "fields"
            and self.fields_margin == "onehot"
            and self.sparse_lanes is not None
        ):
            # the onehot margin has no gather to widen — accepting lanes
            # here would silently ignore them and misattribute any
            # lane-width measurement (same rule as auto-format pinning)
            raise ValueError(
                "sparse_lanes has no effect under fields_margin='onehot' "
                "(no gathers to lane-replicate); drop one of the two"
            )
        if self.sparse_format == "auto" and self.sparse_lanes is not None:
            # an explicit lane request pins the PaddedRows lowering so the
            # historical lane measurements stay attributed to it; the
            # composed fields x lanes lowering must be asked for explicitly
            # (sparse_format="fields") until its race flips this default
            self.sparse_format = "padded"
        if self.decode not in ("fixed", "optimal"):
            raise ValueError(
                f"decode must be fixed/optimal, got {self.decode!r}"
            )
        if self.pipeline_depth not in (0, 1):
            raise ValueError(
                f"pipeline_depth must be 0 (synchronous) or 1 (bounded "
                f"staleness tau=1), got {self.pipeline_depth}"
            )
        if self.pipeline_depth:
            if self.update_rule != UpdateRule.GD:
                raise PipelineRefusal(
                    "momentum_unproven",
                    f"pipeline_depth=1 refuses update_rule="
                    f"{self.update_rule.value!r}: the momentum/adaptive "
                    "update's stability under a tau=1 stale gradient is "
                    "unproven here — use update_rule='GD' with pipelining",
                )
            if self.arrival_mode == "measured":
                raise PipelineRefusal(
                    "measured_arrivals",
                    "pipeline_depth=1 refuses arrival_mode='measured': the "
                    "measured trainer times real per-worker dispatches "
                    "round by round, and overlapping rounds would make the "
                    "measurement racy instead of stale — use the simulated-"
                    "arrival trainer with pipelining",
                )
        if self.num_collect is None:
            self.num_collect = self.n_workers
        if self.dataset not in DATASET_PRESETS:
            raise ValueError(
                f"unknown dataset {self.dataset!r}; known: {sorted(DATASET_PRESETS)}"
            )
        # scheme-specific invariants (partial partition counts, positive
        # deadlines, third-party knobs) live on the scheme's registry
        # descriptor, not in an if/elif spine here
        from erasurehead_tpu import schemes

        schemes.get(self.scheme).validate(self)

    def static_signature_fields(self) -> dict:
        """LABELED form of :meth:`static_signature`: field name -> value.

        The names feed the recompile detector (obs/detect.py), which
        diffs executable-cache miss signatures against prior in-process
        compiles and must be able to NAME the knob that differed ("dtype
        changed", not "element 4 changed"). :meth:`static_signature`
        derives from this dict so the two can never drift."""
        return {
            "model": self.model.value,
            "compute_mode": self.compute_mode.value,
            # the RESOLVED ring choice also enters the trainer-side key
            # (auto depends on a footprint estimate cfg alone cannot see);
            # the raw knob here keeps explicit/auto requests distinct
            "stack_mode": self.stack_mode,
            # memory-system knobs (PR 6): the raw knobs here name the
            # differing field in recompile-detector warnings; the trainer
            # keys the RESOLVED values too (ring signature carries the
            # pipeline schedule, data_tree carries the stack dtype, and
            # the donation field carries the resolved aliasing)
            "ring_pipeline": self.ring_pipeline,
            "stack_dtype": self.stack_dtype,
            # residency changes the compiled step only below a full
            # window, but keying the raw knobs keeps streamed and
            # resident dispatches (and their cohort signatures —
            # serve/packer packs by this) distinct by construction
            "stack_residency": self.stack_residency,
            "stream_window": self.stream_window,
            "donate": self.donate,
            "update_rule": self.update_rule.value,
            "dtype": self.dtype,
            "scan_unroll": self.scan_unroll,
            # the staleness slot restructures the scan carry (two params
            # slots), so tau=0 and tau=1 dispatches can never share an
            # executable — and the recompile detector names the knob
            "pipeline_depth": self.pipeline_depth,
            # features-module lowering knobs (scoped per run by
            # trainer._with_run_sparse_lanes; they retrace every jit)
            "sparse_lanes": self.sparse_lanes,
            "dense_margin_cols": self.dense_margin_cols,
            # per-layer coding + deepmlp depth both change the compiled
            # step (decode structure / layer count); the raw layer_coding
            # knob here names the field in recompile-detector warnings —
            # the trainer keys the RESOLVED choice via
            # step.lowering_signature
            "layer_coding": self.layer_coding,
            # blockwise-decode lowering fork (fused per-leaf kernel vs
            # treewise table einsum): raw knob here, RESOLVED choice in
            # step.lowering_signature — a tune decision-cache update
            # moves the resolved tuple, never a stale executable
            "block_decode": self.block_decode,
            "deep_layers": self.deep_layers,
            "sparse_format": self.sparse_format,
            "fields_scatter": self.fields_scatter,
            "fields_margin": self.fields_margin,
            # model-family internal axes (change for_mesh's model variant)
            "sp_form": self.sp_form,
            "seq_shards": self.seq_shards,
            "tp_shards": self.tp_shards,
            "pp_shards": self.pp_shards,
            "ep_shards": self.ep_shards,
        }

    def static_signature(self) -> tuple:
        """The config-derived half of the sweep-engine executable cache key
        (train/cache.py): every knob that changes the compiled scan's
        lowering but is NOT already captured by argument shapes/dtypes or
        by the trainer's resolved-lowering tuple. Per-round weight tables,
        the arrival schedule, and lr values are traced ARGUMENTS and
        deliberately absent — sharing the executable across them is the
        whole point. When adding a lowering knob to RunConfig, add it to
        :meth:`static_signature_fields` (this derives from it)."""
        return tuple(self.static_signature_fields().values())

    def resolve_stack_dtype(self) -> str:
        """The feature stack's RESOLVED storage dtype: "float32",
        "bfloat16", or "int8". ``stack_dtype="auto"`` follows the DATA
        dtype (the pre-knob behavior, so existing configs and cache keys
        are unchanged); explicit float values override it (labels ride
        along — equivalent to setting ``dtype``); "int8" quantizes the
        feature stack while labels keep the ``dtype`` cast."""
        if self.stack_dtype == "auto":
            return self.dtype
        return self.stack_dtype

    @property
    def effective_alpha(self) -> float:
        return self.alpha if self.alpha is not None else 1.0 / self.n_rows

    def resolve_lr_schedule(self) -> np.ndarray:
        if self.lr_schedule is not None:
            lr = np.asarray(self.lr_schedule, dtype=np.float64)
            if lr.ndim == 0:
                lr = np.full(self.rounds, float(lr))
            assert lr.shape == (self.rounds,)
            return lr
        preset = DATASET_PRESETS[self.dataset]
        kind, *args = preset["lr"]
        if kind == "constant":
            return constant_schedule(args[0], self.rounds)
        if kind == "inv":
            return inverse_time_schedule(args[0], args[1], self.rounds)
        if kind == "exp":
            return exponential_decay_schedule(args[0], args[1], self.rounds)
        raise ValueError(f"unknown lr schedule kind {kind!r}")


#: env var controlling the sweep engine's trajectory-batched dispatch when
#: the CLI flag is absent (same flag > env > default precedence as the
#: sweep cache and telemetry knobs)
BATCH_TRAJECTORIES_ENV = "ERASUREHEAD_BATCH_TRAJECTORIES"


def resolve_batch_trajectories(
    flag: Optional[str] = None, env: Optional[str] = None
) -> str:
    """Resolve the sweep engine's trajectory-batching mode to one of
    ``"on"`` / ``"off"`` / ``"auto"``.

    ``"auto"`` (the default) dispatches every cohort of >= 2 eligible
    trajectories through :func:`trainer.train_cohort` (one compiled scan
    per cohort) and runs singletons sequentially; ``"on"`` routes even
    singletons through the cohort engine; ``"off"`` forces strictly
    sequential :func:`trainer.train` calls (debugging; bitwise-reference
    trajectories). Precedence: explicit ``flag`` >
    :data:`BATCH_TRAJECTORIES_ENV` env var > ``"auto"``. ``env`` overrides
    the real environment lookup (tests).
    """
    val = flag
    if val is None:
        val = env if env is not None else os.environ.get(
            BATCH_TRAJECTORIES_ENV
        )
    if val is None or val == "":
        return "auto"
    val = str(val).strip().lower()
    if val in _TELEMETRY_ON:
        return "on"
    if val in _TELEMETRY_OFF:
        return "off"
    if val in ("on", "off", "auto"):
        return val
    raise ValueError(
        f"batch-trajectories setting must be on/off/auto (or a "
        f"truthy/falsy {BATCH_TRAJECTORIES_ENV} value), got {val!r}"
    )


#: env var selecting a recorded arrival-trace file
#: (parallel/straggler.load_arrival_trace) when the config/CLI flag is
#: absent — trainer.default_arrivals replays it instead of drawing
#: i.i.d. exponential delays
ARRIVAL_TRACE_ENV = "ERASUREHEAD_ARRIVAL_TRACE"


def resolve_arrival_trace(
    flag: Optional[str] = None, env: Optional[str] = None
) -> Optional[str]:
    """The arrival-trace path, or None (drawn delays). Precedence mirrors
    the other sweep knobs: explicit ``--arrival-trace``/cfg value >
    :data:`ARRIVAL_TRACE_ENV` env var > off. ``env`` overrides the real
    environment lookup (tests)."""
    val = flag
    if val is None:
        val = env if env is not None else os.environ.get(ARRIVAL_TRACE_ENV)
    return val or None


#: env var enabling the sweep journal (train/journal.py) when no journal
#: is passed explicitly: its value is the journal DIRECTORY
SWEEP_JOURNAL_ENV = "ERASUREHEAD_SWEEP_JOURNAL"

#: env var enabling resume-from-journal (skip already-completed
#: trajectories) when the CLI flag is absent
RESUME_SWEEP_ENV = "ERASUREHEAD_RESUME_SWEEP"


def resolve_sweep_journal(
    flag: Optional[str] = None, env: Optional[str] = None
) -> Optional[str]:
    """The sweep-journal directory, or None (journaling off).

    Precedence mirrors the other sweep knobs: explicit CLI ``--sweep-
    journal DIR`` flag > :data:`SWEEP_JOURNAL_ENV` env var > off. ``env``
    overrides the real environment lookup (tests)."""
    val = flag
    if val is None:
        val = env if env is not None else os.environ.get(SWEEP_JOURNAL_ENV)
    return val or None


def resolve_resume_sweep(
    flag: Optional[bool] = None, env: Optional[str] = None
) -> bool:
    """Should a journaled sweep SKIP trajectories its journal already
    completed? Explicit flag > :data:`RESUME_SWEEP_ENV` truthy/falsy env
    value > False (record-only). ``env`` overrides the real environment
    lookup (tests)."""
    if flag is not None:
        return bool(flag)
    val = env if env is not None else os.environ.get(RESUME_SWEEP_ENV)
    if val is None or val == "":
        return False
    val = str(val).strip().lower()
    if val in _TELEMETRY_ON:
        return True
    if val in _TELEMETRY_OFF:
        return False
    raise ValueError(
        f"{RESUME_SWEEP_ENV} must be truthy/falsy, got {val!r}"
    )


#: env var setting the serve daemon's in-flight HBM budget when the CLI
#: flag is absent (serve/admission.py); value is bytes with an optional
#: k/m/g/t suffix, e.g. "2g". Unset = unbounded admission.
SERVE_BUDGET_ENV = "ERASUREHEAD_SERVE_BUDGET"

#: env var capping how many trajectories one packed serve dispatch may
#: carry when the CLI flag is absent (serve/packer.py)
SERVE_MAX_COHORT_ENV = "ERASUREHEAD_SERVE_MAX_COHORT"

_BYTE_SUFFIXES = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_bytes(val) -> int:
    """"2g" / "512m" / "1048576" -> bytes (suffixes are binary powers)."""
    s = str(val).strip().lower()
    mult = 1
    if s and s[-1] in _BYTE_SUFFIXES:
        mult = _BYTE_SUFFIXES[s[-1]]
        s = s[:-1]
    try:
        n = int(float(s) * mult)
    except ValueError:
        raise ValueError(
            f"byte size must be an integer with an optional k/m/g/t "
            f"suffix, got {val!r}"
        ) from None
    if n <= 0:
        raise ValueError(f"byte size must be positive, got {val!r}")
    return n


def resolve_serve_budget(
    flag: Optional[str] = None, env: Optional[str] = None
) -> Optional[int]:
    """The serve admission budget in bytes, or None (unbounded).
    Precedence mirrors the other serve knobs: explicit CLI ``--budget``
    flag > :data:`SERVE_BUDGET_ENV` env var > unbounded. ``env`` overrides
    the real environment lookup (tests)."""
    val = flag
    if val is None:
        val = env if env is not None else os.environ.get(SERVE_BUDGET_ENV)
    if val is None or val == "":
        return None
    return parse_bytes(val)


def resolve_serve_max_cohort(
    flag: Optional[int] = None, env: Optional[str] = None, default: int = 64
) -> int:
    """Max trajectories per packed serve dispatch. Explicit flag >
    :data:`SERVE_MAX_COHORT_ENV` env var > ``default``. ``env`` overrides
    the real environment lookup (tests)."""
    val = flag
    if val is None:
        raw = env if env is not None else os.environ.get(
            SERVE_MAX_COHORT_ENV
        )
        if raw is None or raw == "":
            return default
        try:
            val = int(raw)
        except ValueError:
            raise ValueError(
                f"{SERVE_MAX_COHORT_ENV} must be an integer, got {raw!r}"
            ) from None
    if val < 1:
        raise ValueError(f"serve max-cohort must be >= 1, got {val}")
    return int(val)


#: env var arming an out-of-core HOST→DEVICE stream budget in bytes
#: (k/m/g/t suffixes, like the serve budget): the ceiling on device bytes
#: the streamed partition window may occupy. stack_residency="auto"
#: resolves to streamed exactly when this is set; the trainer sizes the
#: window so two of them (the one computing + the one in flight,
#: data/prefetch's double buffer) fit the budget.
STREAM_WINDOW_ENV = "ERASUREHEAD_STREAM_WINDOW"


def resolve_stream_budget(
    flag: Optional[str] = None, env: Optional[str] = None
) -> Optional[int]:
    """The streamed-window byte budget, or None (unarmed). Precedence
    mirrors the serve budget: explicit flag > :data:`STREAM_WINDOW_ENV`
    env var > off. ``env`` overrides the real environment lookup
    (tests)."""
    val = flag
    if val is None:
        val = env if env is not None else os.environ.get(STREAM_WINDOW_ENV)
    if val is None or val == "":
        return None
    return parse_bytes(val)


#: env var controlling run telemetry when the CLI flag is absent
#: (mirrors ERASUREHEAD_SWEEP_CACHE's flag > env > default precedence)
TELEMETRY_ENV = "ERASUREHEAD_TELEMETRY"

_TELEMETRY_ON = ("1", "on", "true", "yes")
_TELEMETRY_OFF = ("0", "off", "false", "no")


def resolve_telemetry(
    flag: Optional[str] = None,
    out_dir_set: bool = False,
    env: Optional[str] = None,
) -> bool:
    """Should this invocation write a run-telemetry event log (obs/)?

    Precedence mirrors the ``--sweep-cache`` pattern: the explicit CLI
    ``--telemetry {on,off,auto}`` flag wins, else the
    :data:`TELEMETRY_ENV` env var, else the default ``off``. The ``auto``
    setting resolves to on exactly when the caller passed an explicit
    output directory (``out_dir_set`` — the CLI's ``--output-dir``): a run
    that asked for a place to keep artifacts wants the event log beside
    them, while ad-hoc runs stay zero-overhead by default.

    ``env`` overrides the real environment lookup (tests).
    """
    val = flag
    if val is None:
        val = env if env is not None else os.environ.get(TELEMETRY_ENV)
    if val is None or val == "":
        val = "off"
    val = str(val).strip().lower()
    if val in _TELEMETRY_ON:
        return True
    if val in _TELEMETRY_OFF:
        return False
    if val == "auto":
        return bool(out_dir_set)
    raise ValueError(
        f"telemetry setting must be on/off/auto (or a truthy/falsy "
        f"{TELEMETRY_ENV} value), got {val!r}"
    )
