"""Chaos injection hook: deterministic fault injection for the sweep runner.

The resilience machinery (sweep journal + resume, cohort OOM bisection,
checkpoint fallback) exists to survive failures that are awkward to produce
on demand — a preemption mid-sweep, a cohort dispatch blowing HBM, a kill
mid-checkpoint-save. This module makes those failures *reproducible*: the
``ERASUREHEAD_CHAOS`` env var arms exactly one fault, and instrumented call
sites (:func:`maybe_fire`) trigger it at a deterministic invocation count.
The chaos harness (tools/chaos_sweep.py, ``make chaos-smoke``) drives
kill→resume cycles through it and asserts the resumed sweep's rows are
identical to an uninterrupted baseline.

Spec grammar (``ERASUREHEAD_CHAOS=mode:site:count[:message]``):

  - ``mode``   — ``kill`` (the process dies via ``os._exit`` with
                 :data:`KILL_EXIT`, simulating a preemption: no cleanup, no
                 atexit, nothing flushed beyond what already hit disk) or
                 ``raise`` (a :class:`ChaosInjection` whose message carries
                 an XLA-style status marker, default ``RESOURCE_EXHAUSTED``,
                 so the cohort-degradation guard exercises its real
                 classification path);
  - ``site``   — which instrumented hook arms: ``trajectory`` (after a
                 sweep trajectory's summary row is finalized/journaled —
                 experiments.compare), ``cohort`` (at the head of a
                 trajectory-batched cohort dispatch — trainer.train_cohort),
                 ``checkpoint`` (at the head of checkpoint.save, i.e. the
                 save never commits);
  - ``count``  — fire on the Nth invocation of that site (``2``), or on the
                 Nth and every later one (``2+`` — e.g. ``raise:cohort:1+``
                 fails every cohort dispatch, forcing full degradation to
                 sequential train());
  - ``message``— optional fault text; the guard classifies transients vs
                 OOM from it (``raise:cohort:1:UNAVAILABLE`` produces a
                 retryable transient instead of an OOM-style failure).

The hook is a no-op when the env var is unset; library code pays one dict
lookup. Invocation counters are process-global (:func:`reset` for tests).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

#: env var arming the fault
CHAOS_ENV = "ERASUREHEAD_CHAOS"

#: exit code of a chaos kill — distinctive, so harnesses can tell an
#: injected preemption from a genuine crash
KILL_EXIT = 43

#: instrumented call sites ("adapt" fires at the adaptive controller's
#: chunk boundaries — adapt/driver.py — so kill→resume decision-replay
#: invariance is testable mid-adaptation)
SITES = ("trajectory", "cohort", "checkpoint", "adapt")


class ChaosInjection(RuntimeError):
    """An injected fault (mode ``raise``); the message carries the
    configured status marker so error classifiers treat it like the real
    failure it stands in for."""


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    mode: str  # "kill" | "raise"
    site: str
    count: int  # 1-based invocation number that fires
    sticky: bool  # True = fire on count and every later invocation
    message: str


def parse_spec(spec: str) -> ChaosSpec:
    """Parse ``mode:site:count[:message]``; loud on malformed specs — a
    typo'd chaos run silently doing nothing would invalidate the harness."""
    parts = spec.split(":", 3)
    if len(parts) < 3:
        raise ValueError(
            f"{CHAOS_ENV}={spec!r}: want mode:site:count[:message]"
        )
    mode, site, count = parts[0], parts[1], parts[2]
    message = parts[3] if len(parts) > 3 else "RESOURCE_EXHAUSTED"
    if mode not in ("kill", "raise"):
        raise ValueError(f"{CHAOS_ENV}={spec!r}: mode must be kill|raise")
    if site not in SITES:
        raise ValueError(
            f"{CHAOS_ENV}={spec!r}: site must be one of {SITES}"
        )
    sticky = count.endswith("+")
    try:
        n = int(count[:-1] if sticky else count)
    except ValueError:
        raise ValueError(
            f"{CHAOS_ENV}={spec!r}: count must be an int or 'N+'"
        ) from None
    if n < 1:
        raise ValueError(f"{CHAOS_ENV}={spec!r}: count must be >= 1")
    return ChaosSpec(
        mode=mode, site=site, count=n, sticky=sticky, message=message
    )


_counts: dict[str, int] = {}


def reset() -> None:
    """Zero the per-site invocation counters (tests)."""
    _counts.clear()


def active() -> Optional[ChaosSpec]:
    """The armed spec, or None when chaos is off."""
    spec = os.environ.get(CHAOS_ENV)
    return parse_spec(spec) if spec else None


def maybe_fire(site: str) -> None:
    """Count one invocation of ``site``; fire the armed fault if its
    trigger condition is met. No-op (beyond one env lookup) when unarmed."""
    if CHAOS_ENV not in os.environ:
        return
    spec = active()
    if spec is None or spec.site != site:
        return
    _counts[site] = _counts.get(site, 0) + 1
    n = _counts[site]
    if n != spec.count and not (spec.sticky and n > spec.count):
        return
    if spec.mode == "kill":
        # preemption semantics: no cleanup, no atexit — only what already
        # reached disk (the journal flushes per line) survives
        os._exit(KILL_EXIT)
    raise ChaosInjection(
        f"{spec.message}: chaos injection at site {site!r} "
        f"(invocation {n}, spec {spec.mode}:{spec.site}:"
        f"{spec.count}{'+' if spec.sticky else ''})"
    )


# ---------------------------------------------------------------------------
# straggler-regime injection (ISSUE 8 satellite): a deterministic mid-run
# regime change, armed by env var like the fault spec above. Not a fault —
# nothing crashes — but the same philosophy: the adaptive controller
# (adapt/) exists to survive regime shifts that are awkward to produce on
# demand, and this makes them reproducible for tests and bench.

#: env var arming a straggler-regime shift
#: (``kind:round[:param[:param2]]``): ``heavytail:50[:alpha]`` switches
#: the delay stream from exponential to Pareto(alpha)-tailed at round 50;
#: ``adversary:50[:worker[:slowdown]]`` turns one worker adversarially
#: slow from round 50 (arXiv:1901.08166's fixed-straggler worst case).
#: Consumed by trainer.default_arrivals — unset, arrival schedules are
#: byte-for-byte what they always were.
REGIME_ENV = "ERASUREHEAD_REGIME"


def parse_regime(spec: str):
    """Parse :data:`REGIME_ENV`; loud on malformed specs (a typo'd regime
    run silently staying stationary would invalidate the experiment)."""
    from erasurehead_tpu.parallel.straggler import RegimeShift

    parts = spec.split(":")
    if len(parts) < 2:
        raise ValueError(
            f"{REGIME_ENV}={spec!r}: want kind:round[:param[:param2]]"
        )
    kind = parts[0]
    try:
        rnd = int(parts[1])
    except ValueError:
        raise ValueError(
            f"{REGIME_ENV}={spec!r}: round must be an int"
        ) from None
    if kind == "heavytail":
        alpha = float(parts[2]) if len(parts) > 2 else 1.2
        return RegimeShift(kind=kind, round=rnd, alpha=alpha)
    if kind == "adversary":
        worker = int(parts[2]) if len(parts) > 2 else 0
        slowdown = float(parts[3]) if len(parts) > 3 else 5.0
        return RegimeShift(
            kind=kind, round=rnd, worker=worker, slowdown=slowdown
        )
    raise ValueError(
        f"{REGIME_ENV}={spec!r}: kind must be heavytail|adversary"
    )


def active_regime():
    """The armed RegimeShift, or None when the env var is unset."""
    spec = os.environ.get(REGIME_ENV)
    return parse_regime(spec) if spec else None
