"""Chaos injection hook: deterministic fault injection for the sweep runner.

The resilience machinery (sweep journal + resume, cohort OOM bisection,
checkpoint fallback) exists to survive failures that are awkward to produce
on demand — a preemption mid-sweep, a cohort dispatch blowing HBM, a kill
mid-checkpoint-save. This module makes those failures *reproducible*: the
``ERASUREHEAD_CHAOS`` env var arms exactly one fault, and instrumented call
sites (:func:`maybe_fire`) trigger it at a deterministic invocation count.
The chaos harness (tools/chaos_sweep.py, ``make chaos-smoke``) drives
kill→resume cycles through it and asserts the resumed sweep's rows are
identical to an uninterrupted baseline.

Spec grammar (``ERASUREHEAD_CHAOS=spec[,spec...]`` — a comma-separated
list of independently armed faults; each spec is
``mode:site:count[:message]``):

  - ``mode``   — ``kill`` (the process dies via ``os._exit`` with
                 :data:`KILL_EXIT`, simulating a preemption: no cleanup, no
                 atexit, nothing flushed beyond what already hit disk),
                 ``raise`` (a :class:`ChaosInjection` whose message carries
                 an XLA-style status marker, default ``RESOURCE_EXHAUSTED``,
                 so the cohort-degradation guard exercises its real
                 classification path), or ``stall`` (the invocation sleeps
                 the number of SECONDS carried in the message field,
                 default 30 — a hung dispatch, distinguishable from a dead
                 one, which is what request timeouts exist for). For the
                 MEMBERSHIP sites below the mode field is a WORKER ID
                 instead (an integer — the fault is a membership change,
                 not a process fault).
  - ``site``   — which instrumented hook arms: ``trajectory`` (after a
                 sweep trajectory's summary row is finalized/journaled —
                 experiments.compare), ``cohort`` (at the head of a
                 trajectory-batched cohort dispatch — trainer.train_cohort),
                 ``checkpoint`` (at the head of checkpoint.save, i.e. the
                 save never commits), ``adapt`` / ``elastic`` (the chunk
                 boundaries of the adaptive and elastic drivers),
                 ``prefetch`` (per staged partition window of a streamed
                 run — data/prefetch.py);
  - ``count``  — fire on the Nth invocation of that site (``2``), or on the
                 Nth and every later one (``2+`` — e.g. ``raise:cohort:1+``
                 fails every cohort dispatch, forcing full degradation to
                 sequential train());
  - ``message``— optional fault text; the guard classifies transients vs
                 OOM from it (``raise:cohort:1:UNAVAILABLE`` produces a
                 retryable transient instead of an OOM-style failure).

Membership sites (:data:`MEMBERSHIP_SITES`, consumed by the elastic
membership driver — erasurehead_tpu/elastic/) use the worker-id form
``worker:site:count``: ``3:worker_death:2`` kills live worker 3 at the
elastic driver's 2nd chunk boundary, and ``3:worker_revive:5`` offers it
back at the 5th — so one env var drives a full die-then-rejoin cycle::

    ERASUREHEAD_CHAOS=3:worker_death:2,3:worker_revive:5

The hook is a no-op when the env var is unset; library code pays one dict
lookup. Invocation counters are process-global (:func:`reset` for tests).
Multi-spec messages cannot contain commas (the list separator).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

#: env var arming the fault
CHAOS_ENV = "ERASUREHEAD_CHAOS"

#: exit code of a chaos kill — distinctive, so harnesses can tell an
#: injected preemption from a genuine crash
KILL_EXIT = 43

#: instrumented call sites ("adapt" fires at the adaptive controller's
#: chunk boundaries — adapt/driver.py — so kill→resume decision-replay
#: invariance is testable mid-adaptation; "elastic" is the same hook in
#: the elastic membership driver — elastic/driver.py)
SITES = (
    "trajectory", "cohort", "checkpoint", "adapt", "elastic",
    "worker_death", "worker_revive",
    # serve-daemon failure domains (erasurehead_tpu/serve/server.py):
    # "serve_intake" fires after a request's intake-WAL append (a kill
    # there proves the WAL preserved the acceptance), "serve_dispatch"
    # at the head of a packed cohort dispatch (accepted + WAL'd, row not
    # yet journaled — the warm-restart working set), "serve_reply" after
    # the row is journaled but before the reply is delivered (the client
    # must be able to re-fetch by resubmitting)
    "serve_intake", "serve_dispatch", "serve_reply",
    # serve-fleet replica death (serve/server.py _run_cohort, fired just
    # before serve_dispatch): a kill here takes down ONE replica of a
    # fleet mid-dispatch — accepted + WAL'd, rows not yet journaled —
    # and the drill (tools/fleet_smoke.py) proves a peer adopts the dead
    # replica's intake WAL and replays its accepted rows bitwise
    "fleet_replica",
    # out-of-core streaming (data/prefetch.py): fires once per staged
    # partition window, BEFORE the shard read — a kill there is a
    # mid-epoch preemption of a streamed run (tools/outofcore_smoke.py
    # proves the sweep journal rehydrates completed rows bitwise)
    "prefetch",
    # autotune races (tune/racer.py): fires at the head of a race, before
    # any candidate is timed — a kill there proves a half-finished race
    # leaves the decision cache untouched (atomic writes) and a cold
    # re-run produces the byte-identical cache
    "tune_race",
)

#: sites whose fault is a MEMBERSHIP change (a worker dying or offering
#: to join) rather than a process fault; their specs carry a worker id in
#: the mode field and fire through :func:`fire_membership`, never
#: :func:`maybe_fire`
MEMBERSHIP_SITES = ("worker_death", "worker_revive")


class ChaosInjection(RuntimeError):
    """An injected fault (mode ``raise``); the message carries the
    configured status marker so error classifiers treat it like the real
    failure it stands in for."""


@dataclasses.dataclass(frozen=True)
class ChaosSpec:
    mode: str  # "kill" | "raise" | "member" (membership sites)
    site: str
    count: int  # 1-based invocation number that fires
    sticky: bool  # True = fire on count and every later invocation
    message: str
    worker: Optional[int] = None  # membership sites: which worker


def parse_spec(spec: str) -> ChaosSpec:
    """Parse one ``mode:site:count[:message]`` spec (worker-id mode for
    membership sites); loud on malformed specs — a typo'd chaos run
    silently doing nothing would invalidate the harness."""
    parts = spec.split(":", 3)
    if len(parts) < 3:
        raise ValueError(
            f"{CHAOS_ENV}={spec!r}: want mode:site:count[:message]"
        )
    mode, site, count = parts[0], parts[1], parts[2]
    message = parts[3] if len(parts) > 3 else "RESOURCE_EXHAUSTED"
    if site not in SITES:
        raise ValueError(
            f"{CHAOS_ENV}={spec!r}: site must be one of {SITES}"
        )
    worker = None
    if site in MEMBERSHIP_SITES:
        # membership grammar: the first field is the worker id the event
        # concerns (3:worker_death:2 = worker 3 dies at invocation 2)
        try:
            worker = int(mode)
        except ValueError:
            raise ValueError(
                f"{CHAOS_ENV}={spec!r}: membership sites take a worker id "
                f"first (e.g. 3:{site}:2), got {mode!r}"
            ) from None
        if worker < 0:
            raise ValueError(
                f"{CHAOS_ENV}={spec!r}: worker id must be >= 0"
            )
        mode = "member"
    elif mode not in ("kill", "raise", "stall"):
        raise ValueError(
            f"{CHAOS_ENV}={spec!r}: mode must be kill|raise|stall"
        )
    if mode == "stall":
        # the message field carries the stall duration in seconds
        if len(parts) <= 3:
            message = "30"
        try:
            if float(message) < 0:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"{CHAOS_ENV}={spec!r}: stall takes a non-negative "
                f"seconds value in the message field, got {message!r}"
            ) from None
    sticky = count.endswith("+")
    try:
        n = int(count[:-1] if sticky else count)
    except ValueError:
        raise ValueError(
            f"{CHAOS_ENV}={spec!r}: count must be an int or 'N+'"
        ) from None
    if n < 1:
        raise ValueError(f"{CHAOS_ENV}={spec!r}: count must be >= 1")
    return ChaosSpec(
        mode=mode, site=site, count=n, sticky=sticky, message=message,
        worker=worker,
    )


def parse_specs(value: str) -> list[ChaosSpec]:
    """Parse the full env value: a comma-separated spec list (one spec,
    no comma, is the historical grammar unchanged)."""
    return [parse_spec(part) for part in value.split(",") if part]


_counts: dict[str, int] = {}


def reset() -> None:
    """Zero the per-site invocation counters (tests)."""
    _counts.clear()


def active() -> Optional[ChaosSpec]:
    """The first armed spec, or None when chaos is off (compat accessor;
    multi-spec callers use :func:`active_specs`)."""
    specs = active_specs()
    return specs[0] if specs else None


def active_specs() -> list[ChaosSpec]:
    """All armed specs ([] when chaos is off)."""
    value = os.environ.get(CHAOS_ENV)
    return parse_specs(value) if value else []


def _fires(spec: ChaosSpec, n: int) -> bool:
    return n == spec.count or (spec.sticky and n > spec.count)


def maybe_fire(site: str) -> None:
    """Count one invocation of ``site``; fire the armed fault if its
    trigger condition is met. No-op (beyond one env lookup) when unarmed.
    Membership sites never fire here (:func:`fire_membership`)."""
    if CHAOS_ENV not in os.environ:
        return
    specs = [s for s in active_specs() if s.site == site]
    if not specs or site in MEMBERSHIP_SITES:
        return
    _counts[site] = _counts.get(site, 0) + 1
    n = _counts[site]
    for spec in specs:
        if not _fires(spec, n):
            continue
        if spec.mode == "kill":
            # preemption semantics: no cleanup, no atexit — only what
            # already reached disk (the journal flushes per line) survives
            os._exit(KILL_EXIT)
        if spec.mode == "stall":
            import time

            time.sleep(float(spec.message))
            continue
        raise ChaosInjection(
            f"{spec.message}: chaos injection at site {site!r} "
            f"(invocation {n}, spec {spec.mode}:{spec.site}:"
            f"{spec.count}{'+' if spec.sticky else ''})"
        )


def membership_fires(site: str, invocation: int) -> tuple[int, ...]:
    """PURE query: the worker ids of armed MEMBERSHIP specs firing at the
    1-based ``invocation`` of ``site``. No counters are touched — the
    elastic driver indexes invocations by its own absolute chunk-boundary
    number, so a killed-and-resumed run replays the identical membership
    chaos without re-firing already-applied events (process-global
    counters would restart at zero and shift every firing)."""
    if site not in MEMBERSHIP_SITES:
        raise ValueError(
            f"membership_fires: {site!r} is not one of {MEMBERSHIP_SITES}"
        )
    if invocation < 1:
        raise ValueError(f"invocation must be >= 1, got {invocation}")
    if CHAOS_ENV not in os.environ:
        return ()
    return tuple(
        s.worker
        for s in active_specs()
        if s.site == site and _fires(s, invocation)
    )


def fire_membership(site: str) -> tuple[int, ...]:
    """Counter-based form of :func:`membership_fires`: count one
    invocation of ``site`` and return the worker ids firing at it. Never
    kills or raises; returns () when unarmed."""
    if site not in MEMBERSHIP_SITES:
        raise ValueError(
            f"fire_membership: {site!r} is not one of {MEMBERSHIP_SITES}"
        )
    if CHAOS_ENV not in os.environ:
        return ()
    if not any(s.site == site for s in active_specs()):
        return ()
    _counts[site] = _counts.get(site, 0) + 1
    return membership_fires(site, _counts[site])


# ---------------------------------------------------------------------------
# straggler-regime injection (ISSUE 8 satellite): a deterministic mid-run
# regime change, armed by env var like the fault spec above. Not a fault —
# nothing crashes — but the same philosophy: the adaptive controller
# (adapt/) exists to survive regime shifts that are awkward to produce on
# demand, and this makes them reproducible for tests and bench.

#: env var arming a straggler-regime shift
#: (``kind:round[:param[:param2]]``): ``heavytail:50[:alpha]`` switches
#: the delay stream from exponential to Pareto(alpha)-tailed at round 50;
#: ``adversary:50[:worker[:slowdown]]`` turns one worker adversarially
#: slow from round 50 (arXiv:1901.08166's fixed-straggler worst case);
#: ``targeted:50[:group[:slowdown]]`` slows EVERY replica of one coded
#: partition group at once — the fractional-repetition worst case the same
#: paper proves (the attacked workers are derived from the run's layout by
#: trainer.default_arrivals; see straggler.targeted_workers).
#: Consumed by trainer.default_arrivals — unset, arrival schedules are
#: byte-for-byte what they always were.
REGIME_ENV = "ERASUREHEAD_REGIME"


def parse_regime(spec: str):
    """Parse :data:`REGIME_ENV`; loud on malformed specs (a typo'd regime
    run silently staying stationary would invalidate the experiment)."""
    from erasurehead_tpu.parallel.straggler import RegimeShift

    parts = spec.split(":")
    if len(parts) < 2:
        raise ValueError(
            f"{REGIME_ENV}={spec!r}: want kind:round[:param[:param2]]"
        )
    kind = parts[0]
    try:
        rnd = int(parts[1])
    except ValueError:
        raise ValueError(
            f"{REGIME_ENV}={spec!r}: round must be an int"
        ) from None
    if kind == "heavytail":
        alpha = float(parts[2]) if len(parts) > 2 else 1.2
        return RegimeShift(kind=kind, round=rnd, alpha=alpha)
    if kind == "adversary":
        worker = int(parts[2]) if len(parts) > 2 else 0
        slowdown = float(parts[3]) if len(parts) > 3 else 5.0
        return RegimeShift(
            kind=kind, round=rnd, worker=worker, slowdown=slowdown
        )
    if kind == "targeted":
        group = int(parts[2]) if len(parts) > 2 else 0
        slowdown = float(parts[3]) if len(parts) > 3 else 5.0
        return RegimeShift(
            kind=kind, round=rnd, group=group, slowdown=slowdown
        )
    raise ValueError(
        f"{REGIME_ENV}={spec!r}: kind must be heavytail|adversary|targeted"
    )


def active_regime():
    """The armed RegimeShift, or None when the env var is unset."""
    spec = os.environ.get(REGIME_ENV)
    return parse_regime(spec) if spec else None
