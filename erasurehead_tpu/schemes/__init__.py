"""Declarative scheme registry (ISSUE 8 / ROADMAP item 5).

A scheme is a frozen :class:`SchemeDescriptor` bundling its layout
builder, collection rule (host + traced), failure feasibility, optimal-
decode hook, capability flags, and config surface. The nine builtins
register on import; third-party codes register via
:func:`register` or the ``erasurehead_tpu.schemes`` entry-point group
(:data:`ENTRY_POINT_GROUP`) — see README "Schemes & adaptive collection".

All scheme dispatch in the package resolves through :func:`get`; a
grep-enforced test (tests/test_schemes.py) pins that no ``if scheme ==``
spine survives outside this package.
"""

from erasurehead_tpu.schemes.base import SchemeDescriptor
from erasurehead_tpu.schemes.registry import (
    ENTRY_POINT_GROUP,
    descriptors,
    get,
    is_registered,
    load_entry_points,
    names,
    register,
    scheme_name,
    unregister,
)

# importing the package declares the builtins (registration is idempotent
# per interpreter: module import runs once)
from erasurehead_tpu.schemes import builtin as _builtin  # noqa: F401,E402

__all__ = [
    "SchemeDescriptor",
    "ENTRY_POINT_GROUP",
    "descriptors",
    "get",
    "is_registered",
    "load_entry_points",
    "names",
    "register",
    "scheme_name",
    "unregister",
]
