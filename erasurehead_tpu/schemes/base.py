"""SchemeDescriptor: the declarative unit of the scheme registry.

A *scheme* used to be an if/elif spine threaded through six files — layout
construction in train/trainer.py, the host collection rule in
parallel/collect.py, the on-device rule in parallel/dynamic.py, failure
feasibility in parallel/failures.py, config validation in utils/config.py,
and ad-hoc capability checks everywhere else. Each of those branches was
one facet of the same object; this module gives that object a home.

A :class:`SchemeDescriptor` bundles, per scheme:

  - **layout builder** (``build_layout``): RunConfig -> ops/codes
    CodingLayout — which partitions each worker holds, with which coding
    coefficients (the reference's per-scheme data-assignment blocks).
  - **host collection rule** (``build_schedule``): the stop condition +
    decode weights as a pure function of the arrival matrix
    (parallel/collect.py's rule functions; the reference's master
    ``Waitany`` loop).
  - **dynamic rule factory** (``dynamic_rule``): the fully on-device jnp
    form of the same rule (parallel/dynamic.py), or None when the scheme
    has no traced implementation.
  - **failure feasibility** (``feasibility``): would the master's wait
    loop ever exit under these deaths (parallel/failures.analyze)?
  - **optimal-decode hook** (``optimal_decode``): the registry-level
    ``decode="optimal"`` option (arXiv:2006.09638) — per-round
    least-squares collection weights fit to the *actual* arrival pattern.
    None = the scheme's fixed weights are kept (partial schemes).
  - **capability flags**: exact vs approximate, partial (two-part)
    layouts, measured-mode support, dynamic/on-device decode support,
    cohort batchability (what the sweep planner and the serve packer key
    compatibility on).
  - **config/CLI surface**: which RunConfig knobs the scheme reads
    (``config_fields``), plus a ``validate_config`` hook holding the
    scheme's own config invariants (utils/config delegates to it).

Descriptors are frozen: registration is declaration, not construction.
Third-party codes ship one descriptor and register it — directly via
:func:`erasurehead_tpu.schemes.register` or through the
``erasurehead_tpu.schemes`` entry-point group (see registry.py) — and the
CLI ``--scheme`` choices, ``utils.config`` validation, sweep planning and
serve packing all pick it up without touching core.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

#: RunConfig fields every scheme shares (the descriptor's ``config_fields``
#: lists scheme-SPECIFIC knobs beyond these)
COMMON_CONFIG_FIELDS = ("scheme", "n_workers", "n_stragglers", "seed")


@dataclasses.dataclass(frozen=True)
class SchemeDescriptor:
    """One collection/coding scheme, declaratively (module docstring)."""

    #: the CLI / config name ("approx", "cyccoded", ...)
    name: str
    #: one-line human summary (CLI help, report rendering)
    summary: str = ""

    # ---- behavior --------------------------------------------------------
    #: (cfg: RunConfig) -> ops.codes.CodingLayout
    build_layout: Optional[Callable] = None
    #: (t [R, W], layout, *, num_collect, deadline) ->
    #: parallel.collect.CollectionSchedule — the host (float64) rule
    build_schedule: Optional[Callable] = None
    #: (layout, *, num_collect, deadline) -> (t [W] -> dynamic.RoundSchedule),
    #: the traced on-device rule factory; None = no dynamic implementation
    dynamic_rule: Optional[Callable] = None
    #: (layout, dead [R, W] bool, *, num_collect) -> (feasible [R] bool,
    #: reason str) — parallel.failures.analyze's per-scheme core
    feasibility: Optional[Callable] = None
    #: (schedule, layout) -> schedule with decode="optimal" weights
    #: (least-squares fit to the actual arrival set); None = fixed weights
    #: are already the scheme's only decode (partial schemes)
    optimal_decode: Optional[Callable] = None

    # ---- capabilities ----------------------------------------------------
    #: decodes to the exact full gradient whenever its stop rule is
    #: satisfiable (decode error snaps to 0.0)
    exact: bool = False
    #: two-part partial layout (uncoded slots + coded band)
    partial: bool = False
    #: the layout depends on cfg.seed (cyclic MDS / randreg generator draws)
    seed_dependent_layout: bool = False
    #: has a per-worker-timed measured-arrival implementation
    #: (trainer.train_measured refuses schemes that don't)
    supports_measured: bool = True
    #: has a traced on-device rule (trainer.train_dynamic)
    supports_dynamic: bool = True
    #: may ride a trajectory-batched cohort dispatch (the sweep planner's
    #: plan_cohorts and the serve packer both derive eligibility from this)
    cohort_batchable: bool = True
    #: sound under bounded-staleness pipelined training (cfg.pipeline_depth
    #: = 1, parallel/pipeline.py): True only where the scheme's decode is
    #: already approximate — ErasureHead's decay-rate analysis tolerates a
    #: noisy gradient, and a tau=1-stale one is just another noise source.
    #: Exact-decode schemes keep False: their contract is "the decoded
    #: gradient IS the full gradient at the current iterate", which
    #: staleness breaks by construction. Third-party schemes default to
    #: False (refuse until proven).
    staleness_tolerant: bool = False

    # ---- config / CLI surface -------------------------------------------
    #: scheme-specific RunConfig knobs (beyond COMMON_CONFIG_FIELDS)
    config_fields: Tuple[str, ...] = ()
    #: cfg.num_collect is required (AGC-family stop counts)
    needs_num_collect: bool = False
    #: cfg.deadline is required
    needs_deadline: bool = False
    #: (cfg) -> None, raising ValueError on scheme-specific config
    #: violations (partial partition counts, positive deadlines, ...)
    validate_config: Optional[Callable] = None
    #: (n_workers) -> num_collect override for straggler sweeps whose base
    #: config would collect everything (experiments.straggler_sweep's
    #: "AGC's interesting regime collects fewer than all")
    sweep_num_collect: Optional[Callable] = None

    # ---- artifact naming -------------------------------------------------
    #: reference artifact filename stem (train/artifacts.run_prefix, e.g.
    #: "coded_acc" for cyccoded per src/coded.py:250-254); None =
    #: "<name>_acc" — so schemes registered after the artifact writer was
    #: written get a stem by construction instead of a KeyError
    artifact_stem: Optional[str] = None
    #: artifacts carry the reference's "_<n_stragglers>" filename suffix
    #: (partial schemes append "_<partitions_per_worker>" too, keyed on
    #: ``partial``); naive is the reference's one suffix-free scheme
    artifact_straggler_suffix: bool = True

    #: ships with erasurehead_tpu (entry-point/third-party schemes: False)
    builtin: bool = False

    def __post_init__(self):
        if not self.name or not isinstance(self.name, str):
            raise ValueError(f"scheme descriptor needs a name, got {self.name!r}")
        for field in ("build_layout", "build_schedule"):
            if getattr(self, field) is None:
                raise ValueError(
                    f"scheme {self.name!r}: descriptor field {field!r} is "
                    "required (a scheme must at least build a layout and a "
                    "collection schedule)"
                )

    def capabilities(self) -> dict:
        """Flag dict (report rendering, third-party introspection)."""
        return {
            "exact": self.exact,
            "partial": self.partial,
            "seed_dependent_layout": self.seed_dependent_layout,
            "supports_measured": self.supports_measured,
            "supports_dynamic": self.supports_dynamic,
            "cohort_batchable": self.cohort_batchable,
            "staleness_tolerant": self.staleness_tolerant,
            "supports_optimal_decode": self.optimal_decode is not None,
            "needs_num_collect": self.needs_num_collect,
            "needs_deadline": self.needs_deadline,
        }

    def validate(self, cfg) -> None:
        """Scheme-specific config validation (utils.config delegates here
        from RunConfig.__post_init__)."""
        if getattr(cfg, "pipeline_depth", 0) and not self.staleness_tolerant:
            from erasurehead_tpu.utils.config import PipelineRefusal

            kind = "exact-decode" if self.exact else "not staleness-tolerant"
            raise PipelineRefusal(
                "exact_decode" if self.exact else "untested_scheme",
                f"pipeline_depth=1 refuses scheme={self.name!r} ({kind}): "
                "a tau=1-stale gradient breaks the exactness contract, and "
                "only schemes whose descriptor declares staleness_tolerant "
                "(the approximate first-k/deadline families) run pipelined",
            )
        if self.needs_deadline and (cfg.deadline is None or cfg.deadline <= 0):
            raise ValueError(
                f"scheme={self.name!r} needs a positive deadline "
                f"(got {cfg.deadline!r})"
            )
        if self.validate_config is not None:
            self.validate_config(cfg)
