"""The scheme registry: name -> SchemeDescriptor, entry-point discoverable.

Lookup (:func:`get`) is the single dispatch point replacing the old
if/elif spines — ``trainer.build_layout``, ``collect.build_schedule``,
``dynamic.make_round_schedule_fn`` and ``failures.analyze`` all resolve
their scheme through here (a grep-enforced test pins that no scheme
dispatch survives outside ``schemes/``).

Third-party codes register without touching core, two ways:

  - **direct**: ``erasurehead_tpu.schemes.register(descriptor)`` at import
    time of the extension module;
  - **entry point**: expose the descriptor (or a zero-arg factory
    returning one) under the ``erasurehead_tpu.schemes`` group::

        [project.entry-points."erasurehead_tpu.schemes"]
        mycode = "mypkg.schemes:MYCODE_DESCRIPTOR"

    Entry points load lazily on the first registry read, so importing
    erasurehead_tpu costs nothing extra; a broken third-party entry point
    degrades to a one-time warning, never a core import failure.

Registered names surface everywhere the builtins do: CLI ``--scheme``
choices, ``utils.config`` validation errors, ``experiments.compare()``,
and the serve packer's cohort-compatibility checks.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional

from erasurehead_tpu.schemes.base import SchemeDescriptor

#: the entry-point group third-party schemes publish under
ENTRY_POINT_GROUP = "erasurehead_tpu.schemes"

_REGISTRY: dict[str, SchemeDescriptor] = {}
_lock = threading.RLock()
_entry_points_loaded = False


def register(desc: SchemeDescriptor, *, replace: bool = False) -> SchemeDescriptor:
    """Register a descriptor under its name. Refuses silent shadowing:
    re-registering an existing name (builtin or not) needs ``replace=True``
    — a third-party package overriding ``approx`` by accident would
    silently change every run's math."""
    if not isinstance(desc, SchemeDescriptor):
        raise TypeError(
            f"register() takes a SchemeDescriptor, got {type(desc).__name__}"
        )
    with _lock:
        prev = _REGISTRY.get(desc.name)
        if prev is not None and not replace:
            raise ValueError(
                f"scheme {desc.name!r} is already registered "
                f"({'builtin' if prev.builtin else 'extension'}); pass "
                "replace=True to shadow it deliberately"
            )
        _REGISTRY[desc.name] = desc
    return desc


def unregister(name: str) -> None:
    """Remove a non-builtin descriptor (tests, plugin unload)."""
    with _lock:
        desc = _REGISTRY.get(name)
        if desc is None:
            return
        if desc.builtin:
            raise ValueError(f"cannot unregister builtin scheme {name!r}")
        del _REGISTRY[name]


def _ensure_loaded() -> None:
    # builtins register at schemes package import; entry points load once,
    # on the first registry READ, so `import erasurehead_tpu` stays cheap
    if not _entry_points_loaded:
        load_entry_points()


def load_entry_points(force: bool = False) -> list[str]:
    """Discover and register ``erasurehead_tpu.schemes`` entry points.

    Each entry point's ``load()`` must yield a :class:`SchemeDescriptor`
    or a zero-arg callable returning one. Returns the names newly
    registered. Broken entry points warn once (stderr) instead of
    breaking the registry — a bad plugin must not take the CLI down.
    ``force=True`` re-scans (tests monkeypatching ``importlib.metadata``).
    """
    global _entry_points_loaded
    with _lock:
        if _entry_points_loaded and not force:
            return []
        _entry_points_loaded = True
        import importlib.metadata as _md

        try:
            eps = _md.entry_points()
            group: Iterable = (
                eps.select(group=ENTRY_POINT_GROUP)
                if hasattr(eps, "select")
                else eps.get(ENTRY_POINT_GROUP, ())  # pre-3.10 dict API
            )
        except Exception as e:  # noqa: BLE001 — discovery must not raise
            _warn_entry_point("<entry-point scan>", e)
            return []
        added: list[str] = []
        for ep in group:
            try:
                obj = ep.load()
                if callable(obj) and not isinstance(obj, SchemeDescriptor):
                    obj = obj()
                if not isinstance(obj, SchemeDescriptor):
                    raise TypeError(
                        f"entry point yielded {type(obj).__name__}, not a "
                        "SchemeDescriptor"
                    )
                if obj.name not in _REGISTRY:
                    register(obj)
                    added.append(obj.name)
            except Exception as e:  # noqa: BLE001 — isolate bad plugins
                _warn_entry_point(getattr(ep, "name", "?"), e)
        return added


def _warn_entry_point(name: str, err: Exception) -> None:
    from erasurehead_tpu.obs.metrics import warn_once

    warn_once(
        f"scheme_entry_point:{name}",
        f"schemes: entry point {name!r} in group {ENTRY_POINT_GROUP!r} "
        f"failed to load ({type(err).__name__}: {err}); ignoring it",
    )


def scheme_name(scheme) -> str:
    """The registry key for a Scheme enum member / ExtensionScheme /
    plain string."""
    return getattr(scheme, "value", None) or str(scheme)


def get(scheme) -> SchemeDescriptor:
    """The descriptor for a scheme (enum member, extension tag, or name);
    ValueError naming the registered schemes otherwise."""
    _ensure_loaded()
    name = scheme_name(scheme)
    desc = _REGISTRY.get(name)
    if desc is None:
        raise ValueError(
            f"unknown scheme {name!r}; registered schemes: {names()}"
        )
    return desc


def is_registered(scheme) -> bool:
    _ensure_loaded()
    return scheme_name(scheme) in _REGISTRY


def names() -> list[str]:
    """All registered scheme names, builtins first (in registration
    order), extensions after — the CLI ``--scheme`` choices."""
    _ensure_loaded()
    with _lock:
        builtin = [n for n, d in _REGISTRY.items() if d.builtin]
        ext = sorted(n for n, d in _REGISTRY.items() if not d.builtin)
    return builtin + ext


def descriptors() -> list[SchemeDescriptor]:
    _ensure_loaded()
    with _lock:
        return [_REGISTRY[n] for n in names()]
