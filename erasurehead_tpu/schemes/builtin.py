"""The built-in scheme descriptors: the reference's seven plus randreg and
deadline, declared as registry entries.

Each descriptor wires the scheme's existing rule implementations together
— layout factories from ops/codes.py, host collection rules from
parallel/collect.py, traced rules from parallel/dynamic.py — so the
registry refactor changes DISPATCH, never math: every builtin's layout and
collection schedule are bitwise-identical to the old if/elif spines
(pinned by tests/test_schemes.py's round-trip suite and by the existing
equivalence suites running unchanged).

Feasibility cores reproduce parallel/failures.analyze's per-scheme table
(the "would the reference's master ever exit its wait loop" question);
reasons keep the exact wording the failure reports always used.

The ``optimal_decode`` hook is the registry-level ``decode="optimal"``
option (arXiv:2006.09638): least-squares collection weights fit to the
*actual* per-round arrival set over the layout's effective coding matrix.
On exact schemes the fit reproduces the fixed weights' zero decode error;
on approximate schemes it is the minimum-weight-space-error decode, which
the obs/decode.py error norm proves ≤ the fixed weights round for round.
Partial schemes keep ``optimal_decode=None``: their separate slots are
unconditionally weighted 1.0 outside the message-weight system, so the
fixed decode is the only one defined.
"""

from __future__ import annotations

import numpy as np

from erasurehead_tpu.ops import codes
from erasurehead_tpu.schemes.base import SchemeDescriptor
from erasurehead_tpu.schemes.registry import register


# ---------------------------------------------------------------------------
# shared feasibility helpers (parallel/failures.analyze's precomputations)
# ---------------------------------------------------------------------------


def _alive_cnt(dead: np.ndarray) -> np.ndarray:
    return (~dead).sum(axis=1)


def _all_groups_alive(layout, dead: np.ndarray) -> np.ndarray:
    groups = np.asarray(layout.groups)
    return np.stack(
        [(~dead[:, groups == g]).any(axis=1) for g in range(layout.n_groups)],
        axis=1,
    ).all(axis=1)


# ---------------------------------------------------------------------------
# optimal decode (arXiv:2006.09638): the shared least-squares hook
# ---------------------------------------------------------------------------


def lstsq_optimal_decode(schedule, layout):
    """decode="optimal": refit the schedule's message weights as the
    least-squares solution over the ACTUAL collected set (delegates to
    parallel.collect.optimal_decode_schedule — the solve lives beside the
    other host collection math)."""
    from erasurehead_tpu.parallel import collect

    return collect.optimal_decode_schedule(schedule, layout)


# ---------------------------------------------------------------------------
# dynamic-rule factories (parallel/dynamic.py's per-scheme closures,
# including each MDS-family scheme's f64 decode-table construction)
# ---------------------------------------------------------------------------


def _mds_table_or_warn(scheme_name, layout, max_stragglers, exact_only):
    """Build the f64 decode table for an MDS-family dynamic rule, warning
    (exactly as the old dispatch did) when C(W, s) exceeds the table cap
    and the rule must fall back to the unreliable on-device fp32 solve."""
    table = codes.build_decode_table(
        np.asarray(layout.B), max_stragglers, exact_only=exact_only
    )
    if table is None and layout.n_workers > 16:
        import warnings

        warnings.warn(
            f"{scheme_name}: C(W, s) too large for a decode table at "
            f"W={layout.n_workers}; falling back to the on-device fp32 "
            "solve, which is UNRELIABLE for ill-conditioned straggler "
            "patterns at this scale (see ops/codes.mds_decode_weights_host)."
            " Prefer trainer.train() (host f64 control plane) for science"
            " runs.",
            stacklevel=3,
        )
    return table


def _dyn_naive(layout, *, num_collect=None, deadline=None):
    from erasurehead_tpu.parallel import dynamic

    return dynamic.collect_all_jnp


def _dyn_cyclic_mds(layout, *, num_collect=None, deadline=None):
    import jax.numpy as jnp

    from erasurehead_tpu.parallel import dynamic

    B = jnp.asarray(layout.B, jnp.float32)
    table = _mds_table_or_warn(
        "cyccoded", layout, layout.n_stragglers, exact_only=True
    )
    return lambda t: dynamic.collect_first_k_mds_jnp(
        t, B, layout.n_stragglers, decode_table=table
    )


def _dyn_frc(layout, *, num_collect=None, deadline=None):
    import jax.numpy as jnp

    from erasurehead_tpu.parallel import dynamic

    onehot = jnp.asarray(dynamic._group_onehot(np.asarray(layout.groups)))
    return lambda t: dynamic.collect_frc_jnp(t, onehot)


def _dyn_agc(layout, *, num_collect=None, deadline=None):
    import jax.numpy as jnp

    from erasurehead_tpu.parallel import dynamic

    if num_collect is None:
        raise ValueError("AGC needs num_collect")
    onehot = jnp.asarray(dynamic._group_onehot(np.asarray(layout.groups)))
    return lambda t: dynamic.collect_agc_jnp(t, onehot, num_collect)


def _dyn_avoidstragg(layout, *, num_collect=None, deadline=None):
    from erasurehead_tpu.parallel import dynamic

    return lambda t: dynamic.collect_avoidstragg_jnp(t, layout.n_stragglers)


def _dyn_randreg(layout, *, num_collect=None, deadline=None):
    import jax.numpy as jnp

    from erasurehead_tpu.parallel import dynamic

    if num_collect is None:
        raise ValueError("randreg needs num_collect")
    B = jnp.asarray(layout.B, jnp.float32)
    table = _mds_table_or_warn(
        "randreg", layout, layout.n_workers - num_collect, exact_only=True
    )
    return lambda t: dynamic._first_k_lstsq_jnp(
        t, B, num_collect, decode_table=table
    )


def _dyn_deadline(layout, *, num_collect=None, deadline=None):
    from erasurehead_tpu.parallel import dynamic

    if deadline is None:
        raise ValueError("deadline scheme needs a deadline")
    return lambda t: dynamic.collect_deadline_jnp(t, deadline)


def _dyn_partial_cyclic(layout, *, num_collect=None, deadline=None):
    import jax.numpy as jnp

    from erasurehead_tpu.parallel import dynamic

    B = jnp.asarray(layout.B, jnp.float32)
    # completed sets can exceed W-s here -> full 0..s pattern range
    table = _mds_table_or_warn(
        "partialcyccoded", layout, layout.n_stragglers, exact_only=False
    )
    frac = layout.uncoded_frac
    return lambda t: dynamic.collect_partial_jnp(
        t, variant="mds", frac=frac, n_stragglers=layout.n_stragglers,
        B=B, decode_table=table,
    )


def _dyn_partial_frc(layout, *, num_collect=None, deadline=None):
    import jax.numpy as jnp

    from erasurehead_tpu.parallel import dynamic

    onehot = jnp.asarray(dynamic._group_onehot(np.asarray(layout.groups)))
    gids = jnp.asarray(np.asarray(layout.groups))
    frac = layout.uncoded_frac
    return lambda t: dynamic.collect_partial_jnp(
        t, variant="frc", frac=frac, onehot=onehot, group_ids=gids,
    )


# ---------------------------------------------------------------------------
# config validation hooks
# ---------------------------------------------------------------------------


def _validate_partial(cfg) -> None:
    if cfg.partitions_per_worker < cfg.n_stragglers + 2:
        raise ValueError(
            "partial schemes need partitions_per_worker >= n_stragglers+2"
        )


def _validate_frc(cfg) -> None:
    # the reference guard (src/replication.py:24-26), surfaced at CONFIG
    # time: frc_layout raises the same constraint deep inside layout
    # construction, which is too late for callers picking a worker count
    # online (elastic re-layout onto W' survivors) — they need the
    # violated invariant named before any compute is spent
    if cfg.n_workers % (cfg.n_stragglers + 1):
        raise ValueError(
            f"scheme={cfg.scheme.value!r} needs (n_stragglers+1) | "
            f"n_workers for its fractional-repetition layout (reference "
            f"guard src/replication.py:24-26); got n_workers="
            f"{cfg.n_workers}, n_stragglers={cfg.n_stragglers}"
        )


def _validate_deadline(cfg) -> None:
    if cfg.deadline is None or cfg.deadline <= 0:
        raise ValueError(
            "scheme='deadline' needs a positive deadline "
            f"(got {cfg.deadline!r})"
        )


# ---------------------------------------------------------------------------
# host collection rules needing argument guards (the old dispatch's checks)
# ---------------------------------------------------------------------------


def _sched_agc(t, layout, *, num_collect=None, deadline=None):
    from erasurehead_tpu.parallel import collect

    if num_collect is None:
        raise ValueError("AGC needs num_collect")
    return collect.collect_agc(t, layout.groups, num_collect)


def _sched_randreg(t, layout, *, num_collect=None, deadline=None):
    from erasurehead_tpu.parallel import collect

    if num_collect is None:
        raise ValueError("randreg needs num_collect")
    return collect.collect_first_k_optimal(t, layout.B, num_collect)


def _sched_deadline(t, layout, *, num_collect=None, deadline=None):
    from erasurehead_tpu.parallel import collect

    if deadline is None:
        raise ValueError("deadline scheme needs a deadline")
    return collect.collect_deadline(t, deadline)


def _sched(fn_name):
    """Host rule passthrough: resolve parallel.collect.<fn_name> lazily so
    this module imports without pulling the jax-heavy stack."""

    def rule(t, layout, *, num_collect=None, deadline=None, _n=fn_name):
        from erasurehead_tpu.parallel import collect

        fn = getattr(collect, _n)
        if _n == "collect_all":
            return fn(t)
        if _n == "collect_first_k_mds":
            return fn(t, layout.B, layout.n_stragglers)
        if _n == "collect_frc":
            return fn(t, layout.groups)
        if _n == "collect_avoidstragg":
            return fn(t, layout.n_stragglers)
        raise AssertionError(_n)

    return rule


def _sched_partial(variant):
    def rule(t, layout, *, num_collect=None, deadline=None):
        from erasurehead_tpu.parallel import collect

        return collect.collect_partial(t, layout, variant)

    return rule


# ---------------------------------------------------------------------------
# the nine builtins
# ---------------------------------------------------------------------------

NAIVE = register(SchemeDescriptor(
    name="naive",
    summary="uncoded synchronous GD: wait for all W workers (src/naive.py)",
    build_layout=lambda cfg: codes.uncoded_layout(cfg.n_workers),
    build_schedule=_sched("collect_all"),
    dynamic_rule=_dyn_naive,
    feasibility=lambda layout, dead, *, num_collect=None: (
        _alive_cnt(dead) == dead.shape[1], "needs all W workers"
    ),
    optimal_decode=lstsq_optimal_decode,
    exact=True,
    artifact_straggler_suffix=False,  # "naive_acc", no _<s> (src/naive.py:203)
    builtin=True,
))

CYCLIC_MDS = register(SchemeDescriptor(
    name="cyccoded",
    summary="exact gradient coding, cyclic MDS code (src/coded.py)",
    build_layout=lambda cfg: codes.cyclic_mds_layout(
        cfg.n_workers, cfg.n_stragglers, seed=cfg.seed
    ),
    build_schedule=_sched("collect_first_k_mds"),
    dynamic_rule=_dyn_cyclic_mds,
    feasibility=lambda layout, dead, *, num_collect=None: (
        _alive_cnt(dead) >= dead.shape[1] - layout.n_stragglers,
        f"needs first {layout.n_workers - layout.n_stragglers} arrivals",
    ),
    optimal_decode=lstsq_optimal_decode,
    exact=True,
    seed_dependent_layout=True,
    artifact_stem="coded_acc",  # src/coded.py:250-254
    builtin=True,
))

FRC = register(SchemeDescriptor(
    name="repcoded",
    summary="exact coding, fractional repetition groups (src/replication.py)",
    build_layout=lambda cfg: codes.frc_layout(
        cfg.n_workers, cfg.n_stragglers
    ),
    build_schedule=_sched("collect_frc"),
    dynamic_rule=_dyn_frc,
    feasibility=lambda layout, dead, *, num_collect=None: (
        _all_groups_alive(layout, dead), "needs one arrival per group"
    ),
    optimal_decode=lstsq_optimal_decode,
    exact=True,
    validate_config=_validate_frc,
    artifact_stem="replication_acc",  # src/replication.py
    builtin=True,
))

APPROX = register(SchemeDescriptor(
    name="approx",
    summary=(
        "approximate gradient coding: first num_collect arrivals, group "
        "erasures (src/approximate_coding.py)"
    ),
    build_layout=lambda cfg: codes.frc_layout(
        cfg.n_workers, cfg.n_stragglers
    ),
    build_schedule=_sched_agc,
    dynamic_rule=_dyn_agc,
    feasibility=lambda layout, dead, *, num_collect=None: (
        (_feas_agc(layout, dead, num_collect)),
        f"needs {num_collect} arrivals or full group coverage",
    ),
    optimal_decode=lstsq_optimal_decode,
    needs_num_collect=True,
    # AGC's decode is already approximate (group erasures) — ErasureHead's
    # decay-rate analysis absorbs a tau=1-stale gradient the same way it
    # absorbs the erasure noise, so pipelined dispatch is sound here
    staleness_tolerant=True,
    config_fields=("num_collect",),
    validate_config=_validate_frc,  # AGC shares FRC's grouped layout
    # the straggler sweep's "interesting regime collects fewer than all"
    sweep_num_collect=lambda n_workers: n_workers // 2,
    builtin=True,
))


def _feas_agc(layout, dead, num_collect):
    if num_collect is None:
        raise ValueError("AGC needs num_collect")
    return (_alive_cnt(dead) >= num_collect) | _all_groups_alive(layout, dead)


AVOID_STRAGGLERS = register(SchemeDescriptor(
    name="avoidstragg",
    summary=(
        "ignore-stragglers baseline: first W-s uncoded gradients, W/(W-s) "
        "rescale (src/avoidstragg.py)"
    ),
    build_layout=lambda cfg: codes.uncoded_layout(
        cfg.n_workers, n_stragglers=cfg.n_stragglers
    ),
    build_schedule=_sched("collect_avoidstragg"),
    dynamic_rule=_dyn_avoidstragg,
    feasibility=lambda layout, dead, *, num_collect=None: (
        _alive_cnt(dead) >= dead.shape[1] - layout.n_stragglers,
        f"needs first {layout.n_workers - layout.n_stragglers} arrivals",
    ),
    optimal_decode=lstsq_optimal_decode,
    staleness_tolerant=True,  # rescaled-subset gradient: already approximate
    builtin=True,
))

RANDOM_REGULAR = register(SchemeDescriptor(
    name="randreg",
    summary=(
        "sparse random d-regular code with lstsq-optimal decoding "
        "(arXiv:1711.06771 + 2006.09638)"
    ),
    build_layout=lambda cfg: codes.random_regular_layout(
        cfg.n_workers, cfg.n_stragglers, seed=cfg.seed
    ),
    build_schedule=_sched_randreg,
    dynamic_rule=_dyn_randreg,
    feasibility=lambda layout, dead, *, num_collect=None: (
        _feas_randreg(dead, num_collect),
        f"needs first {num_collect} arrivals",
    ),
    optimal_decode=lstsq_optimal_decode,
    needs_num_collect=True,
    staleness_tolerant=True,  # lstsq decode over a partial set: approximate
    config_fields=("num_collect",),
    seed_dependent_layout=True,
    builtin=True,
))


def _feas_randreg(dead, num_collect):
    if num_collect is None:
        raise ValueError("randreg needs num_collect")
    return _alive_cnt(dead) >= num_collect


def _first_k_optimal_family(
    name, summary, build_layout, *, seed_dependent,
):
    """The shared descriptor shape of the sparse-code families (randreg /
    sparsegraph / expander): 0/1-incidence layouts whose collection rule
    is first-``num_collect`` arrivals with the lstsq-optimal combination
    over the received rows of B (arXiv 2006.09638), graceful-degradation
    approximate, exact at full collection (w = 1/d)."""

    def _sched(t, layout, *, num_collect=None, deadline=None):
        from erasurehead_tpu.parallel import collect

        if num_collect is None:
            raise ValueError(f"{name} needs num_collect")
        return collect.collect_first_k_optimal(t, layout.B, num_collect)

    def _dyn(layout, *, num_collect=None, deadline=None):
        import jax.numpy as jnp

        from erasurehead_tpu.parallel import dynamic

        if num_collect is None:
            raise ValueError(f"{name} needs num_collect")
        B = jnp.asarray(layout.B, jnp.float32)
        table = _mds_table_or_warn(
            name, layout, layout.n_workers - num_collect, exact_only=True
        )
        return lambda t: dynamic._first_k_lstsq_jnp(
            t, B, num_collect, decode_table=table
        )

    return register(SchemeDescriptor(
        name=name,
        summary=summary,
        build_layout=build_layout,
        build_schedule=_sched,
        dynamic_rule=_dyn,
        feasibility=lambda layout, dead, *, num_collect=None: (
            _feas_randreg(dead, num_collect),
            f"needs first {num_collect} arrivals",
        ),
        optimal_decode=lstsq_optimal_decode,
        needs_num_collect=True,
        # first-k + lstsq over whatever arrived: approximate by design,
        # so the family tolerates the tau=1 staleness noise source too
        staleness_tolerant=True,
        config_fields=("num_collect",),
        seed_dependent_layout=seed_dependent,
        # the same "interesting regime collects fewer than all" default
        # the straggler sweep applies to the other first-k families
        sweep_num_collect=lambda n_workers: n_workers // 2,
        builtin=True,
    ))


SPARSE_GRAPH = _first_k_optimal_family(
    "sparsegraph",
    (
        "sparse random bipartite-graph code with lstsq-optimal decoding "
        "(arXiv:1711.06771 + 2006.09638): partition-regular, ragged "
        "worker loads"
    ),
    lambda cfg: codes.sparse_graph_layout(
        cfg.n_workers, cfg.n_stragglers, seed=cfg.seed
    ),
    seed_dependent=True,
)

EXPANDER = _first_k_optimal_family(
    "expander",
    (
        "deterministic circulant expander-style code with lstsq decoding "
        "(arXiv:1707.03858): evenly spread cyclic chords, seed-free "
        "layout"
    ),
    lambda cfg: codes.expander_layout(cfg.n_workers, cfg.n_stragglers),
    seed_dependent=False,
)


DEADLINE = register(SchemeDescriptor(
    name="deadline",
    summary=(
        "deadline collection: whatever arrived by the cutoff, W/collected "
        "rescale (beyond the reference)"
    ),
    build_layout=lambda cfg: codes.uncoded_layout(cfg.n_workers),
    build_schedule=_sched_deadline,
    dynamic_rule=_dyn_deadline,
    feasibility=lambda layout, dead, *, num_collect=None: (
        np.ones(dead.shape[0], dtype=bool),
        "deadline collection always completes",
    ),
    optimal_decode=lstsq_optimal_decode,
    needs_deadline=True,
    staleness_tolerant=True,  # deadline-subset rescale: already approximate
    config_fields=("deadline",),
    validate_config=_validate_deadline,
    builtin=True,
))

PARTIAL_CYCLIC = register(SchemeDescriptor(
    name="partialcyccoded",
    summary=(
        "two-part partial MDS: unique uncoded slots + cyclic coded band "
        "(src/partial_coded.py)"
    ),
    build_layout=lambda cfg: codes.partial_cyclic_layout(
        cfg.n_workers, cfg.partitions_per_worker, cfg.n_stragglers,
        seed=cfg.seed,
    ),
    build_schedule=_sched_partial("mds"),
    dynamic_rule=_dyn_partial_cyclic,
    feasibility=lambda layout, dead, *, num_collect=None: (
        _alive_cnt(dead) == dead.shape[1],
        "needs every worker's uncoded first-part",
    ),
    optimal_decode=None,  # separate slots sit outside the message weights
    exact=True,
    partial=True,
    seed_dependent_layout=True,
    supports_measured=False,  # two-part send has no single-message timing
    config_fields=("partitions_per_worker",),
    validate_config=_validate_partial,
    artifact_stem="partialcoded",  # src/partial_coded.py (stem bug fixed)
    builtin=True,
))

PARTIAL_FRC = register(SchemeDescriptor(
    name="partialrepcoded",
    summary=(
        "two-part partial FRC: unique uncoded slots + replicated coded "
        "band (src/partial_replication.py)"
    ),
    build_layout=lambda cfg: codes.partial_frc_layout(
        cfg.n_workers, cfg.partitions_per_worker, cfg.n_stragglers
    ),
    build_schedule=_sched_partial("frc"),
    dynamic_rule=_dyn_partial_frc,
    feasibility=lambda layout, dead, *, num_collect=None: (
        _alive_cnt(dead) == dead.shape[1],
        "needs every worker's uncoded first-part",
    ),
    optimal_decode=None,
    exact=True,
    partial=True,
    supports_measured=False,
    config_fields=("partitions_per_worker",),
    validate_config=_validate_partial,
    artifact_stem="partialreplication",  # src/partial_replication.py
    builtin=True,
))
