"""erasurehead-tpu: straggler-tolerant distributed GD via gradient coding, TPU-native.

A from-scratch JAX/XLA re-design of the capabilities of
Distributed-Deep-Learning/ErasureHead (arXiv 1901.09671): a master/worker MPI
research framework for coded gradient descent under stragglers. Here the MPI
point-to-point protocol becomes jit-compiled SPMD over a `jax.sharding.Mesh`
("workers" axis), the first-k Waitany collection becomes fixed-shape masked
collectives driven by a seeded straggler-arrival simulator, and the host-side
lstsq decode becomes an on-device masked solve + einsum.

Layout:
  ops/       coding-theory core (layouts, generator matrices, decode weights)
             and TPU-friendly sparse feature ops
  models/    per-partition gradient kernels: logistic / linear GLMs, MLP
             (tensor-parallel), attention classifier (sequence-parallel),
             deep MLP (pipeline-parallel), soft MoE (expert-parallel);
             losses and metrics
  parallel/  mesh + collective step, straggler arrival simulation, collection
             rules (the scheme layer), failure handling / elastic recovery,
             ring + all-to-all sequence parallelism, distributed backend init
  data/      synthetic GMM + real-dataset preprocessing, partitioning, disk IO
  train/     GD/AGD optimizer, scan-based trainer, post-hoc evaluation replay,
             result artifacts, checkpointing
  schemes/   the declarative scheme registry: a scheme = layout builder +
             collection rules + capability flags, entry-point-discoverable
             for third-party codes (group "erasurehead_tpu.schemes")
  adapt/     online straggler-adaptive collection: a seeded bandit over
             registry-compatible (scheme, collect, deadline) arms reading
             the run's own decode-error + arrival telemetry
  utils/     typed config, determinism audit, profiler tracing
"""

__version__ = "0.1.0"

from erasurehead_tpu.utils.config import (  # noqa: F401
    ComputeMode,
    ModelKind,
    RunConfig,
    Scheme,
    UpdateRule,
)


def train(cfg, dataset, **kw):
    """Convenience re-export of train.trainer.train (lazy: importing the
    package must not pull in jax)."""
    from erasurehead_tpu.train import trainer

    return trainer.train(cfg, dataset, **kw)


def train_dynamic(cfg, dataset, **kw):
    """Convenience re-export of train.trainer.train_dynamic."""
    from erasurehead_tpu.train import trainer

    return trainer.train_dynamic(cfg, dataset, **kw)


def train_measured(cfg, dataset, **kw):
    """Convenience re-export of train.trainer.train_measured (real
    per-worker arrival timing feeding the collection rules)."""
    from erasurehead_tpu.train import trainer

    return trainer.train_measured(cfg, dataset, **kw)


def train_elastic(cfg, dataset, deaths, **kw):
    """Convenience re-export of parallel.failures.train_elastic (re-shard
    onto the survivors after permanent worker deaths and keep training)."""
    from erasurehead_tpu.parallel import failures

    return failures.train_elastic(cfg, dataset, deaths, **kw)


def train_adaptive(cfg, dataset, **kw):
    """Convenience re-export of adapt.train_adaptive (chunk-boundary
    bandit over registry-compatible collection policies)."""
    from erasurehead_tpu import adapt

    return adapt.train_adaptive(cfg, dataset, **kw)
