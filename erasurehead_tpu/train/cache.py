"""Sweep engine caches: run-to-run executable and device-data reuse.

The experiment harness (train/experiments.compare / straggler_sweep /
baseline_suite) races many configs over the SAME dataset, mesh, shapes and
lowering — only the per-round weight tables differ, and those are ordinary
traced *arguments* of the training scan. Historically every `train()` call
still recompiled the full scan (its jit lived in a closure, so jit's own
cache could never hit) and re-stacked + re-uploaded the worker stacks. At
paper-scale shapes compile time dominates sweep wall-clock.

Two module-level caches fix that:

  - the **executable cache** maps a hashable static signature — everything
    that changes the lowering: model kind, resolved gradient lowering
    (parallel/step's resolve_flat_grad / resolve_margin_flat / pallas
    gates), mesh axes + devices, stack shapes/dtypes, optimizer family,
    scan_unroll, scan length — to the AOT-compiled scan. The Nth run of a
    signature skips tracing, compilation, and the warm-up execution.
  - the **data cache** maps (dataset identity, layout stacking signature,
    mesh, data dtype, sparse format, compute mode) to the device-resident
    ShardedData, so repeated runs reuse the uploaded worker/partition
    stacks instead of re-stacking and re-transferring.

Correctness: a cached executable was compiled from an identical lowering,
so cached and fresh runs are **bitwise identical** (pinned in
tests/test_sweep_cache.py). Anything that changes the compiled program must
be part of the key — when adding a lowering knob, add it to
RunConfig.static_signature() or the trainer-side resolved tuple.

Disable with ``ERASUREHEAD_SWEEP_CACHE=0`` in the env, ``--sweep-cache
off`` on the CLI, or :func:`set_enabled`. Telemetry (hits/misses, compile
seconds saved, bytes not re-uploaded) lands in ``TrainResult.cache_info``
and the experiment rows.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
from collections import OrderedDict
from typing import Any, Callable

import jax
import numpy as np

from erasurehead_tpu.obs.metrics import REGISTRY as _METRICS

#: LRU bounds — sweeps cycle over a handful of signatures; the caps only
#: guard against unbounded growth in long-lived servers.
EXEC_CACHE_MAX = 32
DATA_CACHE_MAX = 8


class CacheStats:
    """Cumulative cache telemetry (process lifetime; reset via clear()).

    A live VIEW over the ``sweep_cache.*`` counters in the obs metrics
    registry (obs/metrics.py) — the cache reports through the registry
    like every other telemetry source, and this class keeps the historical
    attribute/snapshot() interface the trainers and tests consume.

    Fields: ``exec_hits`` / ``exec_misses`` / ``data_hits`` /
    ``data_misses``; ``compile_seconds_saved`` — compile+warmup seconds
    NOT spent thanks to executable hits (each hit credits the measured
    cost of the miss that populated its entry); ``bytes_reused`` — device
    bytes NOT re-uploaded thanks to data hits.
    """

    FIELDS = (
        "exec_hits", "exec_misses", "data_hits", "data_misses",
        "compile_seconds_saved", "bytes_reused",
    )

    @staticmethod
    def counter(field: str):
        if field not in CacheStats.FIELDS:
            raise AttributeError(field)
        return _METRICS.counter(f"sweep_cache.{field}")

    def __getattr__(self, name: str):
        return CacheStats.counter(name).value

    def snapshot(self) -> dict:
        return {f: CacheStats.counter(f).value for f in self.FIELDS}

    def reset(self) -> None:
        for f in self.FIELDS:
            CacheStats.counter(f).reset()


_stats = CacheStats()
#: key -> (executable, compile_seconds)
_exec_cache: "OrderedDict[Any, tuple[Any, float]]" = OrderedDict()
#: key -> (ShardedData, device_bytes)
_data_cache: "OrderedDict[Any, tuple[Any, int]]" = OrderedDict()

_enabled = os.environ.get("ERASUREHEAD_SWEEP_CACHE", "1").lower() not in (
    "0", "off", "false",
)

_token_counter = itertools.count()


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def clear() -> None:
    """Drop both caches and reset the counters (tests; memory pressure)."""
    _exec_cache.clear()
    _data_cache.clear()
    _stats.reset()
    from erasurehead_tpu.obs import detect

    # the caches ARE the detector's notion of "already compiled in-process"
    detect.reset()


def stats() -> CacheStats:
    return _stats


def data_cache_bytes() -> int:
    """Device bytes currently pinned by the data cache's entries — what a
    long-lived process (the serve daemon's admission controller,
    serve/admission.py) counts against its HBM budget alongside in-flight
    dispatches, and what :func:`drop_data_cache` would release."""
    return sum(nbytes for _, nbytes in _data_cache.values())


def drop_data_cache() -> int:
    """Release the data cache's references to device-resident stacks;
    returns the device bytes whose cache pin was dropped (counted in
    ``sweep_cache.data_dropped_bytes``).

    The memory-pressure response: after a RESOURCE_EXHAUSTED cohort
    dispatch, the sweep's degradation guard (experiments._dispatch_cohort)
    calls this before retrying the bisected halves, so the retries don't
    contend with HBM pinned by stacks no live run is using. Stacks still
    referenced by an in-flight run stay alive (jax Arrays are refcounted);
    only the cache's own pins go."""
    released = sum(nbytes for _, nbytes in _data_cache.values())
    _data_cache.clear()
    _METRICS.counter("sweep_cache.data_dropped_bytes").inc(released)
    return released


# ---------------------------------------------------------------------------
# persistent (on-disk) compilation cache: warm restarts for long-lived
# daemons. The in-process executable cache above dies with the process;
# routing XLA compiles through JAX's on-disk compilation cache makes the
# restarted process's "misses" disk hits — the serve daemon re-serves its
# working set with zero fresh backend compiles (the restart-under-load
# contract in tests/test_serve.py and the serve_load bench extra). The
# disk key is JAX's hash of the lowered computation + compile options +
# backend, a superset of our lowering signature, so a disk hit is exactly
# as bitwise-safe as an in-process hit.


#: directory of the process's persistent XLA compilation cache, once
#: enabled (None = never enabled). Read by trainer._resolve_donate:
#: donation must not combine with possibly-deserialized executables.
_PERSISTENT_CACHE_DIR: str | None = None


def persistent_compilation_cache_dir() -> str | None:
    """The persistent compilation cache directory this process routes
    compiles through, or None if :func:`enable_persistent_compilation_cache`
    was never called."""
    return _PERSISTENT_CACHE_DIR


def enable_persistent_compilation_cache(directory: str) -> str:
    """Route this process's XLA compiles through JAX's on-disk
    compilation cache at ``directory`` (created if absent). Thresholds
    are zeroed so even sub-second CPU test compiles persist — a daemon's
    working set is warm because it was WRITTEN, not because it was slow.
    Returns the directory."""
    import jax

    global _PERSISTENT_CACHE_DIR
    _PERSISTENT_CACHE_DIR = directory
    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # the cache module latches its config at the process's FIRST compile
    # (jax 0.4.x: _cache_initialized); a daemon enabling the dir after
    # any compile has happened must reset it or every write is silently
    # skipped. Public alias first, private fallback, best-effort.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — older/newer layouts
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001
            pass
    _METRICS.counter("sweep_cache.persistent_enabled").inc()
    return directory


def persistent_cache_entries(directory: str) -> int:
    """How many compiled executables the on-disk cache holds (its
    ``*-cache`` payload files; ``*-atime`` bookkeeping files don't
    count). The restart-under-load tests pin this delta to ZERO across a
    warm restart — the re-served working set added no fresh compiles."""
    if not os.path.isdir(directory):
        return 0
    return sum(
        1 for name in os.listdir(directory) if name.endswith("-cache")
    )


# ---------------------------------------------------------------------------
# key builders


def dataset_token(dataset) -> Any:
    """Stable identity token for a dataset object.

    Content-hashing paper-scale arrays would cost more than the upload the
    cache avoids; instead the first sighting brands the OBJECT with a
    process-unique token (plain ``id()`` is unsafe — ids get reused after
    GC). An object that refuses attributes (slots/frozen) is uncacheable:
    returns a fresh token every call, turning the cache into a no-op for
    it rather than a correctness hazard."""
    tok = getattr(dataset, "_sweep_cache_token", None)
    if tok is None:
        tok = next(_token_counter)
        try:
            dataset._sweep_cache_token = tok
        except (AttributeError, TypeError):
            return next(_token_counter)
    return tok


def layout_stack_signature(layout, *, worker_major: bool) -> tuple:
    """Content signature of the device stack a (layout, stacking mode)
    materializes — the data-cache key component AND the cohort grouping
    key (trainer.train_cohort / cohort_signature).

    Partition-major stacking (deduped mode, ring faithful) reads only
    ``n_partitions`` — it is scheme-independent, which is the structural
    fact that lets a whole multi-scheme compare() share one upload and one
    batched dispatch. Worker-major stacking (materialized faithful)
    gathers through ``layout.assignment``, so its CONTENT keys the stack:
    schemes sharing an assignment (FRC and AGC) share a stack; cyclic MDS
    has its own.
    """
    if worker_major:
        assignment = np.asarray(layout.assignment)
        return ("workers", assignment.shape, assignment.tobytes())
    return ("parts", int(layout.n_partitions))


def mesh_signature(mesh) -> tuple:
    """Axes, sizes, and the exact device assignment (executables bind
    input shardings to concrete devices)."""
    return (
        tuple(mesh.axis_names),
        tuple(int(mesh.shape[a]) for a in mesh.axis_names),
        tuple(int(d.id) for d in np.asarray(mesh.devices).flat),
    )


def tree_signature(tree) -> tuple:
    """Treedef + per-leaf (shape, dtype) — the aval part of a jit key."""
    leaves, treedef = jax.tree.flatten(tree)
    return (
        str(treedef),
        tuple(
            (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", type(l))))
            for l in leaves
        ),
    )


def device_nbytes(obj) -> int:
    """Total device bytes of the jax Arrays inside ``obj`` — which may be
    a plain (unregistered) dataclass like ShardedData, so unpack its
    fields before the pytree walk. Public: the trainers report it as the
    per-run ``stack_bytes`` telemetry (the number that drops (s+1)x under
    stack_mode="ring")."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        parts = [getattr(obj, f.name) for f in dataclasses.fields(obj)]
    else:
        parts = [obj]
    return sum(
        int(l.nbytes)
        for part in parts
        for l in jax.tree.leaves(part)
        if isinstance(l, jax.Array)
    )


# ---------------------------------------------------------------------------
# lookups


def get_or_build_data(key, build: Callable[[], Any]):
    """ShardedData for ``key``, building (stack + upload) on miss.

    Returns ``(data, hit)``. jax Arrays are immutable, so sharing one
    ShardedData across runs is safe."""
    if not _enabled or key is None:
        return build(), False
    if key in _data_cache:
        data, nbytes = _data_cache[key]
        _data_cache.move_to_end(key)
        CacheStats.counter("data_hits").inc()
        CacheStats.counter("bytes_reused").inc(nbytes)
        return data, True
    data = build()
    CacheStats.counter("data_misses").inc()
    _data_cache[key] = (data, device_nbytes(data))
    while len(_data_cache) > DATA_CACHE_MAX:
        _data_cache.popitem(last=False)
    return data, False


def get_or_compile(key, compile_fn: Callable[[], tuple[Any, float]]):
    """Compiled scan executable for ``key``.

    ``compile_fn`` runs on miss and returns ``(executable,
    compile_seconds)`` — the measured trace+compile+warmup cost, credited
    to ``compile_seconds_saved`` on every later hit. Returns
    ``(executable, hit)``."""
    if not _enabled:
        return compile_fn()[0], False
    if key in _exec_cache:
        ex, secs = _exec_cache[key]
        _exec_cache.move_to_end(key)
        CacheStats.counter("exec_hits").inc()
        CacheStats.counter("compile_seconds_saved").inc(secs)
        return ex, True
    ex, secs = compile_fn()
    CacheStats.counter("exec_misses").inc()
    _exec_cache[key] = (ex, secs)
    while len(_exec_cache) > EXEC_CACHE_MAX:
        _exec_cache.popitem(last=False)
    return ex, False
