"""Result artifacts: the five per-run files the reference saves.

Every reference run writes, into ``<input_dir>/results/``
(src/naive.py:200-208, src/coded.py:246-254):

  <prefix>_training_loss.dat   per-iteration train loss
  <prefix>_testing_loss.dat    per-iteration test loss
  <prefix>_auc.dat             per-iteration test AUC
  <prefix>_timeset.dat         per-iteration wall-clock
  <prefix>_worker_timeset.dat  [rounds x W] per-worker arrival latencies

We keep the same five files and naming skeleton so reference-side analysis
scripts keep working, with deviations (documented, SURVEY.md §2.5):
  - every scheme gets its own prefix — the reference saves AGC under
    ``replication_acc_*`` (src/approximate_coding.py:259-263, clobbering
    EGC-FRC results) and partial-coded's training loss under a
    ``partialreplication_`` prefix (src/partial_coded.py:286);
  - full float precision — the reference's save_vector truncates to 3
    decimals (src/util.py:32-36);
  - a run_manifest.json capturing the full config (the reference encodes
    only n_stragglers in the filename).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

import numpy as np

from erasurehead_tpu.obs.events import arrival_summary
from erasurehead_tpu.train.evaluate import EvalResult
from erasurehead_tpu.train.trainer import TrainResult
from erasurehead_tpu.utils.config import RunConfig

def run_prefix(cfg: RunConfig) -> str:
    """Reference filename prefix, from the scheme's registry descriptor
    (``artifact_stem`` / ``artifact_straggler_suffix`` / ``partial`` —
    matching src/naive.py:203-208 "naive_acc", src/coded.py:250-254
    "coded_acc_%d", partial schemes "<name>_%d_%d", with the reference's
    two stem-clobbering filename bugs fixed; see schemes/builtin.py).
    Schemes registered after this writer was written — sparsegraph,
    expander, entry-point third parties — get "<name>_acc" stems by
    construction instead of a KeyError: the registry, not a table here,
    is the source of scheme behavior."""
    from erasurehead_tpu import schemes

    desc = schemes.get(cfg.scheme)
    stem = desc.artifact_stem or f"{desc.name}_acc"
    if desc.partial:
        return f"{stem}_{cfg.n_stragglers}_{cfg.partitions_per_worker}"
    if not desc.artifact_straggler_suffix:
        return stem
    return f"{stem}_{cfg.n_stragglers}"


def save_vector(v: np.ndarray, path: str) -> None:
    """One value per line (text, like the reference's .dat files but full
    precision — src/util.py:32-36 rounds to 3 decimals)."""
    np.savetxt(path, np.asarray(v).reshape(-1), fmt="%.18g")


def save_matrix(m: np.ndarray, path: str) -> None:
    np.savetxt(path, np.asarray(m), fmt="%.18g")


def write_run_artifacts(
    result: TrainResult,
    ev: Optional[EvalResult],
    output_dir: str,
) -> dict:
    """Write the five reference artifacts + manifest; returns paths."""
    cfg: RunConfig = result.config
    prefix = run_prefix(cfg)
    os.makedirs(output_dir, exist_ok=True)
    paths = {}

    def emit(name, saver, data):
        path = os.path.join(output_dir, f"{prefix}_{name}.dat")
        saver(data, path)
        paths[name] = path

    # A resumed run's history (and hence the eval curves) covers rounds
    # [start_round, rounds) while the precomputed clocks cover the full run;
    # slice the clocks to the same window so row i of EVERY artifact is
    # round start_round + i (recorded in the manifest).
    sr = result.start_round
    if ev is not None:
        emit("training_loss", save_vector, ev.training_loss)
        emit("testing_loss", save_vector, ev.testing_loss)
        emit("auc", save_vector, ev.auc)
    emit("timeset", save_vector, result.timeset[sr:])
    emit("worker_timeset", save_matrix, result.worker_times[sr:])

    def jsonable(v):
        if hasattr(v, "value"):  # enums
            return v.value
        if isinstance(v, np.ndarray):
            return v.tolist()
        return v

    manifest = {
        "config": {
            k: jsonable(v) for k, v in dataclasses.asdict(cfg).items()
        },
        # sim_total_time covers the FULL precomputed schedule; for resumed
        # runs the emitted artifacts cover [start_round, rounds), whose
        # simulated clock is window_sim_total_time (== sum of the timeset
        # artifact's rows)
        "sim_total_time": result.sim_total_time,
        "window_sim_total_time": float(np.sum(result.timeset[sr:])),
        "start_round": sr,
        "wall_time": result.wall_time,
        "steps_per_sec": result.steps_per_sec,
        "n_train": result.n_train,
        # straggler-arrival latency stats over the emitted window, with
        # the -1 never-arrived sentinel MASKED OUT (obs/events.py): a
        # deadline/failover run where some workers never arrive must not
        # average sentinels into its latency quantiles
        "arrival": arrival_summary(result.worker_times[sr:]),
        "artifacts": paths,
    }
    if result.decode_error is not None:
        err = np.asarray(result.decode_error[sr:], dtype=np.float64)
        manifest["decode_error_mean"] = (
            float(err.mean()) if err.size else 0.0
        )
        manifest["decode_error_max"] = float(err.max()) if err.size else 0.0
    mpath = os.path.join(output_dir, f"{prefix}_run_manifest.json")
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=2, default=str)
    paths["manifest"] = mpath
    return paths


def print_iteration_table(result: TrainResult, ev: EvalResult) -> None:
    """The reference's per-iteration eval printout (src/naive.py:198).

    Rows are labeled with true round numbers: a resumed run's eval curves
    start at result.start_round, and the clocks are indexed to match.
    Per-iteration arrival latency averages only the workers that actually
    arrived — the -1 never-arrived sentinel is masked, never averaged in
    (regression: tests/test_telemetry.py's deadline case)."""
    sr = result.start_round
    for i in range(len(ev.training_loss)):
        line = (
            f"Iteration {sr + i}: Train Loss = {ev.training_loss[i]:.5f}, "
            f"Test Loss = {ev.testing_loss[i]:.5f}"
        )
        if not np.isnan(ev.auc[i]):
            line += f", AUC = {ev.auc[i]:.5f}"
        line += f", Sim time = {result.timeset[sr + i]:.4f}s"
        wt = np.asarray(result.worker_times[sr + i], dtype=np.float64)
        arrived = wt[wt >= 0.0]
        if arrived.size:
            line += (
                f", Mean arrival = {arrived.mean():.4f}s "
                f"({arrived.size}/{wt.size})"
            )
        else:
            line += ", no arrivals"
        print(line)
    # the total matches the rows just printed (the resumed window, when
    # start_round > 0 — result.sim_total_time covers the full schedule)
    print(
        f"Total simulated time: {float(np.sum(result.timeset[sr:])):.3f}s | "
        f"real wall {result.wall_time:.3f}s | "
        f"{result.steps_per_sec:.1f} steps/s"
    )
