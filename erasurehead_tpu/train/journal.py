"""Sweep journal: per-trajectory persistence + resume for the sweep runner.

The sweep engine's central artifact — a multi-scheme/multi-seed comparison
— used to be all-or-nothing: one preemption, OOM, or diverging trajectory
and the whole ``experiments.compare`` loop died with nothing persisted.
This module journals each trajectory's finished summary row AS IT
COMPLETES, into an append-only JSONL file written through the obs event
machinery (obs/events.EventLogger — same envelope, flushed per line, and
schema-checked by the same validator as every other event log).

Each ``sweep_trajectory`` record carries:

  - ``key``    — the trajectory's identity: a digest over the row label,
                 the FULL RunConfig (obs/events.config_hash — a superset of
                 ``RunConfig.static_signature``), the dataset content
                 digest, and the arrival-schedule digest. A resumed sweep
                 only reuses a row when all four match — change a seed, a
                 dataset, or the delay stream and the trajectory re-runs;
  - ``status`` — ``"ok"`` or ``"diverged"`` (divergence is deterministic
                 under the key, so diverged rows resume as diverged rather
                 than burning the rounds again);
  - ``row``    — the full UNROUNDED RunSummary payload (loss curves and
                 clocks with their dtypes), so a rehydrated row is
                 bit-identical to the one the interrupted run computed:
                 JSON float round-trips are exact (repr round-trip), and
                 arrays restore to their original dtype.

Enable by passing a :class:`SweepJournal` to ``experiments.compare`` /
``straggler_sweep`` / ``baseline_suite`` (the CLIs expose
``--sweep-journal DIR`` / ``--resume-sweep``), or ambiently via
``ERASUREHEAD_SWEEP_JOURNAL=DIR`` (+ ``ERASUREHEAD_RESUME_SWEEP=1``) —
:func:`from_env` hands every sweep entry point one shared process journal.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional

import numpy as np

from erasurehead_tpu.obs import events as events_lib
from erasurehead_tpu.obs.metrics import REGISTRY as _METRICS

#: journal file name inside the journal directory
JOURNAL_NAME = "sweep_journal.jsonl"

#: arrays larger than this are digested by a strided sample + exact shape/
#: dtype/checksums instead of full bytes (hashing a paper-scale matrix
#: would cost more than the sweep step the journal is protecting)
_FULL_HASH_MAX_BYTES = 64 * 1024 * 1024

#: RunSummary fields persisted verbatim (floats/str/None/dict — JSON
#: round-trips them exactly); arrays and config are handled separately
_SCALAR_FIELDS = (
    "label", "sim_total_time", "sim_steps_per_sec", "real_steps_per_sec",
    "final_train_loss", "final_test_loss", "final_auc", "time_to_target",
    "note", "suite", "cache", "decode_error_mean", "status",
)


def _hash_update_array(h, arr: np.ndarray) -> None:
    arr = np.ascontiguousarray(arr)
    h.update(str(arr.shape).encode())
    h.update(str(arr.dtype).encode())
    if arr.nbytes <= _FULL_HASH_MAX_BYTES:
        h.update(arr.tobytes())
        return
    # paper-scale: exact shape/dtype + strided sample + global checksums.
    # A probabilistic content digest — documented tradeoff: a collision
    # needs two same-shaped datasets agreeing on the sample AND the sums.
    flat = arr.reshape(-1)
    stride = max(1, flat.size * flat.itemsize // _FULL_HASH_MAX_BYTES)
    h.update(np.ascontiguousarray(flat[::stride]).tobytes())
    if np.issubdtype(arr.dtype, np.number):
        h.update(np.asarray(
            [np.float64(flat.sum(dtype=np.float64))]
        ).tobytes())


def dataset_digest(dataset) -> str:
    """Content digest of a Dataset, memoized on the object (sweeps reuse
    one dataset object; the digest is computed once per process). Sparse
    matrices digest their underlying buffers."""
    tok = getattr(dataset, "_sweep_journal_digest", None)
    if tok is not None:
        return tok
    h = hashlib.sha256()
    for name in ("X_train", "y_train", "X_test", "y_test"):
        part = getattr(dataset, name, None)
        if part is None:
            continue
        h.update(name.encode())
        if hasattr(part, "tocsr") and not isinstance(part, np.ndarray):
            csr = part.tocsr()
            for buf in (csr.data, csr.indices, csr.indptr):
                _hash_update_array(h, np.asarray(buf))
        else:
            _hash_update_array(h, np.asarray(part))
    tok = h.hexdigest()[:16]
    try:
        dataset._sweep_journal_digest = tok
    except (AttributeError, TypeError):
        pass  # uncacheable object: recompute next time
    return tok


def arrivals_digest(arrivals) -> str:
    h = hashlib.sha256()
    _hash_update_array(h, np.asarray(arrivals, dtype=np.float64))
    return h.hexdigest()[:16]


def trajectory_key(label: str, cfg, dataset, arrivals) -> str:
    """The journal identity of one sweep trajectory: label + full config
    hash + data digest + arrival digest. Anything that can change the
    row's numbers is in here — a resumed sweep can only reuse a row whose
    inputs are provably the same."""
    payload = json.dumps(
        {
            "label": label,
            "config": events_lib.config_hash(cfg),
            "data": dataset_digest(dataset),
            "arrivals": arrivals_digest(arrivals),
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


#: RunSummary.row() keys that legitimately differ between a resumed sweep
#: and an uninterrupted one: real wall-clock and cache telemetry are
#: measurements of THIS process, not of the science. Everything else —
#: labels, simulated clocks, losses, decode-error columns — must match
#: bitwise (the kill→resume invariance the chaos harness pins).
VOLATILE_ROW_KEYS = ("real_steps_per_sec", "cache")


def science_row(row: dict) -> dict:
    """A summary row with the run-local volatile keys dropped — the part
    of the row the kill→resume invariance contract covers."""
    return {k: v for k, v in row.items() if k not in VOLATILE_ROW_KEYS}


def _pack_array(arr) -> dict:
    arr = np.asarray(arr)
    return {"values": arr.tolist(), "dtype": str(arr.dtype)}


def _unpack_array(blob) -> np.ndarray:
    return np.asarray(blob["values"], dtype=np.dtype(blob["dtype"]))


def summary_payload(summary) -> dict:
    """The RunSummary -> journal ``row`` payload: every field needed to
    rebuild the summary bit-identically, UNROUNDED (``RunSummary.row()``'s
    rounding happens at render time, identically for fresh and rehydrated
    rows). ``config`` is intentionally absent — the resuming sweep supplies
    the config object, and the key already pins its content."""
    out = {f: getattr(summary, f) for f in _SCALAR_FIELDS}
    out["training_loss"] = _pack_array(summary.training_loss)
    out["timeset"] = _pack_array(summary.timeset)
    return out


def rehydrate_summary(row: dict, cfg):
    """Journal ``row`` payload -> RunSummary (import deferred: experiments
    imports this module)."""
    from erasurehead_tpu.train.experiments import RunSummary

    kw = {f: row.get(f) for f in _SCALAR_FIELDS}
    kw["training_loss"] = _unpack_array(row["training_loss"])
    kw["timeset"] = _unpack_array(row["timeset"])
    if kw.get("status") is None:
        kw["status"] = "ok"
    return RunSummary(config=cfg, **kw)


class SweepJournal:
    """Append-only sweep journal over ``<dir>/sweep_journal.jsonl``.

    ``resume=True`` makes :meth:`lookup` serve previously journaled rows;
    with ``resume=False`` the journal only records (a restart that wants a
    fresh measurement of everything can journal without skipping). The
    writer opens lazily in append mode, so constructing a journal never
    clobbers an interrupted run's records.

    Safe under CONCURRENT WRITERS — the serve daemon
    (erasurehead_tpu/serve/) journals per-tenant rows from its dispatch
    pool threads, and several processes may share one journal file:

      - within a process, a lock serializes the lazy logger open, the
        append, and the completed-map update;
      - across processes, the append-mode EventLogger (obs/events.py)
        emits each record as ONE ``write()`` on an O_APPEND fd, so
        interleaved writers produce interleaved whole LINES, never torn
        ones — every record any writer flushed survives, and a resuming
        reader sees the union (last record per key wins, as before).
    """

    def __init__(self, directory: str, resume: bool = False):
        self.directory = directory
        self.path = os.path.join(directory, JOURNAL_NAME)
        self.resume = bool(resume)
        self._logger: Optional[events_lib.EventLogger] = None
        self._completed: dict[str, dict] = {}
        self._lock = threading.Lock()
        if os.path.exists(self.path):
            self._load()

    def _load(self) -> None:
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    # a kill mid-write can leave one torn final line; every
                    # complete line before it is intact (per-line flush)
                    continue
                if (
                    isinstance(rec, dict)
                    and rec.get("type") == "sweep_trajectory"
                    and isinstance(rec.get("key"), str)
                    and isinstance(rec.get("row"), dict)
                ):
                    self._completed[rec["key"]] = rec  # last record wins

    def __len__(self) -> int:
        return len(self._completed)

    def lookup(self, key: str) -> Optional[dict]:
        """The journaled record for ``key`` (resume mode only)."""
        if not self.resume:
            return None
        return self._completed.get(key)

    def record(self, key: str, label: str, summary) -> None:
        """Append one finished trajectory. Flushed before returning — a
        kill any time after this call preserves the row. Thread-safe (see
        class docstring)."""
        payload = summary_payload(summary)
        with self._lock:
            if self._logger is None:
                self._logger = events_lib.EventLogger(self.path, mode="a")
            self._logger.emit(
                "sweep_trajectory",
                key=key,
                label=label,
                status=summary.status,
                scheme=summary.config.scheme.value,
                row=payload,
            )
            self._completed[key] = {
                "type": "sweep_trajectory", "key": key, "label": label,
                "status": summary.status, "row": payload,
            }
        _METRICS.counter("sweep_journal.records").inc()

    def close(self) -> None:
        with self._lock:
            if self._logger is not None:
                self._logger.close()
                self._logger = None


# ---------------------------------------------------------------------------
# ambient (env-driven) journal: lets EVERY sweep entry point — compare,
# straggler_sweep, baseline_suite, the CLIs — journal/resume without each
# one growing plumbing. One shared instance per (dir, resume) resolution.

_env_journal: Optional[SweepJournal] = None
_env_key: Optional[tuple] = None


def from_env() -> Optional[SweepJournal]:
    """The process's ambient journal per ``ERASUREHEAD_SWEEP_JOURNAL`` /
    ``ERASUREHEAD_RESUME_SWEEP`` (utils/config resolvers), or None when
    unset. Cached so repeated ``compare()`` calls share one writer."""
    from erasurehead_tpu.utils.config import (
        resolve_resume_sweep,
        resolve_sweep_journal,
    )

    global _env_journal, _env_key
    directory = resolve_sweep_journal()
    if directory is None:
        return None
    key = (directory, resolve_resume_sweep())
    if _env_journal is None or _env_key != key:
        if _env_journal is not None:
            _env_journal.close()
        _env_journal = SweepJournal(directory, resume=key[1])
        _env_key = key
    return _env_journal


def reset_env_journal() -> None:
    """Drop the cached ambient journal (tests)."""
    global _env_journal, _env_key
    if _env_journal is not None:
        _env_journal.close()
    _env_journal = None
    _env_key = None
