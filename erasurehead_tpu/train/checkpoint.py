"""Checkpoint/resume via orbax — a capability the reference lacks.

The reference keeps the full iterate history in master RAM and loses
everything on failure (SURVEY.md §5.4: no checkpointing anywhere; its runs
are only 100 iterations). Real pod runs preempt; this module adds
orbax-backed save/restore of the optimizer state plus the round cursor, and
the trainer exposes ``checkpoint_every`` by running its scan in chunks with
a save between chunks (chunking costs one extra dispatch per chunk, not a
recompile — the chunked scan is jitted once per chunk length).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np
import orbax.checkpoint as ocp

from erasurehead_tpu.train.optimizer import OptState


def _pack(state: OptState, next_round: int) -> dict:
    # next_round stays a host numpy scalar: a jnp.asarray here would be a
    # host-LOCAL jax array (SingleDeviceSharding), which orbax refuses to
    # serialize in a multi-process cluster — the state leaves are globally
    # replicated by the trainer, and this must not be the odd one out
    return {
        "params": state.params,
        "momentum": state.momentum,
        "next_round": np.asarray(next_round, np.int32),
    }


def save(path: str, state: OptState, next_round: int) -> None:
    """Write a checkpoint directory (overwrites)."""
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _pack(state, next_round), force=True)
    ckptr.wait_until_finished()


def restore(path: str, template_state: OptState) -> Tuple[OptState, int]:
    """Load (state, next_round); ``template_state`` supplies structure/shape."""
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    back = ckptr.restore(path, _pack(template_state, 0))
    state = OptState(params=back["params"], momentum=back["momentum"])
    return state, int(back["next_round"])


def latest(checkpoint_dir: str) -> Optional[str]:
    """Most recent ``round_<N>`` checkpoint under ``checkpoint_dir``."""
    if not os.path.isdir(checkpoint_dir):
        return None
    rounds = []
    for name in os.listdir(checkpoint_dir):
        if name.startswith("round_"):
            try:
                rounds.append((int(name.split("_", 1)[1]), name))
            except ValueError:
                continue
    if not rounds:
        return None
    return os.path.join(checkpoint_dir, max(rounds)[1])
