"""Checkpoint/resume via orbax — a capability the reference lacks.

The reference keeps the full iterate history in master RAM and loses
everything on failure (SURVEY.md §5.4: no checkpointing anywhere; its runs
are only 100 iterations). Real pod runs preempt; this module adds
orbax-backed save/restore of the optimizer state plus the round cursor, and
the trainer exposes ``checkpoint_every`` by running its scan in chunks with
a save between chunks (chunking costs one extra dispatch per chunk, not a
recompile — the chunked scan is jitted once per chunk length).

Preemptions also strike MID-save: a killed process can leave a partially
written or corrupt ``round_N`` directory that a naive "newest wins" resume
would then crash on — losing the run a checkpoint exists to protect. So
:func:`latest` structurally validates candidates (orbax's commit marker)
before returning one, and :func:`restore_latest` goes further: it attempts
the restore newest-first and falls back to the next-older checkpoint — with
a ``warning`` event and a counter per rejected candidate — when the data
itself is torn (truncated array files pass the structural check)."""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np
import orbax.checkpoint as ocp

from erasurehead_tpu.train.optimizer import OptState

#: orbax's commit marker: written when a save finalizes. A round_N
#: directory without it is a save that never completed (killed mid-write).
_COMMIT_MARKER = "_CHECKPOINT_METADATA"


def _pack(state: OptState, next_round: int) -> dict:
    # next_round stays a host numpy scalar: a jnp.asarray here would be a
    # host-LOCAL jax array (SingleDeviceSharding), which orbax refuses to
    # serialize in a multi-process cluster — the state leaves are globally
    # replicated by the trainer, and this must not be the odd one out
    return {
        "params": state.params,
        "momentum": state.momentum,
        "next_round": np.asarray(next_round, np.int32),
    }


def save(path: str, state: OptState, next_round: int) -> None:
    """Write a checkpoint directory (overwrites)."""
    from erasurehead_tpu.utils import chaos as chaos_lib

    # chaos site "checkpoint": an injected kill here is a preemption
    # mid-checkpoint — the save never commits, and resume must fall back
    # to the previous round_N (restore_latest)
    chaos_lib.maybe_fire("checkpoint")
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, _pack(state, next_round), force=True)
    ckptr.wait_until_finished()


def restore(path: str, template_state: OptState) -> Tuple[OptState, int]:
    """Load (state, next_round); ``template_state`` supplies structure/shape."""
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    back = ckptr.restore(path, _pack(template_state, 0))
    state = OptState(params=back["params"], momentum=back["momentum"])
    return state, int(back["next_round"])


def is_valid(path: str) -> bool:
    """Structural validity of one ``round_N`` directory: it exists and
    orbax's commit marker is present (a kill mid-save leaves the marker
    missing). Cheap by design — torn DATA inside a committed layout is
    caught by :func:`restore_latest`'s restore attempt instead."""
    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, _COMMIT_MARKER)
    )


def _candidates(checkpoint_dir: str) -> list:
    """``round_N`` subdirectories, newest round first."""
    if not os.path.isdir(checkpoint_dir):
        return []
    rounds = []
    for name in os.listdir(checkpoint_dir):
        if name.startswith("round_"):
            try:
                rounds.append((int(name.split("_", 1)[1]), name))
            except ValueError:
                continue
    return [
        os.path.join(checkpoint_dir, name)
        for _, name in sorted(rounds, reverse=True)
    ]


def _warn_invalid(path: str, why: str) -> None:
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.obs.metrics import REGISTRY, warn_once

    REGISTRY.counter("checkpoint.invalid").inc()
    msg = (
        f"checkpoint: skipping {path!r} ({why}); falling back to the "
        f"next-older checkpoint"
    )
    obs_events.emit("warning", kind="checkpoint_invalid", message=msg)
    warn_once(f"checkpoint_invalid:{path}", msg)


def latest(checkpoint_dir: str) -> Optional[str]:
    """Most recent VALID ``round_<N>`` checkpoint under ``checkpoint_dir``.

    Partially written candidates (killed mid-save: commit marker missing)
    are skipped with a ``warning`` event rather than returned — the old
    newest-wins behavior handed resume a directory restore() would crash
    on, destroying the run the checkpoint existed to protect."""
    for path in _candidates(checkpoint_dir):
        if is_valid(path):
            return path
        _warn_invalid(path, "partially written: commit marker missing")
    return None


#: controller-state sidecar inside a ``round_N`` directory (elastic
#: membership driver): written AFTER the orbax commit, so a kill between
#: the two leaves a committed-but-auxless checkpoint that the aux-aware
#: resume path skips (falling back older) instead of resuming with state
#: but no membership ledger
AUX_NAME = "elastic_aux.json"


def save_aux(path: str, aux: dict) -> None:
    """Atomically attach a JSON sidecar to checkpoint directory ``path``
    (write-to-temp + rename: a kill mid-write never leaves a torn aux)."""
    import json

    target = os.path.join(os.path.abspath(path), AUX_NAME)
    tmp = target + ".tmp"
    with open(tmp, "w") as f:
        json.dump(aux, f)
    os.replace(tmp, target)


def load_aux(path: str) -> Optional[dict]:
    """The checkpoint's aux sidecar, or None (absent or torn)."""
    import json

    target = os.path.join(os.path.abspath(path), AUX_NAME)
    try:
        with open(target) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def save_with_aux(
    path: str, state: OptState, next_round: int, aux: dict
) -> None:
    """Checkpoint plus controller-state sidecar (elastic driver): the aux
    commits only after the orbax save does, so every recoverable
    checkpoint carries a consistent (state, ledger) pair."""
    save(path, state, next_round)
    save_aux(path, aux)


def restore_latest_with_aux(
    checkpoint_dir: str, template_state: OptState
) -> Optional[Tuple[OptState, int, str, dict]]:
    """Like :func:`restore_latest`, but only candidates carrying a
    readable aux sidecar qualify — a checkpoint without its membership
    ledger cannot resume an elastic run, so it is skipped with a warning
    exactly like a torn one. Returns (state, next_round, path, aux)."""
    for path in _candidates(checkpoint_dir):
        if not is_valid(path):
            _warn_invalid(path, "partially written: commit marker missing")
            continue
        aux = load_aux(path)
        if aux is None:
            _warn_invalid(
                path, "aux sidecar missing/torn (killed between orbax "
                "commit and aux write)"
            )
            continue
        try:
            state, next_round = restore(path, template_state)
        except Exception as e:  # noqa: BLE001 — any torn checkpoint must
            # fall back, whatever layer of orbax/tensorstore it broke in
            _warn_invalid(
                path, f"restore failed: {type(e).__name__}: "
                f"{str(e).splitlines()[0][:160]}"
            )
            continue
        return state, next_round, path, aux
    return None


def restore_latest(
    checkpoint_dir: str, template_state: OptState
) -> Optional[Tuple[OptState, int, str]]:
    """Restore the newest checkpoint that actually loads.

    Candidates are tried newest-first; structurally invalid ones AND ones
    whose restore raises (truncated/corrupt data files — a committed
    layout with torn contents) are skipped with a ``warning`` event and a
    ``checkpoint.invalid`` count. Returns ``(state, next_round, path)``,
    or None when no candidate survives (callers start from round 0, as
    with no checkpoint at all)."""
    for path in _candidates(checkpoint_dir):
        if not is_valid(path):
            _warn_invalid(path, "partially written: commit marker missing")
            continue
        try:
            state, next_round = restore(path, template_state)
        except Exception as e:  # noqa: BLE001 — any torn checkpoint must
            # fall back, whatever layer of orbax/tensorstore it broke in
            _warn_invalid(
                path, f"restore failed: {type(e).__name__}: "
                f"{str(e).splitlines()[0][:160]}"
            )
            continue
        return state, next_round, path
    return None
