"""Post-hoc evaluation replay: loss/AUC curves over the whole iterate history.

The reference's master, after training, reloads the full train and test sets
and replays every saved iterate through numpy + sklearn, printing one line
per iteration (src/naive.py:157-198). Here the replay is a single jitted
lax.scan over the stacked history — the full [rounds, F] betaset against the
full train/test matrices, on device.

Deviations from the reference (documented, SURVEY.md §2.5):
  - the reference's replay silently drops the last worker's partition from
    the train loss (``range(2, n_procs-1)``, src/naive.py:161-169); we
    evaluate on the full training set,
  - AUC uses the (tested-equal) Mann-Whitney form on device instead of
    sklearn's roc_curve on host.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from erasurehead_tpu.models import metrics
from erasurehead_tpu.utils.config import ModelKind


@dataclasses.dataclass
class EvalResult:
    training_loss: np.ndarray  # [rounds]
    testing_loss: np.ndarray  # [rounds]
    auc: np.ndarray  # [rounds]; NaN for regression (reference prints none)


#: (model identity, is_regression) -> the jitted replay scan. The jit
#: used to live in a per-call closure, so EVERY replay re-traced and
#: re-compiled (~0.3 s each) — for a 28-trajectory sweep or a serve
#:  daemon summarizing every request, the replay recompiles dominated the
#: wall-clock. Models are stateless value objects (trainer.build_model
#: constructs a fresh instance per call), so the cache keys on the model's
#: TYPE + constructor attrs and passes data/history as traced arguments;
#: jit's own cache then handles shape polymorphism.
_replay_fns: dict = {}


def _replay_core(model, is_regression: bool):
    """The un-jitted replay scan for one trajectory's history — shared by
    the scalar (:func:`_replay_fn`) and trajectory-batched
    (:func:`_replay_batch_fn`) compiled forms, so the two can never
    compute different curves."""

    def one(carry, params, X_train, y_train, X_test, y_test):
        train_loss = model.loss_mean(params, X_train, y_train)
        pred_test = model.predict(params, X_test)
        test_loss = (
            metrics.mse_mean(y_test, pred_test)
            if is_regression
            else metrics.log_loss_mean(y_test, pred_test)
        )
        auc_val = (
            jnp.nan if is_regression else metrics.auc(y_test, pred_test)
        )
        return carry, (train_loss, test_loss, auc_val)

    def run(history, X_train, y_train, X_test, y_test):
        _, out = jax.lax.scan(
            lambda c, p: one(c, p, X_train, y_train, X_test, y_test),
            0,
            history,
        )
        return out

    return run


def _model_key(model, is_regression: bool) -> tuple:
    return (
        type(model),
        repr(sorted(getattr(model, "__dict__", {}).items())),
        is_regression,
    )


def _replay_fn(model, is_regression: bool):
    key = _model_key(model, is_regression)
    fn = _replay_fns.get(key)
    if fn is None:
        _replay_fns[key] = fn = jax.jit(_replay_core(model, is_regression))
    return fn


def _replay_batch_fn(model, is_regression: bool):
    """The trajectory-batched form of :func:`_replay_fn`: one jitted
    vmap-of-scan evaluating a [B, R, ...] stacked history in a single
    dispatch — the what-if engine's reduction path, where hundreds of
    Monte-Carlo trajectories would otherwise pay one replay dispatch
    each. Cached per model identity exactly like the scalar form."""
    key = _model_key(model, is_regression) + ("batch",)
    fn = _replay_fns.get(key)
    if fn is None:
        core = _replay_core(model, is_regression)

        @jax.jit
        def run(histories, X_train, y_train, X_test, y_test):
            return jax.vmap(
                lambda h: core(h, X_train, y_train, X_test, y_test)
            )(histories)

        _replay_fns[key] = fn = run
    return fn


def replay_batch(
    model,
    model_kind: ModelKind,
    histories: Any,
    X_train,
    y_train,
    X_test,
    y_test,
) -> EvalResult:
    """Batched :func:`replay`: ``histories`` carries a leading trajectory
    axis ([B, R, ...] per leaf); the returned curves are [B, R]. Same
    math per lane as the scalar replay — the vmap only adds the batch
    dimension."""
    import scipy.sparse as sps

    from erasurehead_tpu.ops.features import PaddedRows

    if sps.issparse(X_train):
        X_train = PaddedRows.from_scipy(X_train)
    if sps.issparse(X_test):
        X_test = PaddedRows.from_scipy(X_test)
    y_train = jnp.asarray(np.asarray(y_train, np.float32))
    y_test = jnp.asarray(np.asarray(y_test, np.float32))
    is_regression = ModelKind(model_kind) == ModelKind.LINEAR

    run = _replay_batch_fn(model, is_regression)
    train_l, test_l, auc_l = run(
        histories, X_train, y_train, X_test, y_test
    )
    return EvalResult(
        training_loss=np.asarray(train_l),
        testing_loss=np.asarray(test_l),
        auc=np.asarray(auc_l),
    )


def replay(
    model,
    model_kind: ModelKind,
    params_history: Any,
    X_train,
    y_train,
    X_test,
    y_test,
) -> EvalResult:
    """Loss (and AUC for classifiers) of every iterate in the history.

    Accepts dense ndarrays or scipy sparse matrices; the latter are converted
    to the TPU-native PaddedRows format here so callers can pass a Dataset's
    matrices straight through. Repeat replays of the same model family and
    shapes reuse one compiled scan (see :data:`_replay_fns`).
    """
    import scipy.sparse as sps

    from erasurehead_tpu.ops.features import PaddedRows

    if sps.issparse(X_train):
        X_train = PaddedRows.from_scipy(X_train)
    if sps.issparse(X_test):
        X_test = PaddedRows.from_scipy(X_test)
    y_train = jnp.asarray(np.asarray(y_train, np.float32))
    y_test = jnp.asarray(np.asarray(y_test, np.float32))
    is_regression = ModelKind(model_kind) == ModelKind.LINEAR

    run = _replay_fn(model, is_regression)
    train_l, test_l, auc_l = run(
        params_history, X_train, y_train, X_test, y_test
    )
    return EvalResult(
        training_loss=np.asarray(train_l),
        testing_loss=np.asarray(test_l),
        auc=np.asarray(auc_l),
    )
