"""The training driver: one lax.scan over rounds, everything on device.

Replaces the reference's 100-iteration master/worker MPI loop (SURVEY.md
§3.1). Control plane (host, float64, precomputed — tiny): straggler arrival
schedule, per-round collection/decode weights, learning-rate schedule. Data
plane (device, one jit): per-round coded gradients via the shard_map step,
GD/AGD update, iterate history. The scan compiles once and runs at silicon
speed — there is no per-iteration Python, no host round-trip, no sleeps.

Timing artifacts keep the reference's two clocks separate and honest:
  - ``timeset``/``worker_times``: *simulated* cluster seconds from the
    arrival model (what the reference measured with time.time around its MPI
    waits, src/naive.py:95,126 — there the sleeps were real; here they are
    modeled),
  - ``wall_time``/``steps_per_sec``: *real* measured TPU executime time of
    the whole scan.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from contextlib import contextmanager
from functools import partial, wraps
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from erasurehead_tpu.data.sharding import (
    ShardedData,
    np_global,
    partition_stack,
    plan_ring_transport,
    put_global,
    resolve_ring_stack,
    shard_run_data,
    worker_stack,
)
from erasurehead_tpu.data.synthetic import Dataset
from erasurehead_tpu.models.glm import LinearModel, LogisticModel
from erasurehead_tpu.models.mlp import MLPModel
from erasurehead_tpu.ops import codes
from erasurehead_tpu.parallel import collect, step as step_lib, straggler
from erasurehead_tpu.parallel.mesh import (
    WORKER_AXIS,
    replicated,
    worker_mesh,
)
from erasurehead_tpu.train import optimizer
from erasurehead_tpu.utils.config import (
    ComputeMode,
    ModelKind,
    PipelineRefusal,
    RunConfig,
    Scheme,
)


def build_layout(cfg: RunConfig) -> codes.CodingLayout:
    """Scheme -> layout via the registry descriptor (erasurehead_tpu/
    schemes/; the reference's dispatch was main.py:62-92)."""
    from erasurehead_tpu import schemes

    return schemes.get(cfg.scheme).build_layout(cfg)


def build_model(cfg: RunConfig):
    if cfg.model == ModelKind.LOGISTIC:
        return LogisticModel()
    if cfg.model == ModelKind.LINEAR:
        return LinearModel()
    if cfg.model == ModelKind.MLP:
        return MLPModel()
    if cfg.model == ModelKind.ATTENTION:
        from erasurehead_tpu.models.attention import AttentionModel

        return AttentionModel(sp_form=cfg.sp_form)
    if cfg.model == ModelKind.DEEPMLP:
        from erasurehead_tpu.models.deep_mlp import DeepMLPModel

        # cfg.deep_layers sweeps the family's depth (0 = model default);
        # the decode-error-vs-depth series rides this knob
        if cfg.deep_layers:
            return DeepMLPModel(n_layers=cfg.deep_layers)
        return DeepMLPModel()
    if cfg.model == ModelKind.MOE:
        from erasurehead_tpu.models.moe import MoEModel

        return MoEModel()
    raise ValueError(f"unknown model {cfg.model}")


#: reasons already surfaced as use_pallas-declined warnings (one event per
#: distinct reason per process — the auto gate runs per train() call)
_pallas_declined_seen: set = set()


def _warn_pallas_declined(reason: str) -> None:
    from erasurehead_tpu.obs import events as obs_events

    if reason in _pallas_declined_seen:
        return
    _pallas_declined_seen.add(reason)
    obs_events.emit("warning", kind="use_pallas_declined", message=reason)


def resolved_stack(cfg: RunConfig, dataset: Dataset, mesh=None):
    """(model, X) exactly as :func:`train` would resolve them — the shape
    the tune plane races and resolves under (erasurehead_tpu/tune/races).

    Mirrors train()'s stack selection: ring-transported faithful runs
    consume the partition-major stack, materialized faithful runs the
    worker-major stack, deduped runs the partition-major stack. The tune
    decision cache keys on tune.run_shape_signature(model, X) of THIS
    pair, so races and warm-run resolutions can never key apart."""
    faithful = cfg.compute_mode == ComputeMode.FAITHFUL
    setup = _setup_run(cfg, dataset, mesh, faithful=faithful)
    if faithful and not setup.ring:
        return setup.model, setup.data.Xw
    return setup.model, setup.data.Xp


def _auto_mesh(need: int):
    """Largest device count dividing the sharded axis length (the reference
    ran W workers on exactly W nodes; we fold logical workers onto whatever
    chips exist — e.g. W=30 uses 6 of 8 chips, 5 workers per chip)."""
    avail = len(jax.devices())
    return worker_mesh(max(d for d in range(1, avail + 1) if need % d == 0))


def _model_axis_request(cfg: RunConfig):
    """(axis_name, shards) for the config's model-internal parallelism
    axis — seq for attention, model for MLP tensor parallelism — or None.
    Config validation guarantees at most one exceeds 1."""
    if cfg.seq_shards > 1:
        from erasurehead_tpu.parallel.ring import SEQ_AXIS

        return SEQ_AXIS, cfg.seq_shards
    if cfg.tp_shards > 1:
        from erasurehead_tpu.parallel.mesh import MODEL_AXIS

        return MODEL_AXIS, cfg.tp_shards
    if cfg.pp_shards > 1:
        from erasurehead_tpu.models.deep_mlp import PIPE_AXIS

        return PIPE_AXIS, cfg.pp_shards
    if cfg.ep_shards > 1:
        from erasurehead_tpu.models.moe import EXPERT_AXIS

        return EXPERT_AXIS, cfg.ep_shards
    return None


def _auto_2d_mesh(need: int, axis_name: str, shards: int):
    """2-D (workers, <axis>) mesh: ``shards`` devices per model-parallel
    group, the worker dim the largest divisor of ``need`` that fits."""
    from erasurehead_tpu.parallel.mesh import worker_plus_axis_mesh

    avail = len(jax.devices())
    if shards > avail:
        raise ValueError(
            f"{axis_name} shards={shards} exceeds the {avail} available "
            f"devices"
        )
    per = avail // shards
    wd = max(d for d in range(1, per + 1) if need % d == 0)
    return worker_plus_axis_mesh(axis_name, shards, wd)


def _init_params_f32(cfg: RunConfig, model, n_features: int):
    p = model.init_params(jax.random.key(cfg.seed), n_features)
    return jax.tree.map(lambda x: x.astype(jnp.float32), p)


@dataclasses.dataclass
class _RunSetup:
    """Shared per-run state assembled identically by all three trainers
    (train / train_measured / train_dynamic) — one home so init, data
    sharding, and schedules can never desynchronize between them (tests
    compare the trainers' outputs assuming identical initialization)."""

    layout: codes.CodingLayout
    model: Any
    mesh: Any
    data: ShardedData
    state0: Any  # optimizer state; params cast to f32 (cfg.dtype is DATA)
    update_fn: Any
    lr: np.ndarray
    alpha: float
    n_train: int
    # did the sweep-engine data cache (train/cache.py) serve the device
    # stacks, skipping the host re-stack + upload?
    data_cache_hit: bool = False
    # RESOLVED stack transport for faithful mode (cfg.stack_mode; "auto"
    # resolves by sharding.resolve_ring_stack's footprint estimate): True
    # = only the partition-major stack is resident and the step rebuilds
    # worker slot buffers over ppermute ring hops
    ring: bool = False
    # RESOLVED feature-stack storage dtype (cfg.resolve_stack_dtype):
    # "float32" / "bfloat16" / "int8" — int8 means the device stacks are
    # QuantizedStack containers (payload + scale tables)
    stack_dtype: str = "float32"


def _with_run_sparse_lanes(fn):
    """Scope cfg's features-module lowering knobs (sparse_lanes,
    dense_margin_cols) to the trainer call: set them for the run's traces,
    restore the previous values on exit. Without the restore a global
    would leak into every later matvec/rmatvec — e.g. cli.run's
    evaluate.replay over the FULL training set, where an L-lane gather's
    [n, nnz, L] intermediate is L x the memory (19 GB at the covtype
    shape with L=1024). All jitted fns inside the trainers are per-run
    closures, so the flips always retrace.
    """

    @wraps(fn)
    def wrapper(cfg, dataset, *args, **kwargs):
        from erasurehead_tpu.ops import features as features_lib

        prev = features_lib.get_sparse_lanes()
        prev_cols = features_lib.get_dense_margin_cols()
        prev_scatter = features_lib.get_fields_scatter()
        prev_margin = features_lib.get_fields_margin()
        features_lib.set_sparse_lanes(cfg.sparse_lanes)
        features_lib.set_dense_margin_cols(cfg.dense_margin_cols)
        features_lib.set_fields_scatter(cfg.fields_scatter)
        features_lib.set_fields_margin(cfg.fields_margin)
        try:
            return fn(cfg, dataset, *args, **kwargs)
        finally:
            features_lib.set_sparse_lanes(prev)
            features_lib.set_dense_margin_cols(prev_cols)
            features_lib.set_fields_scatter(prev_scatter)
            features_lib.set_fields_margin(prev_margin)

    return wrapper


def _worker_axis_size(mesh) -> int:
    return (
        int(mesh.shape[WORKER_AXIS])
        if WORKER_AXIS in mesh.axis_names
        else int(mesh.devices.size)
    )


def _setup_run(
    cfg: RunConfig,
    dataset: Dataset,
    mesh,
    *,
    faithful: bool,
    single_device: bool = False,
    ring_ok: bool = True,
) -> _RunSetup:
    layout = build_layout(cfg)
    model = build_model(cfg)
    axis_req = _model_axis_request(cfg)
    if mesh is None:
        need = layout.n_workers if faithful else layout.n_partitions
        if single_device:
            mesh = worker_mesh(1)  # per-worker dispatches place themselves
        elif axis_req is not None:
            mesh = _auto_2d_mesh(need, *axis_req)
        else:
            mesh = _auto_mesh(need)
    if axis_req is not None and not single_device:
        # an explicit mesh must actually carry the requested axis — these
        # modes are parity-preserving, so silently running without them
        # would LOOK right while testing nothing
        ax, shards = axis_req
        if ax not in mesh.axis_names or mesh.shape[ax] != shards:
            raise ValueError(
                f"requested {shards} '{ax}' shards but the mesh axes are "
                f"{dict(mesh.shape)}; pass mesh=None (auto) or a 2-D mesh "
                f"with a matching '{ax}' axis"
            )
    # model-parallel families swap themselves in when the mesh carries
    # their axis — attention for seq (models/attention.for_mesh), MLP for
    # the tensor-parallel model axis (models/mlp.for_mesh); eval replay
    # builds its own unsharded model, so this scopes to step construction
    if hasattr(model, "for_mesh"):
        model = model.for_mesh(mesh)
    from erasurehead_tpu.train import cache as cache_lib

    # resolved stack transport: ring streams the faithful redundancy over
    # ppermute hops instead of materializing it (paths with no ring body —
    # measured mode — pass ring_ok=False; use_pallas='on' forces the fused
    # body, so auto pins to materialized there)
    # resolved stack storage dtype (cfg.stack_dtype; "auto" follows the
    # data dtype): int8 builds QuantizedStack containers at upload, and
    # the footprint gate below sees the COMPRESSED itemsize — a stack
    # that only crosses the ring-auto threshold uncompressed stays
    # materialized once int8 shrinks it under it
    stack_dtype = cfg.resolve_stack_dtype()
    stack_np_dtype = (
        np.dtype(np.int8) if stack_dtype == "int8"
        else jnp.dtype(stack_dtype)
    )
    use_ring = faithful and resolve_ring_stack(
        cfg.stack_mode,
        layout,
        dataset,
        _worker_axis_size(mesh),
        stack_np_dtype,
        supported=ring_ok and cfg.use_pallas != "on",
    )
    # device-data cache: repeated runs of the same (dataset, layout
    # stacking, mesh, dtype) reuse the uploaded stacks. The key carries
    # exactly what the stacking consumes — NOT the scheme name: deduped
    # mode stacks partition-major (partition_stack reads only
    # n_partitions, so all non-partial schemes share one upload), while
    # materialized faithful mode gathers through layout.assignment, so the
    # key carries the assignment CONTENT (FRC and AGC share an assignment
    # and therefore a stack; cyclic MDS has its own). Ring faithful keeps
    # only the partition-major stack and re-keys on partition content like
    # deduped — the cache payload shrinks by the same (s+1)x as the stack,
    # and ring runs share uploads with deduped runs of the same shape.
    stack_sig = cache_lib.layout_stack_signature(
        layout, worker_major=faithful and not use_ring
    )
    # the key's dtype token is the RESOLVED stack dtype (plus the label
    # dtype): an int8 run and an f32 run of the same content must never
    # share an upload — re-key on (content, stack_dtype) per ISSUE 6.
    # stack_dtype="auto" resolves to cfg.dtype, so pre-existing keys are
    # byte-for-byte what they were.
    data_key = (
        "stacks",
        cache_lib.dataset_token(dataset),
        stack_sig,
        layout.n_partitions,
        (stack_dtype, str(jnp.dtype(cfg.dtype))),
        cfg.sparse_format,
        cache_lib.mesh_signature(mesh),
    )
    data, data_hit = cache_lib.get_or_build_data(
        data_key,
        lambda: shard_run_data(
            dataset, layout, mesh, faithful=faithful,
            dtype=(
                jnp.dtype(cfg.dtype) if stack_dtype == "int8"
                else jnp.dtype(stack_dtype)
            ),
            sparse_format=cfg.sparse_format,
            ring=use_ring,
            quantize=stack_dtype == "int8",
        ),
    )
    params0 = _init_params_f32(cfg, model, dataset.n_features)
    state0 = optimizer.init_state(params0, cfg.update_rule)
    return _RunSetup(
        layout=layout,
        model=model,
        mesh=mesh,
        data=data,
        state0=state0,
        update_fn=optimizer.make_update_fn(cfg.update_rule),
        lr=cfg.resolve_lr_schedule(),
        alpha=cfg.effective_alpha,
        n_train=data.n_train,
        data_cache_hit=data_hit,
        ring=use_ring,
        stack_dtype=stack_dtype,
    )


def default_arrivals(cfg: RunConfig) -> np.ndarray:
    """The run's default straggler arrival schedule — single home shared by
    train(), the CLI's fault-injection path, and the determinism audit, so
    the arrival construction cannot drift between them.

    ``ERASUREHEAD_REGIME`` (utils/chaos.py) arms a deterministic mid-run
    straggler-regime shift (exp→heavy-tail, or one worker turning
    adversarially slow) on top of the drawn delays; unset, the schedule is
    byte-for-byte the stationary reference stream it always was.

    ``cfg.arrival_trace`` (or ``ERASUREHEAD_ARRIVAL_TRACE``) replays a
    recorded per-round arrival trace instead of the drawn exponential
    stream (straggler.replay_arrival_trace); ``cfg.worker_speed_spread``
    then composes as the seeded per-worker multiplier ON the trace rows
    (heterogeneous replay of a recorded cluster)."""
    from erasurehead_tpu.utils import chaos as chaos_lib
    from erasurehead_tpu.utils.config import resolve_arrival_trace

    trace = resolve_arrival_trace(cfg.arrival_trace)
    trace_speed = None
    if trace is not None and cfg.worker_speed_spread:
        # the same seeded draw model_from_config uses for compute_time
        # heterogeneity, applied multiplicatively to the recorded delays
        rng = np.random.default_rng(cfg.seed + 10_007)
        s = float(cfg.worker_speed_spread)
        trace_speed = rng.uniform(1.0 - s, 1.0 + s, cfg.n_workers)
    regime = chaos_lib.active_regime()
    regime_workers = None
    if regime is not None and regime.kind == "targeted":
        # a targeted attack slows every replica of one coded partition
        # group — the attacked set is a property of THIS config's layout,
        # so only this resolver (which can build it) can name the workers
        regime_workers = straggler.targeted_workers(
            build_layout(cfg), regime.group
        )
    return straggler.arrival_schedule(
        cfg.rounds, cfg.n_workers, cfg.add_delay, cfg.delay_mean,
        arrival_model=straggler.model_from_config(cfg),
        regime=regime,
        trace=trace,
        trace_speed=trace_speed,
        regime_workers=regime_workers,
    )


def _hard_sync(x) -> None:
    """Wait until the computation that produced ``x`` has really finished.

    ``jax.block_until_ready`` alone is not sufficient on remote-tunnel
    backends (this image's experimental ``axon`` TPU platform returns from
    it before execution finishes — measured: a 51KB fetch after a "ready"
    scan took another 9.9s). A device->host fetch is an unambiguous sync,
    and fetching ONE leaf suffices: all outputs of an executable
    materialize when the program completes, and a single small leaf keeps
    the transfer out of the measured wall-time.
    """
    leaves = jax.tree.leaves(x)
    if leaves:
        jax.block_until_ready(leaves[0])
        if not isinstance(leaves[0], jax.Array) or leaves[0].is_fully_addressable:
            # the fetch stays LOCAL: in a cluster a collective gather here
            # would ship the leaf over DCN inside timed regions, and a
            # ready buffer is already an unambiguous completion signal
            np.asarray(leaves[0])


def _ring_signature(ring_plan, pipeline: bool = False) -> tuple:
    """Executable-cache key component for the ring transport: the hop
    tables are compiled into the program as constants, so their CONTENT
    (not just shape) distinguishes executables — as does the RESOLVED
    transport schedule (pipelined vs sequential structure the scan
    differently; ring_pipeline="auto" resolves through module state a
    future race may flip)."""
    if ring_plan is None:
        return ("materialized",)
    return (
        "ring",
        ring_plan.n_hops,
        ring_plan.sel.tobytes(),
        "pipelined" if pipeline else "sequential",
    )


# Whether donate="auto" resolves to donating the scan carry + per-round
# weight tables (jax donate_argnums). On: donation frees the duplicate HBM
# copy of the optimizer state and weight tables across the dispatch —
# bitwise-identical math, and the device-data cache's stacks are never in
# the donated argnums (the use-after-donate hazard is test-pinned in
# tests/test_donation.py), so there is no correctness price to wait on a
# race for. "off" remains forceable for debugging and before/after rows.
DONATE_DEFAULT = True


def _resolve_donate(cfg: RunConfig) -> bool:
    if cfg.donate == "on":
        return True
    if cfg.donate == "off":
        return False
    from erasurehead_tpu.train import cache as cache_lib

    if cache_lib.persistent_compilation_cache_dir() is not None:
        # A donating executable DESERIALIZED from the persistent
        # compilation cache returns a carry whose jax-level alias points
        # at the donated input buffer while the actual output landed
        # elsewhere: reads see stale initial values or freed memory
        # (observed as NaN final params with a bitwise-correct history,
        # false-positiving the divergence quarantine in warm-cache serve
        # replicas). "auto" therefore resolves to no-donation whenever
        # this process routes compiles through the on-disk cache; the
        # explicit "on" above remains forceable. Donation is in the
        # executable signature, so cache entries stay consistent across
        # every daemon sharing the directory.
        return False
    return DONATE_DEFAULT


def _donate_copy(tree):
    """Fresh device buffers for a warm-up execution of a donating
    executable: the warm-up consumes (deletes) its donated arguments, and
    the real run still needs the originals. Copy cost is one transient
    the size of the carry/weights — never the data stacks, which are not
    donated."""
    return jax.tree.map(lambda l: l.copy(), tree)


@contextmanager
def _quiet_donation_warnings():
    """Scope out jax's "Some donated buffers were not usable" warning
    around lowering a donating executable: the per-round weight tables
    have no matching output to alias into (and some backends implement no
    donation at all), so the warning is expected — the donation is still
    correct (unusable donations are simply dropped) and the state carry's
    aliasing is the part that pays."""
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        yield


def _history_update_norms(history) -> np.ndarray:
    """[R-1] L2 norms of successive iterate differences — the host-visible
    gradient-magnitude proxy the ``rounds`` telemetry chunks carry
    (obs/events.emit_round_chunks). The exact per-round gradient norm
    would need an extra device program, and telemetry must add zero
    compiles; the optimizer-step norm comes free from the history the
    caller fetches for eval anyway. Entry j is the step into round j+1
    of the covered window."""
    leaves = jax.tree.leaves(history)
    if not leaves or int(leaves[0].shape[0]) < 2:
        return np.zeros(0)
    total = None
    for leaf in leaves:
        a = np.asarray(leaf, dtype=np.float64)
        d = a[1:] - a[:-1]
        s = (d.reshape(d.shape[0], -1) ** 2).sum(axis=1)
        total = s if total is None else total + s
    return np.sqrt(total)


def _exec_signature_fields(
    kind, platform, cfg, model, X, y, use_fused, ring_plan, weights_shape,
    mesh, state0, alpha, n_train, ring_pipeline=False, **extra
):
    """LABELED executable-cache signature: field name -> value, same
    content as the flat cache key (``tuple(fields.values())``). The names
    feed the recompile detector (obs/detect.py), which must be able to
    say WHICH field made two compiles differ. Anything that changes the
    compiled program must appear here — the single home replacing the
    hand-built exec_sig tuples. (The resolved stack dtype needs no field
    of its own: an int8 stack changes the data_tree leaf dtypes, and the
    raw knob rides in via static_signature_fields.)"""
    from erasurehead_tpu.train import cache as cache_lib

    fields = {
        "kind": kind,
        "platform": platform,
        **cfg.static_signature_fields(),
        "lowering": step_lib.lowering_signature(cfg, model, X),
        "fused": use_fused,
        "ring": _ring_signature(ring_plan, ring_pipeline),
        "weights_shape": tuple(weights_shape),
        "mesh": cache_lib.mesh_signature(mesh),
        "state_tree": cache_lib.tree_signature(state0),
        "data_tree": cache_lib.tree_signature((X, y)),
        "alpha": float(alpha),
        "n_train": int(n_train),
    }
    fields.update(extra)
    return fields


def _emit_run_start(run_id, cfg, setup, platform, lowering, faithful) -> None:
    """run_start + data_upload events for a trainer entry (no-ops without
    a capture installed; obs/events.py)."""
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.train import cache as cache_lib

    obs_events.emit(
        "run_start",
        run_id=run_id,
        scheme=cfg.scheme.value,
        model=cfg.model.value,
        platform=platform,
        config_hash=obs_events.config_hash(cfg),
        mesh=cache_lib.mesh_signature(setup.mesh),
        lowering=repr(lowering),
        static_signature=cfg.static_signature_fields(),
        n_workers=cfg.n_workers,
        n_stragglers=cfg.n_stragglers,
        rounds=cfg.rounds,
        compute_mode=cfg.compute_mode.value,
        stack_mode=(
            "ring" if setup.ring
            else ("materialized" if faithful else "deduped")
        ),
        dtype=cfg.dtype,
        stack_dtype=setup.stack_dtype,
    )
    obs_events.emit(
        "data_upload",
        run_id=run_id,
        bytes=cache_lib.device_nbytes(setup.data),
        cache_hit=setup.data_cache_hit,
        ring=setup.ring,
    )


def _memory_analysis(compiled) -> Optional[dict]:
    """Byte accounting of an AOT-compiled executable (XLA's
    CompiledMemoryStats), or None where the backend doesn't expose it.
    Argument bytes are where the ring stack mode's (s+1)x drop shows up;
    temp bytes carry the per-step reconstruction buffer."""
    try:
        ma = compiled.memory_analysis()
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
    except Exception as e:  # noqa: BLE001 — telemetry must never fail a run
        # ...but it must not fail SILENTLY either: count every swallow and
        # say so once on stderr, so a sweep whose memory telemetry went
        # dark is diagnosable instead of just mysteriously column-less
        from erasurehead_tpu.obs.metrics import REGISTRY, warn_once

        REGISTRY.counter("telemetry.emit_errors").inc()
        warn_once(
            "memory_analysis",
            f"telemetry: memory_analysis unavailable on this backend "
            f"({type(e).__name__}: {e}); memory columns will be null "
            f"(counted in telemetry.emit_errors)",
        )
        return None


@dataclasses.dataclass
class TrainResult:
    """Everything the reference's master holds at the end of a run."""

    params_history: Any  # pytree, leaves [rounds, ...] (the betaset)
    final_params: Any
    timeset: np.ndarray  # [rounds] simulated iteration wall-clock
    worker_times: np.ndarray  # [rounds, W] simulated arrivals, -1 sentinel
    collected: np.ndarray  # [rounds, W]
    sim_total_time: float  # sum of timeset — the reference's elapsed clock
    wall_time: float  # real seconds for the whole scan (compile excluded)
    steps_per_sec: float
    n_train: int
    # first round covered by params_history: 0 for fresh runs; a resumed run
    # starts at its checkpoint, so history leaves have rounds - start_round
    # entries while the (precomputed, deterministic) control-plane arrays
    # still cover the full run. Artifact writers align on this
    # (train/artifacts.py slices the clocks to the same window).
    start_round: int = 0
    config: RunConfig = None
    layout: codes.CodingLayout = None
    # full optimizer state at the end of the run (params + momentum/Adam
    # leaves) — what elastic restart hands to the survivor run
    final_state: Any = None
    # sweep-engine cache telemetry for THIS run (train/cache.py): data/exec
    # hit-miss counts, compile seconds saved, bytes not re-uploaded; None
    # when the trainer path has no cache integration (measured mode)
    cache_info: Optional[dict] = None
    # [rounds] per-round AGC decode-error norm ||pw - 1||/sqrt(P)
    # (obs/decode.py) — 0.0 for exact schemes, > 0 where the decode was
    # genuinely approximate; None where the weights live on device only
    # (train_dynamic)
    decode_error: Optional[np.ndarray] = None
    # event-log run id (obs/events.py) when a telemetry capture was active
    # during the run, else None — callers (cli eval, experiments) reference
    # it to attach their own records to this run
    run_id: Optional[str] = None


def _resolve_residency(cfg: RunConfig) -> str:
    """RESOLVED stack residency (cfg.stack_residency; "auto" streams
    exactly when the host declares a device byte budget via
    ERASUREHEAD_STREAM_WINDOW — a budget is the only signal that the
    resident stack might not fit, and without one streaming would only
    add staging latency)."""
    if cfg.stack_residency != "auto":
        return cfg.stack_residency
    from erasurehead_tpu.utils.config import resolve_stream_budget

    return "streamed" if resolve_stream_budget() is not None else "resident"


def _ensure_store(cfg: RunConfig, dataset: Dataset):
    """The shard store behind a streamed run: reuse the store the dataset
    was rehydrated from (store.dataset() brands ``_shard_store``), else
    spill the in-memory dataset into a temp-dir store once and brand it so
    every later run of the same sweep shares the one spill. A pre-existing
    store must match the run's partition count — the partition grouping is
    baked into the shard files at write time."""
    from erasurehead_tpu.data import store as store_lib

    layout = build_layout(cfg)
    store = getattr(dataset, "_shard_store", None)
    if store is not None:
        if store.n_partitions != layout.n_partitions:
            raise ValueError(
                f"shard store at {store.directory!r} holds "
                f"{store.n_partitions} partitions; this run's layout needs "
                f"{layout.n_partitions} — rewrite the store "
                f"(data/prepare.py --store) with the run's partition count"
            )
        if store.quantized and cfg.resolve_stack_dtype() != "int8":
            raise ValueError(
                f"shard store at {store.directory!r} is quantized (int8); "
                f"this run resolves stack_dtype="
                f"{cfg.resolve_stack_dtype()!r} — training on the "
                "dequantized reconstruction would silently lose precision; "
                "use stack_dtype='int8' or rewrite the store as float32"
            )
        return store
    import tempfile

    store = store_lib.write_store(
        dataset,
        tempfile.mkdtemp(prefix="eh-shard-store-"),
        layout.n_partitions,
        stack_dtype=(
            "int8" if cfg.resolve_stack_dtype() == "int8" else "float32"
        ),
    )
    dataset._shard_store = store
    return store


def _resolve_stream_window(
    cfg: RunConfig, n_partitions: int, partition_bytes: int
) -> int:
    """Partitions per streamed window.

    An explicit ``cfg.stream_window`` wins; else the host byte budget
    (ERASUREHEAD_STREAM_WINDOW) divided by TWO windows' worth of bytes —
    the double buffer keeps the current window AND the prefetched next one
    resident. No knob and no budget → one full-stack window. Sub-full
    windows round DOWN to a divisor of P so every window has the same
    shape: one compiled executable serves every chunk, and any worker
    mesh that divides the window divides all of them."""
    P = int(n_partitions)
    if cfg.stream_window is not None:
        w = int(cfg.stream_window)
    else:
        from erasurehead_tpu.utils.config import resolve_stream_budget

        budget = resolve_stream_budget()
        if budget is None:
            return P
        w = int(budget // max(1, 2 * int(partition_bytes)))
    if w >= P:
        return P
    w = max(1, w)
    while P % w:
        w -= 1
    return w


@_with_run_sparse_lanes
def train(
    cfg: RunConfig,
    dataset: Dataset,
    mesh=None,
    arrivals: Optional[np.ndarray] = None,
    schedule: Optional[collect.CollectionSchedule] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: Optional[int] = None,
    resume: bool = False,
    measure: bool = True,
    initial_state: Optional[Any] = None,
    initial_round: int = 0,
) -> TrainResult:
    """Run one full training run for ``cfg`` on ``dataset``.

    With ``checkpoint_dir`` set, optimizer state is saved every
    ``checkpoint_every`` rounds (orbax; train/checkpoint.py) by running the
    scan in chunks; ``resume=True`` restarts from the latest checkpoint —
    ``params_history`` then covers only the resumed rounds (the control-plane
    arrays still cover the full run; they are precomputed and deterministic).

    ``initial_state``/``initial_round`` start the run mid-schedule from an
    in-memory optimizer state instead of a checkpoint file — the elastic
    restart hook (parallel/failures.train_elastic): round ``initial_round``
    onward runs with THIS config's layout/mesh while the optimizer state
    carries over (its leaves are worker-count independent).
    """
    # argument validation up front, before any device setup (ADVICE r4):
    # a bare initial_round would otherwise silently run the full horizon
    # from round 0 with telemetry misrepresenting the request. resume=True
    # derives its start round from the checkpoint, never from initial_round.
    if initial_round != 0 and initial_state is None:
        raise ValueError(
            f"initial_round={initial_round} requires initial_state: a "
            "mid-schedule restart resumes from donor state (resume=True "
            "takes its start round from the checkpoint instead)"
        )
    if checkpoint_every is not None and checkpoint_every < 1:
        raise ValueError(
            f"checkpoint_every must be >= 1, got {checkpoint_every}"
        )
    if cfg.pipeline_depth:
        # the pipelined scan carries a tau=1-stale params slot that is NOT
        # part of the checkpoint / donor-state contract: any mid-run
        # restore would re-enter the scan with a fabricated stale slot and
        # silently fork the trajectory. Refuse (typed) rather than restore
        # wrong; journaled sweeps (train/journal.py) stay the supported
        # kill->resume path — they re-run whole trajectories bitwise,
        # which the deterministic pipelined schedule preserves.
        if checkpoint_dir is not None or resume:
            raise PipelineRefusal(
                "checkpoint_restart",
                "pipeline_depth=1 refuses checkpoint_dir/resume: the "
                "stale params slot is not in the checkpoint contract, so "
                "a mid-run restore cannot reproduce the pipelined "
                "trajectory (use journaled sweep resume instead)",
            )
        if initial_state is not None:
            raise PipelineRefusal(
                "elastic_restart",
                "pipeline_depth=1 refuses initial_state/initial_round: an "
                "elastic mid-schedule restart carries no stale params "
                "slot, so the resumed pipelined trajectory would fork",
            )
        if schedule is not None:
            raise PipelineRefusal(
                "custom_schedule",
                "pipeline_depth=1 refuses a caller-provided schedule: the "
                "pipelined timing recurrence and the stale-gradient carry "
                "must agree, so the schedule is derived from the arrivals "
                "here (parallel/pipeline.pipelined_schedule), not passed in",
            )
    # ---- stack residency (out-of-core streaming; data/store.py) -----------
    # resolved before any device setup. Streamed runs live out of a shard
    # store; when the resolved window covers every partition the store's
    # rehydrated view rides the UNCHANGED resident pipeline below (bitwise-
    # identical by construction — the parity tests/test_outofcore.py pins),
    # otherwise the windowed block trainer streams partition windows under
    # the byte budget with a double-buffered prefetcher.
    residency = _resolve_residency(cfg)
    if residency == "streamed":
        store = _ensure_store(cfg, dataset)
        stream_window = _resolve_stream_window(
            cfg, store.n_partitions, store.partition_bytes()
        )
        if stream_window < store.n_partitions:
            if cfg.pipeline_depth:
                raise PipelineRefusal(
                    "streamed_window",
                    "pipeline_depth=1 refuses windowed streamed residency: "
                    "the block trainer re-enters the scan per window, and "
                    "threading the stale params slot across windows is "
                    "untested (a single-window streamed run — window "
                    "covering every partition — rides the resident "
                    "pipeline and composes)",
                )
            return _train_streamed(
                cfg, dataset, store, stream_window,
                mesh=mesh, arrivals=arrivals, schedule=schedule,
                checkpoint_dir=checkpoint_dir, resume=resume,
                measure=measure, initial_state=initial_state,
                initial_round=initial_round,
            )
        if getattr(dataset, "_sweep_cache_token", None) != store.cache_token:
            dataset = store.dataset()
    from erasurehead_tpu.train import cache as cache_lib

    stats_before = cache_lib.stats().snapshot()
    faithful = cfg.compute_mode == ComputeMode.FAITHFUL
    setup = _setup_run(cfg, dataset, mesh, faithful=faithful)
    layout, model, mesh, data = setup.layout, setup.model, setup.mesh, setup.data

    # ---- control plane (host, float64) ------------------------------------
    if arrivals is None:
        arrivals = default_arrivals(cfg)
    if schedule is None:
        if cfg.pipeline_depth:
            # pipelined control plane: same drawn arrivals, the bounded-
            # staleness dispatch recurrence on top (parallel/pipeline.py).
            # Duck-types CollectionSchedule, so everything downstream —
            # slot-weight expansion, decode-error series, telemetry —
            # reads it unchanged.
            from erasurehead_tpu.parallel import pipeline as pipeline_lib

            schedule = pipeline_lib.pipelined_schedule(cfg, arrivals, layout)
        else:
            # a custom schedule (e.g. parallel/failures.plan_run's failover
            # rewrite) overrides the scheme's plain collection rule
            schedule = collect.build_schedule(
                cfg.scheme, arrivals, layout, num_collect=cfg.num_collect,
                deadline=cfg.deadline, decode=cfg.decode,
            )
    # per-round decode-error norm (obs/decode.py): host float64 from the
    # weights the run decodes with — computed unconditionally (cheap, and
    # TrainResult.decode_error feeds bench/experiment rows even without an
    # event capture)
    from erasurehead_tpu.obs import decode as obs_decode
    from erasurehead_tpu.obs import detect as obs_detect
    from erasurehead_tpu.obs import events as obs_events

    decode_err = obs_decode.decode_error_series(
        layout, schedule.message_weights
    )
    run_id = obs_events.new_run_id() if obs_events.current() else None
    lr = setup.lr
    alpha = setup.alpha
    n_train = setup.n_train

    # cfg.dtype is the DATA dtype (bfloat16 halves HBM traffic on the
    # bandwidth-bound gradient pass); params/optimizer state stay float32
    dtype = jnp.float32
    # the coded/separate slot rule lives only in expand_slot_weights; both
    # compute modes derive from its output (float64 on host)
    slot_w = np.asarray(
        step_lib.expand_slot_weights(
            schedule.message_weights,
            layout.coeffs,
            np.asarray(layout.slot_is_coded),
        )
    )  # [R, W, S]
    ring_plan = None
    ring_pipe = setup.ring and step_lib.resolve_ring_pipeline(
        cfg.ring_pipeline, model, data.Xp
    )
    if faithful and setup.ring:
        ring_plan = plan_ring_transport(layout, _worker_axis_size(mesh))
        grad_fn = step_lib.make_ring_faithful_grad_fn(
            model, mesh, ring_plan, pipeline=ring_pipe
        )
        weights_seq, X, y = jnp.asarray(slot_w, dtype), data.Xp, data.yp
    elif faithful:
        grad_fn = step_lib.make_faithful_grad_fn(model, mesh)
        weights_seq, X, y = jnp.asarray(slot_w, dtype), data.Xw, data.yw
    else:
        grad_fn = step_lib.make_deduped_grad_fn(model, mesh)
        pw = layout.fold_slot_weights(slot_w)
        weights_seq, X, y = jnp.asarray(pw, dtype), data.Xp, data.yp

    grad_fn = _apply_margin_flat(
        cfg, model, mesh, X, grad_fn, ring_plan, ring_pipe
    )
    grad_fn = _apply_flat_grad(
        cfg, model, mesh, X, grad_fn, ring_plan, ring_pipe
    )

    # fused single-HBM-pass pallas kernel for dense GLM stacks
    from erasurehead_tpu.ops import kernels as kernels_lib

    kind = getattr(model, "name", "")
    platform = jax.devices()[0].platform
    dense_glm = kind in kernels_lib.GLM_KINDS and isinstance(X, jax.Array)
    use_fused = False
    fused_verdict = None
    if cfg.use_pallas == "auto":
        fused_verdict = kernels_lib.supports_fused(X, kind, platform)
        if not fused_verdict:
            # surfaced once per distinct reason per process: "auto
            # silently declined" was the satellite bug — the refusal now
            # names itself in the event log (and nowhere else: the
            # decline is the measured default, not an error)
            _warn_pallas_declined(fused_verdict.reason)
    if cfg.use_pallas == "on" or (
        cfg.use_pallas == "auto" and fused_verdict
    ):
        if cfg.use_pallas == "on" and cfg.flat_grad == "on":
            # both knobs explicitly force a grad lowering; picking one
            # silently would misattribute any measurement tagged by the other
            raise ValueError(
                "use_pallas='on' and flat_grad='on' are mutually exclusive "
                "gradient lowerings; force at most one"
            )
        # ring transport wins over the auto-fused kernel (the fused body
        # has no ring variant; use_pallas='on' + ring is config-refused),
        # as does a forced blockwise decode (config-refused combination)
        if dense_glm and not setup.ring and cfg.layer_coding != "on":
            grad_fn = step_lib.make_fused_grad_fn(
                kind, mesh, interpret=(platform != "tpu")
            )
            use_fused = True
        elif cfg.use_pallas == "on":
            raise ValueError(
                "use_pallas='on' needs a dense logistic/linear stack; "
                f"got model={kind!r}, X={type(X).__name__}"
            )

    if not use_fused:
        grad_fn = _apply_layer_coding(
            cfg, model, mesh, X, grad_fn, setup.state0.params,
            ring_plan, ring_pipe, faithful=faithful,
        )

    update_fn = setup.update_fn

    if run_id is not None:
        _emit_run_start(
            run_id, cfg, setup, platform,
            step_lib.lowering_signature(cfg, model, X), faithful,
        )

    def replicate(state):
        # np_global: a donor initial_state may live on a DIFFERENT mesh
        # (an elastic restart), including a submesh of the cluster
        return jax.tree.map(
            lambda l: put_global(np_global(l), replicated(mesh)), state
        )

    # host-side until the initial_state/resume resolution below picks the
    # actual starting state — replicate exactly once, after that
    state0 = setup.state0

    lr_seq = jnp.asarray(lr, dtype)
    iters = jnp.arange(cfg.rounds, dtype=dtype)

    # X/y enter as jit *arguments*, never closures: closed-over arrays get
    # embedded as HLO literal constants, which made XLA compile ~100x slower
    # and pushed a per-call constant upload into the timed region (measured:
    # 147s compile + 25s first call vs 1.7s + 4ms with argument passing).
    from erasurehead_tpu.utils.tracing import annotate

    def body(Xa, ya, state, xs):
        eta, w_t, i = xs
        # trace-region names (utils/tracing.annotate -> jax.named_scope):
        # the coded-step region subsumes the replicated-params broadcast
        # and contains the eh_step/* sub-phases (ring fill, partial-grad
        # contraction, decode psum — parallel/step.py)
        with annotate("eh_scan/coded_step"):
            g = grad_fn(state.params, Xa, ya, w_t)
        with annotate("eh_scan/update"):
            new_state = update_fn(state, g, eta, alpha, n_train, i)
        return new_state, new_state.params

    def _run(state, Xa, ya, lr_c, w_c, it_c):
        return jax.lax.scan(
            partial(body, Xa, ya), state, (lr_c, w_c, it_c),
            unroll=cfg.scan_unroll,
        )

    if cfg.pipeline_depth:
        # pipelined carry: (live state, stale params slot). Round r's
        # gradient is taken at the params that ENTERED round r-1 (tau=1);
        # the update itself stays at the live iterate, so the trajectory
        # is SGD with a one-round-stale gradient — exactly the bounded-
        # staleness regime the timing model in parallel/pipeline.py
        # overlaps. Init is (state0, state0.params): rounds 0 and 1 both
        # compute at p0 (the fresh warm-up; there is no older iterate),
        # matching staleness_schedule's tau = min(r, depth).
        def body_pipe(Xa, ya, carry, xs):
            state, stale = carry
            eta, w_t, i = xs
            with annotate("eh_scan/coded_step"):
                g = grad_fn(
                    step_lib.staleness_slot_params(
                        state.params, stale, cfg.pipeline_depth
                    ),
                    Xa, ya, w_t,
                )
            with annotate("eh_scan/update"):
                new_state = update_fn(state, g, eta, alpha, n_train, i)
            return (new_state, state.params), new_state.params

        def _run(carry, Xa, ya, lr_c, w_c, it_c):
            return jax.lax.scan(
                partial(body_pipe, Xa, ya), carry, (lr_c, w_c, it_c),
                unroll=cfg.scan_unroll,
            )

    def as_carry(state):
        # the jitted scan's carry argument; pipelined runs thread the
        # extra stale params slot (one params-sized buffer — the +1 slot
        # estimate_stack_bytes charges serve admission for). The slot is
        # COPIED: under donation the carry is donated whole, and a slot
        # aliasing state.params would donate the same buffer twice
        if not cfg.pipeline_depth:
            return state
        return state, jax.tree.map(lambda l: l.copy(), state.params)

    # buffer donation (cfg.donate): the scan carry (params + optimizer
    # state, argnum 0) aliases straight into the final-state output, and
    # the per-round weight table (argnum 4) becomes reusable scratch —
    # the duplicate HBM copies go away. The DATA stacks (argnums 1-2) are
    # deliberately NOT donated: they may be the device-data cache's
    # pinned arrays, and a donated cached stack would poison every later
    # cache hit (tests/test_donation.py pins this).
    donate = _resolve_donate(cfg)
    run = jax.jit(_run, donate_argnums=(0, 4) if donate else ())

    start_round = 0
    if initial_state is not None:
        if resume:
            raise ValueError("pass either initial_state or resume, not both")
        if not 0 <= initial_round < cfg.rounds:
            raise ValueError(
                f"initial_round={initial_round} outside [0, {cfg.rounds})"
            )
        state0 = initial_state
        start_round = initial_round
    if resume and checkpoint_dir:
        from erasurehead_tpu.train import checkpoint as ckpt_lib

        # restore_latest skips partially-written/corrupt round_N dirs
        # (killed mid-save) with a warning, falling back to the next-older
        # valid checkpoint instead of crashing the resume on a torn one
        restored = ckpt_lib.restore_latest(checkpoint_dir, state0)
        if restored is None:
            # loud, not fatal: restart loops (k8s JobSet, tpu_fleet
            # launch_run) legitimately pass resume=True on the FIRST
            # attempt, before any checkpoint exists. A typo'd dir gets the
            # same message rather than silently overwriting prior artifacts.
            print(
                f"train: resume requested but no usable checkpoint found "
                f"under {checkpoint_dir!r}; starting from round 0",
                file=sys.stderr,
            )
        else:
            state0, start_round, _ = restored

    state0 = replicate(state0)

    exec_hits = exec_misses = 0
    compile_seconds = 0.0
    mem_info = None
    if start_round >= cfg.rounds:
        # the checkpoint already covers the requested rounds: nothing to run
        empty_hist = jax.tree.map(
            lambda p: jnp.zeros((0,) + p.shape, p.dtype), state0.params
        )
        final_state, history, wall = state0, empty_hist, 0.0
    else:
        # chunk boundaries: [start, start+every, ..., rounds]
        step_len = checkpoint_every or (cfg.rounds - start_round)
        bounds = list(range(start_round, cfg.rounds, step_len)) + [cfg.rounds]

        def slices(lo, hi):
            return lr_seq[lo:hi], weights_seq[lo:hi], iters[lo:hi]

        # executable-cache signature: everything that changes the compiled
        # scan besides argument shapes — the cfg-side lowering knobs, the
        # RESOLVED grad lowering (step.lowering_signature + the pallas
        # gate), the resolved ring transport ("auto" depends on a
        # footprint estimate the static signature cannot see; the hop plan
        # is baked into the program as constants, and under ring the X
        # stack no longer carries the slot count — so the plan CONTENT and
        # the weight-table shape must key the executable), the mesh's
        # exact device assignment, and the closure constants baked into
        # body (alpha, n_train). Per-round weight tables / lr / arrivals
        # are traced arguments: sharing the executable across them is the
        # sweep engine's whole point. The LABELED form feeds the recompile
        # detector, which names the fields that force a recompile.
        sig_fields = _exec_signature_fields(
            "scan", platform, cfg, model, X, y, use_fused, ring_plan,
            weights_seq.shape, mesh, state0, alpha, n_train,
            ring_pipeline=ring_pipe, donation=donate,
        )
        exec_sig = tuple(sig_fields.values())

        # AOT-compile each distinct chunk length so timing excludes
        # compilation; the module-level executable cache (train/cache.py)
        # makes the Nth run of the same signature skip trace+compile
        # entirely. With measure=True (benchmark-honest mode), also warm
        # each fresh executable once: the first execution pays a one-time
        # program-load cost on the device (measured ~6.5s over the axon
        # tunnel vs 0.12s steady-state for a 50-round scan) that is not a
        # property of the training step — a cache hit is already warm.
        # The warm-up re-executes a full chunk, so long production runs
        # that don't care about steps_per_sec accuracy should pass
        # measure=False.
        compiled = {}
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            n = hi - lo
            if n and n not in compiled:

                def _compile(lo=lo, hi=hi):
                    t0 = time.perf_counter()
                    with _quiet_donation_warnings():
                        ex = run.lower(
                            as_carry(state0), X, y, *slices(lo, hi)
                        ).compile()
                    if measure:
                        lr_c, w_c, it_c = slices(lo, hi)
                        if donate:
                            # the warm-up consumes its donated args; the
                            # real run still needs state0 (and a full-
                            # range weight slice aliases weights_seq)
                            lr_c2, w_c2 = lr_c, _donate_copy(w_c)
                            st = _donate_copy(as_carry(state0))
                        else:
                            lr_c2, w_c2, st = lr_c, w_c, as_carry(state0)
                        _hard_sync(ex(st, X, y, lr_c2, w_c2, it_c)[0])
                    return ex, time.perf_counter() - t0

                t_cmp = time.perf_counter()
                compiled[n], hit = cache_lib.get_or_compile(
                    exec_sig + (n,), _compile
                )
                cmp_secs = time.perf_counter() - t_cmp
                compile_seconds += cmp_secs
                if hit:
                    exec_hits += 1
                else:
                    exec_misses += 1
                    # recompile detector: always observed (it tracks what
                    # compiled in-process); warns into the event log when
                    # a near-identical signature forced this compile
                    obs_detect.observe_and_warn(
                        {**sig_fields, "chunk_rounds": n}, run_id
                    )
                if run_id is not None:
                    obs_events.emit(
                        "compile",
                        run_id=run_id,
                        seconds=round(cmp_secs, 4),
                        cache_hit=hit,
                        chunk_rounds=n,
                        memory_analysis=_memory_analysis(compiled[n]),
                    )

        carry = as_carry(state0)
        pieces = []
        wall = 0.0  # accumulates compute only; checkpoint I/O excluded
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            if hi == lo:
                continue
            t0 = time.perf_counter()
            carry, hist = compiled[hi - lo](carry, X, y, *slices(lo, hi))
            _hard_sync(carry)  # small final carry, not the full history
            wall += time.perf_counter() - t0
            pieces.append(hist)
            if checkpoint_dir and checkpoint_every and hi < cfg.rounds:
                # never pipelined here: checkpointing is config-refused
                # above, so the carry IS the bare optimizer state
                from erasurehead_tpu.train import checkpoint as ckpt_lib

                ckpt_lib.save(
                    os.path.join(checkpoint_dir, f"round_{hi}"), carry, hi
                )
        final_state = carry[0] if cfg.pipeline_depth else carry
        history = (
            pieces[0]
            if len(pieces) == 1
            else jax.tree.map(lambda *xs: jnp.concatenate(xs), *pieces)
        )
        mem_info = _memory_analysis(next(iter(compiled.values())))

    stats_after = cache_lib.stats().snapshot()
    steps_per_sec = (cfg.rounds - start_round) / wall if wall > 0 else 0.0
    if run_id is not None:
        # all emission host-side, AFTER the timed scan: the event log can
        # never perturb the measurement or the trajectory
        obs_events.emit_round_chunks(
            run_id,
            start_round=start_round,
            timeset=schedule.sim_time,
            worker_times=schedule.worker_times,
            decode_error=decode_err,
            update_norm=_history_update_norms(history),
        )
        obs_events.emit(
            "run_end",
            run_id=run_id,
            wall_time_s=round(wall, 6),
            steps_per_sec=round(steps_per_sec, 4),
            sim_total_time_s=float(schedule.sim_time.sum()),
            exec_hits=exec_hits,
            exec_misses=exec_misses,
            data_cache_hit=setup.data_cache_hit,
            compile_seconds=round(compile_seconds, 4),
            stack_bytes=cache_lib.device_nbytes(data),
            arrival=obs_events.arrival_summary(
                schedule.worker_times[start_round:]
            ),
            **obs_decode.summarize(decode_err),
        )
        if cfg.pipeline_depth:
            # pipeline overlap telemetry: pure numpy off the precomputed
            # schedule (zero compiles — the telemetry pin stands). The
            # gradient-space staleness split ("stale_decode") needs a
            # replay compile, so it is a post-run tool concern
            # (obs/decode.emit_staleness_split), never train()'s.
            from erasurehead_tpu.parallel import pipeline as pipeline_lib

            obs_events.emit(
                "dispatch_ahead",
                run_id=run_id,
                first_round=start_round,
                n_rounds=int(cfg.rounds - start_round),
                pipeline_depth=int(cfg.pipeline_depth),
                **pipeline_lib.overlap_summary(schedule),
            )
        from erasurehead_tpu.obs import critical_path as obs_cpath

        obs_cpath.emit_event(
            run_id,
            obs_cpath.attribute(
                schedule.sim_time[start_round:],
                schedule.worker_times[start_round:],
                schedule.collected[start_round:],
                wall_s=wall,
                # resume is config-refused on the pipelined path, so the
                # absolute dispatch/done clocks always start at round 0
                dispatch=getattr(schedule, "dispatch", None),
                done=getattr(schedule, "done", None),
                transport="ring" if setup.ring else "none",
            ),
        )
    return TrainResult(
        params_history=history,
        final_params=final_state.params,
        timeset=schedule.sim_time,
        worker_times=schedule.worker_times,
        collected=schedule.collected,
        sim_total_time=float(schedule.sim_time.sum()),
        wall_time=wall,
        steps_per_sec=steps_per_sec,
        n_train=n_train,
        start_round=start_round,
        config=cfg,
        layout=layout,
        final_state=final_state,
        decode_error=decode_err,
        run_id=run_id,
        cache_info={
            "enabled": cache_lib.enabled(),
            "data_hit": setup.data_cache_hit,
            "exec_hits": exec_hits,
            "exec_misses": exec_misses,
            "compile_seconds_saved": round(
                stats_after["compile_seconds_saved"]
                - stats_before["compile_seconds_saved"],
                4,
            ),
            "bytes_reused": stats_after["bytes_reused"]
            - stats_before["bytes_reused"],
            # memory telemetry: the (s+1)x ring claim asserted by numbers —
            # resident device bytes of the training stacks (what upload /
            # cache payload / HBM residency scale with) plus the compiled
            # executable's own accounting (argument/temp/output bytes)
            "stack_mode": (
                "ring"
                if setup.ring
                else ("materialized" if faithful else "deduped")
            ),
            # memory-system levers (resolved): stack storage dtype, ring
            # transport schedule (None off the ring path), and whether
            # this dispatch donated its carry/weight buffers
            "stack_dtype": setup.stack_dtype,
            "ring_pipeline": (
                ("pipelined" if ring_pipe else "sequential")
                if setup.ring
                else None
            ),
            "donation": donate,
            "stack_bytes": cache_lib.device_nbytes(data),
            "memory_analysis": mem_info,
            # pipelined runs carry one extra params-sized buffer in the
            # scan carry (the stale slot); surfaced so bench's memory
            # honesty rows and serve admission can account for it
            "pipeline_depth": cfg.pipeline_depth,
            "pipeline_params_slot_bytes": (
                cache_lib.device_nbytes(final_state.params)
                if cfg.pipeline_depth
                else 0
            ),
            # RESOLVED stack residency: "streamed" here means the run's
            # window covered the whole stack (the single-window fast path
            # — same resident pipeline, fed from the shard store)
            "residency": residency,
        },
    )


def _stream_remedy(cfg: RunConfig) -> str:
    """The remedy clause of a windowed-streamed refusal, naming the knob
    the CALLER actually used to land on this path (ISSUE 17 satellite:
    telling a ``--stack-residency streamed`` caller to raise an env
    budget they never set is a wrong remedy)."""
    from erasurehead_tpu.utils.config import (
        STREAM_WINDOW_ENV,
        resolve_stream_budget,
    )

    if cfg.stream_window is not None:
        return (
            "raise stream_window (--stream-window) to cover every "
            "partition, or run resident (stack_residency='resident')"
        )
    if resolve_stream_budget() is not None:
        return (
            f"raise the {STREAM_WINDOW_ENV} byte budget to cover every "
            "partition, or unset it to run resident"
        )
    return (
        "run this config resident (stack_residency='resident' or "
        "'auto' without a stream budget)"
    )


def _check_streamed_compat(cfg: RunConfig) -> None:
    """Refuse the knobs with genuinely NO windowed body — loudly, naming
    the knob that landed the run on the streamed path. Everything else
    (faithful/ring transports, int8 stacks, the flat/margin-flat dense
    lowerings, cohort batching) now composes with windowed streaming;
    these three cannot:

    - ``use_pallas='on'``: the fused kernel is a whole-stack single-pass
      body (auto resolves it off on streamed runs rather than refusing);
    - ``layer_coding='on'``: the blockwise decode packs whole-model block
      tables per slot, which has no windowed form (auto likewise
      resolves off);
    - a model-parallel 2-D mesh: the windowed chunk shards only the
      worker/partition axis."""
    if cfg.use_pallas == "on":
        raise ValueError(
            "use_pallas='on' forces the fused whole-stack kernel, which "
            "has no windowed streamed body; use use_pallas='auto'/'off', "
            f"or {_stream_remedy(cfg)}"
        )
    if cfg.layer_coding == "on":
        raise ValueError(
            "layer_coding='on' forces the blockwise decode, which has no "
            "windowed streamed body; use layer_coding='auto'/'off', "
            f"or {_stream_remedy(cfg)}"
        )
    if _model_axis_request(cfg) is not None:
        raise ValueError(
            "streamed windows have no model-parallel (2-D mesh) body; "
            f"{_stream_remedy(cfg)}"
        )


def _resolve_stream_ring(cfg: RunConfig, layout) -> bool:
    """Stack transport for a streamed FAITHFUL run ("ring" forces,
    "materialized" forbids). resolve_ring_stack's auto gate sizes the
    RESIDENT stack against RING_AUTO_MIN_BYTES; a streamed run's stack
    never resides, so the auto rule here is redundancy itself: ring
    whenever the assignment actually duplicates partitions
    (storage_overhead > 1) — the staged window then carries each
    partition once and the (s+1)x blowup exists only inside the ring
    fill's per-hop slices, never as pinned window bytes."""
    if cfg.stack_mode == "ring":
        return True
    if cfg.stack_mode != "auto":
        return False
    return float(layout.storage_overhead) > 1.0


def _make_stream_put(plan, sharding, quantize: bool, cast_dtype):
    """Host→device transfer fn for one staged stream window (runs on the
    prefetch staging thread; shared by the per-run and cohort streamed
    trainers). Deduped/ring windows upload the staged partition-major
    stack as-is; materialized-faithful windows first gather the
    slot-group's worker-major ``[gw, S, rows, F]`` view through the
    plan's local assignment — the same gather shard_run_data performs
    resident, restricted to one slot-group. int8 stores reuse the
    write-time ``(q, scale)`` tables verbatim; f32 stores quantize
    per-partition BEFORE the gather, so the tables are identical to the
    resident path's (quantization is partition-local)."""
    from erasurehead_tpu.ops.features import QuantizedStack

    local = plan.local_assignment if plan.mode == "materialized" else None

    def _cast(arr, to):
        arr = np.asarray(arr)
        return arr.astype(to) if np.issubdtype(
            arr.dtype, np.floating
        ) else arr

    def put(Xh, yh):
        if quantize:
            qs = (
                Xh if isinstance(Xh, QuantizedStack)
                else QuantizedStack.quantize(np.asarray(Xh))
            )
            q, scale = np.asarray(qs.q), np.asarray(qs.scale)
            if local is not None:
                q, scale = q[local], scale[local]
            Xd = QuantizedStack(
                put_global(q, sharding), put_global(scale, sharding)
            )
        else:
            Xh = _cast(Xh, cast_dtype)
            if local is not None:
                Xh = Xh[local]
            Xd = put_global(Xh, sharding)
        yh = _cast(yh, cast_dtype)
        if local is not None:
            yh = yh[local]
        return Xd, put_global(yh, sharding)

    return put


def _stream_group_slot_weights(layout, plan, schedule) -> np.ndarray:
    """Per-slot-group decode weights for sub-full faithful stream windows.

    The resident decode's [R, W] message weights cancel ACROSS workers
    (cyccoded's telescoping sums, the MDS solves), so slicing the
    expanded slot weights down to one slot-group's worker rows
    reconstructs nothing — the cancelling terms live in OTHER groups and
    the restricted sum is an arbitrary signed mixture of staged
    partitions. Each windowed chunk instead gets its own decode: for
    slot-group k, solve the min-norm least squares ``u @ E_k = 1_window``
    over the group's COLLECTED workers, where ``E_k`` is the group's
    effective coding matrix on the staged span and the target is the
    window's partition indicator (halo partitions decode toward 0 — they
    are the NEXT window's block). This is optimal_decode_weights_host's
    estimator (arXiv:2006.09638) localized to one slot-group, so
    sub-full faithful windows are APPROXIMATE gradient coding over each
    block even for exact schemes — the halo mixes into the group's coded
    messages and cannot always be cancelled with gw unknowns. At full
    cover ``n_windows == 1`` and the callers keep the resident slot
    weights (the streamed+ring == resident+ring bitwise pin never routes
    here).

    Returns ``[R, n_windows, gw, S]`` per-slot weights; separate
    (uncoded) slots keep their always-on coeffs and their fixed
    contribution is folded out of the target, mirroring
    expand_slot_weights' rule."""
    R = schedule.collected.shape[0]
    K, gw = plan.n_windows, plan.group_workers
    S = int(plan.local_assignment.shape[1])
    coeffs = np.asarray(layout.coeffs, dtype=np.float64)
    coded = np.broadcast_to(
        np.asarray(layout.slot_is_coded, dtype=bool),
        (int(layout.n_workers), S),
    )
    la = np.asarray(plan.local_assignment)  # [gw, S] staged-buffer index
    staged = plan.staged_partitions
    target0 = (np.arange(staged) < plan.window).astype(np.float64)
    out = np.zeros((R, K, gw, S))
    for k in range(K):
        rows = slice(k * gw, (k + 1) * gw)
        ck = coeffs[rows]
        ik = coded[rows]
        E = np.zeros((gw, staged))
        np.add.at(
            E, (np.arange(gw)[:, None], la), np.where(ik, ck, 0.0)
        )
        fixed = np.zeros(staged)
        np.add.at(fixed, la[~ik], ck[~ik])
        target = target0 - fixed
        masks = schedule.collected[:, rows]
        uniq, inverse = np.unique(masks, axis=0, return_inverse=True)
        u = np.zeros((uniq.shape[0], gw))
        for j in range(uniq.shape[0]):
            live = np.flatnonzero(uniq[j])
            if live.size:
                u[j, live] = np.linalg.lstsq(
                    E[live].T, target, rcond=None
                )[0]
        mw = u[inverse.reshape(-1)]  # [R, gw]
        out[:, k] = np.where(ik, mw[:, :, None] * ck, ck)
    return out


def _train_streamed(
    cfg: RunConfig,
    dataset: Dataset,
    store,
    window: int,
    mesh=None,
    arrivals: Optional[np.ndarray] = None,
    schedule: Optional[collect.CollectionSchedule] = None,
    checkpoint_dir: Optional[str] = None,
    resume: bool = False,
    measure: bool = True,
    initial_state: Optional[Any] = None,
    initial_round: int = 0,
) -> TrainResult:
    """Windowed streamed trainer: the partition stack never fully resides
    on device. ``window`` partitions (a divisor of P, from
    _resolve_stream_window) are materialized per scan chunk while
    data/prefetch.py stages the NEXT window's shard read + host→device
    transfer behind the current chunk's compute — at most two windows of
    device bytes are ever pinned.

    Semantics: BLOCK training, not a bitwise replay of the resident run —
    each round's gradient reads ONE window (n_train is the window's row
    count), and rounds cycle through the windows in fixed order.
    Deterministic run-to-run for a given (config, store), which is what
    lets the sweep journal rehydrate killed runs.

    Any compatible body serves the windowed chunks (the body-factory
    seam of ISSUE 17): the deduped scan streams pure partition windows;
    faithful stacks stream ASSIGNMENT windows (data/sharding.
    plan_stream_windows — contiguous slot-groups staging window + halo
    partitions in ring-hop order), either materialized worker-major per
    window or ring-transported (cfg.stack_mode via _resolve_stream_ring),
    and the flat/margin-flat dense lowerings compose on top exactly as
    they do resident. Sub-full faithful windows decode PER SLOT-GROUP
    (_stream_group_slot_weights — the optimal per-arrival refit
    localized to the group's collected workers), since the resident
    decode's cross-worker cancellations do not survive restriction to
    one group's rows; exact schemes therefore train each block in the
    approximate-gradient-coding regime when windowed. When the window covers the stack, streamed+ring is
    bitwise-identical to resident+ring (test-pinned). Still refused,
    loudly: the forced pallas kernel and forced blockwise decode (no
    windowed bodies), model-parallel 2-D meshes (_check_streamed_compat),
    and non-window-uniform assignments (the planner's refusal — e.g.
    random-regular scatter, where no single hop table serves every
    window).

    Reference mapping: the closest the reference could come was every MPI
    rank eagerly loading its whole NFS assignment at startup
    (src/approximate_coding.py:39-69) — data larger than cluster memory
    simply could not run. Here the store IS the NFS share and residency
    is a sliding window over it.
    """
    _check_streamed_compat(cfg)
    if checkpoint_dir or resume or initial_state is not None \
            or initial_round:
        raise ValueError(
            "checkpoint/resume/mid-schedule restart are not supported on "
            "the windowed streamed path (kill→resume recovery is the "
            "sweep journal's trajectory rehydration; see "
            "tools/outofcore_smoke.py)"
        )
    from math import gcd

    from erasurehead_tpu.data.prefetch import Prefetcher
    from erasurehead_tpu.data.sharding import plan_stream_windows
    from erasurehead_tpu.obs import decode as obs_decode
    from erasurehead_tpu.obs import detect as obs_detect
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.parallel import mesh as mesh_lib
    from erasurehead_tpu.train import cache as cache_lib
    from erasurehead_tpu.utils.tracing import annotate

    stats_before = cache_lib.stats().snapshot()
    layout = build_layout(cfg)
    model = build_model(cfg)
    P, rows = store.n_partitions, store.rows_per_partition
    faithful = cfg.compute_mode == ComputeMode.FAITHFUL
    mode = (
        ("ring" if _resolve_stream_ring(cfg, layout) else "materialized")
        if faithful
        else "deduped"
    )
    try:
        plan = plan_stream_windows(layout, window, mode=mode)
    except ValueError as e:
        raise ValueError(f"{e} — or {_stream_remedy(cfg)}") from None
    n_windows = plan.n_windows
    gw = plan.group_workers
    if mesh is None:
        if mode == "deduped":
            mesh = _auto_mesh(window)
        elif mode == "materialized":
            mesh = _auto_mesh(gw)
        else:
            # the sub-ring plan shards BOTH the slot-group's worker axis
            # and the staged partition span across the mesh
            mesh = _auto_mesh(gcd(gw, plan.staged_partitions))
    if mode == "deduped":
        mesh_lib.check_divisible(window, mesh, "stream_window")
    else:
        mesh_lib.check_divisible(gw, mesh, "stream slot-group workers")
        if mode == "ring":
            mesh_lib.check_divisible(
                plan.staged_partitions, mesh, "staged stream window"
            )
    if hasattr(model, "for_mesh"):
        model = model.for_mesh(mesh)
    stack_dtype = cfg.resolve_stack_dtype()
    if store.quantized and stack_dtype != "int8":
        raise ValueError(
            f"int8 shard store requires stack_dtype='int8' (resolved "
            f"{stack_dtype!r}): re-uploading a dequantized window would "
            "silently train on reconstructed values"
        )
    cast_dtype = jnp.dtype(
        cfg.dtype if stack_dtype == "int8" else stack_dtype
    )

    # ---- control plane: identical to the resident trainer -----------------
    if arrivals is None:
        arrivals = default_arrivals(cfg)
    if schedule is None:
        schedule = collect.build_schedule(
            cfg.scheme, arrivals, layout, num_collect=cfg.num_collect,
            deadline=cfg.deadline, decode=cfg.decode,
        )
    decode_err = obs_decode.decode_error_series(
        layout, schedule.message_weights
    )
    run_id = obs_events.new_run_id() if obs_events.current() else None
    lr = cfg.resolve_lr_schedule()
    alpha = cfg.effective_alpha
    n_train = window * rows  # the block each round's gradient averages
    dtype = jnp.float32
    slot_w = np.asarray(
        step_lib.expand_slot_weights(
            schedule.message_weights,
            layout.coeffs,
            np.asarray(layout.slot_is_coded),
        )
    )  # [R, W, S]
    pw = (
        np.asarray(layout.fold_slot_weights(slot_w))  # [R, P]
        if mode == "deduped"
        else None
    )
    # sub-full faithful windows decode per slot-group (the global slot
    # weights only reconstruct across ALL workers); full cover keeps the
    # resident weights — the bitwise pin path
    gsw = (
        _stream_group_slot_weights(layout, plan, schedule)
        if mode != "deduped" and n_windows > 1
        else None
    )  # [R, K, gw, S]
    ring_pipe = mode == "ring" and step_lib.resolve_ring_pipeline(
        cfg.ring_pipeline
    )
    # the one-window ring plan every chunk reuses (window-uniformity):
    # full-cover plans localize to the identity, so this is byte-identical
    # to the resident plan_ring_transport(layout, D) — the bitwise pin
    sub_ring = (
        plan_ring_transport(plan.sub_layout(), _worker_axis_size(mesh))
        if mode == "ring"
        else None
    )
    update_fn = optimizer.make_update_fn(cfg.update_rule)
    state0 = optimizer.init_state(
        _init_params_f32(cfg, model, store.n_features), cfg.update_rule
    )
    state0 = jax.tree.map(
        lambda l: put_global(np_global(l), replicated(mesh)), state0
    )

    # round chunks: each chunk consumes ONE window; chunk i's window index
    # cycles i mod n_windows, so every window is visited once rounds cover
    # n_windows chunks (fewer rounds visit a deterministic prefix)
    L = max(1, cfg.rounds // n_windows)
    bounds = list(range(0, cfg.rounds, L)) + [cfg.rounds]
    chunks = [
        (lo, hi) for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo
    ]
    win_of = [i % n_windows for i in range(len(chunks))]
    windows = [plan.ranges[k] for k in win_of]

    sharding = mesh_lib.worker_sharding(mesh)
    quantize = stack_dtype == "int8"
    put = _make_stream_put(plan, sharding, quantize, cast_dtype)

    lr_np = np.asarray(lr)
    iters_np = np.arange(cfg.rounds)

    def body(Xa, ya, state, xs):
        eta, w_t, i = xs
        with annotate("eh_scan/coded_step"):
            g = grad_fn(state.params, Xa, ya, w_t)
        with annotate("eh_scan/update"):
            new_state = update_fn(state, g, eta, alpha, n_train, i)
        return new_state, new_state.params

    def _run(state, Xa, ya, lr_c, w_c, it_c):
        return jax.lax.scan(
            partial(body, Xa, ya), state, (lr_c, w_c, it_c),
            unroll=cfg.scan_unroll,
        )

    donate = _resolve_donate(cfg)
    run = jax.jit(_run, donate_argnums=(0, 4) if donate else ())

    def slices(lo, hi, k):
        # per-chunk decode weights: the deduped body reads window k's
        # folded partition columns; sub-full faithful bodies read
        # slot-group k's per-group decode (_stream_group_slot_weights) —
        # at full cover both degenerate to the resident tables
        if mode == "deduped":
            plo = k * window
            w_c = pw[lo:hi, plo:plo + window]
        elif gsw is not None:
            w_c = gsw[lo:hi, k]
        else:
            w_c = slot_w[lo:hi, k * gw:(k + 1) * gw, :]
        return (
            jnp.asarray(lr_np[lo:hi], dtype),
            jnp.asarray(w_c, dtype),
            jnp.asarray(iters_np[lo:hi], dtype),
        )

    platform = jax.devices()[0].platform
    exec_hits = exec_misses = 0
    compile_seconds = 0.0
    pieces = []
    wall = 0.0
    state = state0
    mem_info = None
    pf = Prefetcher(
        store, windows, put, run_id=run_id,
        plan_fields=plan.event_fields(),
    )
    try:
        # the first window synchronously: its device arrays type the
        # lowering (and the prefetcher is already staging window 1)
        X0, y0 = pf.get(0)
        window_nbytes = cache_lib.device_nbytes((X0, y0))
        # body factory (the ISSUE 17 seam): the same transport + lowering
        # ladder the resident trainer composes, built over the windowed
        # stack — X0's device types resolve the dense lowerings exactly
        # as the resident path's uploaded stacks do
        if mode == "ring":
            grad_fn = step_lib.make_ring_faithful_grad_fn(
                model, mesh, sub_ring, pipeline=ring_pipe
            )
        elif mode == "materialized":
            grad_fn = step_lib.make_faithful_grad_fn(model, mesh)
        else:
            grad_fn = step_lib.make_deduped_grad_fn(model, mesh)
        grad_fn = _apply_margin_flat(
            cfg, model, mesh, X0, grad_fn, sub_ring, ring_pipe
        )
        grad_fn = _apply_flat_grad(
            cfg, model, mesh, X0, grad_fn, sub_ring, ring_pipe
        )
        if run_id is not None:
            _emit_run_start(
                run_id, cfg,
                _RunSetup(
                    layout=layout, model=model, mesh=mesh, data=(X0, y0),
                    state0=state0, update_fn=update_fn, lr=lr,
                    alpha=alpha, n_train=n_train, stack_dtype=stack_dtype,
                    ring=mode == "ring",
                ),
                platform, step_lib.lowering_signature(cfg, model, X0),
                faithful=faithful,
            )
        sig_fields = _exec_signature_fields(
            "scan-streamed", platform, cfg, model, X0, y0, False, sub_ring,
            (window,) if mode == "deduped" else (gw, layout.n_slots),
            mesh, state0, alpha, n_train, ring_pipeline=ring_pipe,
            donation=donate,
            stream_plan=(mode, window, plan.halo, gw),
        )
        exec_sig = tuple(sig_fields.values())
        compiled = {}
        for idx, (lo, hi) in enumerate(chunks):
            n = hi - lo
            if n in compiled:
                continue

            def _compile(lo=lo, hi=hi, k=win_of[idx]):
                t0 = time.perf_counter()
                with _quiet_donation_warnings():
                    ex = run.lower(
                        state0, X0, y0, *slices(lo, hi, k)
                    ).compile()
                if measure:
                    lr_c, w_c, it_c = slices(lo, hi, k)
                    st = _donate_copy(state0) if donate else state0
                    _hard_sync(ex(st, X0, y0, lr_c, w_c, it_c)[0])
                return ex, time.perf_counter() - t0

            t_cmp = time.perf_counter()
            compiled[n], hit = cache_lib.get_or_compile(
                exec_sig + (n,), _compile
            )
            cmp_secs = time.perf_counter() - t_cmp
            compile_seconds += cmp_secs
            if hit:
                exec_hits += 1
            else:
                exec_misses += 1
                obs_detect.observe_and_warn(
                    {**sig_fields, "chunk_rounds": n}, run_id
                )
            if run_id is not None:
                obs_events.emit(
                    "compile",
                    run_id=run_id,
                    seconds=round(cmp_secs, 4),
                    cache_hit=hit,
                    chunk_rounds=n,
                    memory_analysis=_memory_analysis(compiled[n]),
                )

        for i, (lo, hi) in enumerate(chunks):
            # the timed region INCLUDES the staging wait: any stall the
            # prefetch failed to hide is streaming overhead and must show
            # up in wall_time/steps_per_sec (BASELINE.md races depend on
            # this honesty)
            t0 = time.perf_counter()
            Xd, yd = (X0, y0) if i == 0 else pf.get(i)
            state, hist = compiled[hi - lo](
                state, Xd, yd, *slices(lo, hi, win_of[i])
            )
            _hard_sync(state)
            wall += time.perf_counter() - t0
            pieces.append(hist)
        mem_info = _memory_analysis(next(iter(compiled.values())))
    finally:
        pf.close()
    pf_stats = pf.stats()
    final_state = state
    history = (
        pieces[0]
        if len(pieces) == 1
        else jax.tree.map(lambda *xs: jnp.concatenate(xs), *pieces)
    )
    stats_after = cache_lib.stats().snapshot()
    steps_per_sec = cfg.rounds / wall if wall > 0 else 0.0
    if run_id is not None:
        obs_events.emit_round_chunks(
            run_id,
            start_round=0,
            timeset=schedule.sim_time,
            worker_times=schedule.worker_times,
            decode_error=decode_err,
            update_norm=_history_update_norms(history),
        )
        obs_events.emit(
            "run_end",
            run_id=run_id,
            wall_time_s=round(wall, 6),
            steps_per_sec=round(steps_per_sec, 4),
            sim_total_time_s=float(schedule.sim_time.sum()),
            exec_hits=exec_hits,
            exec_misses=exec_misses,
            data_cache_hit=False,
            compile_seconds=round(compile_seconds, 4),
            stack_bytes=window_nbytes,
            arrival=obs_events.arrival_summary(schedule.worker_times),
            **obs_decode.summarize(decode_err),
        )
        from erasurehead_tpu.obs import critical_path as obs_cpath

        obs_cpath.emit_event(
            run_id,
            obs_cpath.attribute(
                schedule.sim_time,
                schedule.worker_times,
                schedule.collected,
                wall_s=wall,
                # the streamed timed region includes staging waits; the
                # prefetcher's blocked_s is exactly the un-hidden part
                prefetch_stall_s=float(pf_stats.get("blocked_s", 0.0)),
                transport="ring" if mode == "ring" else "none",
            ),
        )
    return TrainResult(
        params_history=history,
        final_params=final_state.params,
        timeset=schedule.sim_time,
        worker_times=schedule.worker_times,
        collected=schedule.collected,
        sim_total_time=float(schedule.sim_time.sum()),
        wall_time=wall,
        steps_per_sec=steps_per_sec,
        n_train=n_train,
        start_round=0,
        config=cfg,
        layout=layout,
        final_state=final_state,
        decode_error=decode_err,
        run_id=run_id,
        cache_info={
            "enabled": cache_lib.enabled(),
            # the device-data cache is bypassed: windows are transient by
            # design (caching them would defeat the residency bound)
            "data_hit": False,
            "exec_hits": exec_hits,
            "exec_misses": exec_misses,
            "compile_seconds_saved": round(
                stats_after["compile_seconds_saved"]
                - stats_before["compile_seconds_saved"],
                4,
            ),
            "bytes_reused": stats_after["bytes_reused"]
            - stats_before["bytes_reused"],
            "stack_mode": mode,
            "stack_dtype": stack_dtype,
            "ring_pipeline": (
                ("pipelined" if ring_pipe else "sequential")
                if mode == "ring"
                else None
            ),
            "donation": donate,
            # device bytes of ONE staged window (window + halo partitions
            # for the faithful plans) — the residency unit; the double
            # buffer pins at most two of these
            "stack_bytes": window_nbytes,
            "memory_analysis": mem_info,
            "residency": "streamed",
            "stream_window": window,
            "n_windows": n_windows,
            "stream_halo": plan.halo,
            "stream_group_workers": gw,
            "prefetch": pf_stats,
        },
    )


def cohort_eligible(cfg: RunConfig) -> bool:
    """Can this config run inside a trajectory-batched cohort dispatch?
    The cohort engine batches the scan trainer only: measured-arrival mode
    dispatches per worker, and the forced pallas kernel has no batched
    body (it is a correctness/reference path, not a performance option).
    Streamed-residency runs batch too (ISSUE 17): trajectories sharing a
    (store digest, window plan, cohort signature) key ride ONE windowed
    cohort scan (_train_cohort_streamed) — static_signature carries
    stack_residency/stream_window, so streamed cohorts never group with
    resident ones, and serve admission still charges them by the window,
    not the stack. Excluded on the streamed path are only the knobs with
    no windowed body (_check_streamed_compat): the forced blockwise
    decode and model-parallel 2-D meshes.
    The scheme's registry descriptor can also opt out
    (``cohort_batchable=False``) — what the sweep planner
    (experiments.plan_cohorts) and the serve packer (serve/packer.py)
    both key third-party compatibility on.
    Pipelined runs (pipeline_depth > 0) are excluded: the cohort scan has
    no batched stale-carry slot, so they dispatch as per-run train() —
    the routing train_cohort's "cohort_batch" refusal relies on."""
    from erasurehead_tpu import schemes

    if _resolve_residency(cfg) == "streamed" and (
        cfg.layer_coding == "on" or _model_axis_request(cfg) is not None
    ):
        return False
    return (
        cfg.arrival_mode == "simulated"
        and cfg.use_pallas != "on"
        and cfg.pipeline_depth == 0
        and schemes.get(cfg.scheme).cohort_batchable
    )


def estimate_stack_bytes(cfg: RunConfig, dataset: Dataset) -> int:
    """Host-side estimate of the device data-stack footprint a dispatch of
    ``cfg`` pins while in flight — the serve admission controller's charge
    unit (serve/admission.py), built on the same machinery as the
    stack_mode="auto" footprint gate (data/sharding.
    estimate_worker_stack_bytes / RING_AUTO_MIN_BYTES).

    Deduped runs and explicitly ring-streamed faithful runs keep only the
    partition-major stack; materialized faithful pays the (s+1)x
    worker-major stack. ``stack_mode="auto"`` is charged at the
    MATERIALIZED estimate (the auto gate needs the mesh to resolve;
    admission is a bound, so over-charging the undecided case is the safe
    direction). int8 scale tables are counted inside
    estimate_worker_stack_bytes (data/sharding.py) — the per-block unit
    already carries them. Streamed-residency runs are charged their
    resident WINDOWS — at most two (compute + prefetch double buffer),
    never the whole stack; that drop is the admission-side point of
    out-of-core streaming. Partition-major windows are charged STAGED
    (window + assignment halo, data/sharding.plan_stream_windows — the
    ring fill transports the halo partitions, so they are real residency);
    materialized-faithful windows are charged the slot-group's worker
    gather, ``2/n_windows`` of the full worker stack. An estimate, not an
    accounting — refined per signature by the compiled
    ``memory_analysis`` once a dispatch has run (serve/admission.py).
    """
    layout = build_layout(cfg)
    dtype_name = cfg.resolve_stack_dtype()
    est_dtype = {
        "float32": np.float32, "bfloat16": jnp.bfloat16, "int8": np.int8,
    }[dtype_name]
    from erasurehead_tpu.data import sharding as sharding_lib

    worker_stack_est = sharding_lib.estimate_worker_stack_bytes(
        dataset, layout, est_dtype
    )
    per_block = worker_stack_est / max(
        1, layout.n_workers * layout.n_slots
    )
    partition_major = (
        cfg.compute_mode != ComputeMode.FAITHFUL or cfg.stack_mode == "ring"
    )
    streamed = _resolve_residency(cfg) == "streamed"
    w = None
    if streamed:
        # window resolution without a store: mirror ShardStore.
        # partition_bytes() from the dataset's own shapes (host/PCIe
        # bytes per partition — payload + labels + int8 scale row)
        P = layout.n_partitions
        F = int(dataset.X_train.shape[1])
        rows = dataset.n_samples // max(1, P)
        part_bytes = rows * F * np.dtype(est_dtype).itemsize
        part_bytes += rows * np.asarray(dataset.y_train).dtype.itemsize
        if dtype_name == "int8":
            part_bytes += F * 4
        w = _resolve_stream_window(cfg, P, part_bytes)
    if partition_major:
        blocks = layout.n_partitions
        if streamed and w < blocks:
            # a buffered window's device bytes are its STAGED span:
            # window + halo for the ring fill (deduped plans have halo 0)
            staged = w
            if cfg.compute_mode == ComputeMode.FAITHFUL:
                try:
                    staged = sharding_lib.plan_stream_windows(
                        layout, w, mode="ring"
                    ).staged_partitions
                except ValueError:
                    pass  # the run itself will refuse; charge the window
            blocks = min(blocks, 2 * staged)
        est = per_block * blocks
    else:
        est = worker_stack_est
        if streamed and w < layout.n_partitions:
            # materialized-faithful window: the slot-group gather is
            # group_workers x n_slots blocks = 1/n_windows of the worker
            # stack, double-buffered
            n_windows = layout.n_partitions // w
            est = worker_stack_est * min(1.0, 2.0 / n_windows)
    if cfg.pipeline_depth:
        # the pipelined scan carry pins one EXTRA params-sized buffer (the
        # tau=1-stale slot, parallel/pipeline.py) for the whole dispatch.
        # Charged at the dense-GLM params size — features + intercept in
        # float32 (params/optimizer state never ride the stack dtype) —
        # per pipeline depth. Tiny next to the data stack, but admission
        # is a bound and the slot is real residency, so it is counted.
        F = int(dataset.X_train.shape[1])
        est += cfg.pipeline_depth * (F + 1) * 4
    return int(est)


def cohort_signature(cfg: RunConfig) -> Optional[tuple]:
    """Grouping key for trajectory-batched dispatch (experiments.
    plan_cohorts): configs mapping to the same key share a device data
    stack and a compiled-scan lowering, so they can run as ONE cohort
    dispatch (train_cohort). None = not batchable (run sequentially).

    Deduped trajectories group by partition count alone — the
    partition-major stack is scheme-independent, so the whole 7-scheme
    compare() is one cohort. Faithful trajectories group by assignment
    CONTENT (materialized stacks and ring hop plans are both
    assignment-derived), so e.g. FRC and AGC share a cohort while cyclic
    MDS gets its own. Streamed trajectories group separately from
    resident ones without any extra key material: static_signature
    carries ``stack_residency`` and ``stream_window``, so a streamed
    cohort shares one WINDOW PLAN (and one windowed compiled scan,
    _train_cohort_streamed) the same way a resident cohort shares one
    stack."""
    if not cohort_eligible(cfg):
        return None
    from erasurehead_tpu.train import cache as cache_lib

    layout = build_layout(cfg)
    faithful = cfg.compute_mode == ComputeMode.FAITHFUL
    return (
        cfg.static_signature(),
        cfg.rounds,
        cfg.n_workers,
        cache_lib.layout_stack_signature(layout, worker_major=faithful),
    )


def train_cohort(
    cfgs: "Sequence[RunConfig] | RunConfig",
    dataset: Dataset,
    seeds=None,
    mesh=None,
    arrivals=None,
    measure: bool = True,
) -> list[TrainResult]:
    """Trajectory-batched dispatch: run a COHORT of training trajectories
    — (scheme, seed, lr/alpha variant) triples — as ONE compiled scan.

    The generalization of the seed-only ``train_batch``: every trajectory
    that shares a device data stack rides one vmapped/batched scan, so the
    gradient pass streams X from HBM once per round for the whole cohort
    instead of once per trajectory. For dense closed-form GLMs the margin
    lowers as a flat [M*R, F] x [F, B] matmul (parallel/step.
    _cohort_matmul_local_body) — a real MXU matmul fed by one HBM pass,
    which is the roofline lever kernel fusion could not move
    (BASELINE.md "Arithmetic intensity").

    ``cfgs`` is a sequence of fully-formed trajectory configs (a single
    config is accepted too); ``seeds`` optionally expands each config
    across a seed sweep (``replace(cfg, seed=s)``). ``arrivals`` is None
    (each trajectory builds its own default schedule, exactly as
    ``train()`` would), one shared [R, W] matrix (the paired-comparison
    contract of ``experiments.compare``), or a per-trajectory list.

    Contract and limits:
      - per-trajectory results match ``train()`` to float tolerance (the
        batched lowering changes only the reduction order — same math);
        control-plane artifacts (timeset, worker_times, collected,
        decode_error) are IDENTICAL, computed per trajectory on host;
      - all trajectories must share one device data stack: same rounds,
        workers, static lowering signature, and stack signature (deduped:
        partition count; faithful: assignment content). Group arbitrary
        config sets with ``experiments.plan_cohorts``;
      - the scan trainer only (no measured mode, no checkpointing, no
        forced pallas kernel);
      - every returned TrainResult carries the COHORT wall-clock (it was
        one dispatch) and the cohort-aggregate steps_per_sec.
    """
    if isinstance(cfgs, RunConfig):
        cfgs = [cfgs]
    cfgs = list(cfgs)
    if seeds is not None:
        seeds = [int(s) for s in seeds]
        cfgs = [
            dataclasses.replace(c, seed=s) for c in cfgs for s in seeds
        ]
    if not cfgs:
        raise ValueError("train_cohort needs at least one trajectory config")
    cfg0 = cfgs[0]
    for c in cfgs:
        if c.arrival_mode != "simulated":
            raise ValueError(
                "train_cohort batches the scan trainer; "
                "arrival_mode='measured' has no batched implementation"
            )
        if c.use_pallas == "on":
            raise ValueError(
                "train_cohort has no batched fused-kernel dispatch; "
                "use use_pallas='auto' or 'off'"
            )
        if c.pipeline_depth:
            raise PipelineRefusal(
                "cohort_batch",
                "train_cohort has no batched stale-carry scan; pipelined "
                "trajectories dispatch sequentially as per-run train() "
                "(experiments.plan_cohorts already routes them so)",
            )
    sig0 = cfg0.static_signature()
    for c in cfgs[1:]:
        if (
            c.static_signature() != sig0
            or c.rounds != cfg0.rounds
            or c.n_workers != cfg0.n_workers
        ):
            raise ValueError(
                "cohort trajectories must share rounds, workers, and the "
                "full static lowering signature (model, compute_mode, "
                "dtype, update_rule, ...); group mixed config sets with "
                "experiments.plan_cohorts"
            )
    if _resolve_residency(cfg0) == "streamed":
        # streamed cohorts (ISSUE 17): one windowed scan serves every
        # trajectory. static_signature carries stack_residency and
        # stream_window, so the equality check above already guarantees
        # the whole cohort resolves residency — and the window — the same
        # way; the store digest rides the shared dataset (plan_cohorts
        # groups per dataset, serve packing per dataset_token).
        store = _ensure_store(cfg0, dataset)
        window = _resolve_stream_window(
            cfg0, store.n_partitions, store.partition_bytes()
        )
        if window < store.n_partitions:
            return _train_cohort_streamed(
                cfg0, dataset, store, window, cfgs, mesh, arrivals,
                measure,
            )
        # full-cover window: the store's rehydrated view rides the
        # UNCHANGED resident cohort path (bitwise-identical for f32
        # stores; the single-window fast path train() also takes)
        if getattr(dataset, "_sweep_cache_token", None) != store.cache_token:
            dataset = store.dataset()
    return _train_cohort_impl(cfg0, dataset, cfgs, mesh, arrivals, measure)


@_with_run_sparse_lanes
def _train_cohort_impl(cfg, dataset, cfgs, mesh, arrivals, measure):
    from erasurehead_tpu.train import cache as cache_lib
    from erasurehead_tpu.utils import chaos as chaos_lib

    # chaos site "cohort": an injected kill here is a preemption mid-cohort
    # (nothing of the cohort persisted); an injected raise exercises the
    # sweep guard's OOM-bisection / transient-retry path
    # (experiments._dispatch_cohort)
    chaos_lib.maybe_fire("cohort")
    stats_before = cache_lib.stats().snapshot()
    B = len(cfgs)
    faithful = cfg.compute_mode == ComputeMode.FAITHFUL

    # one shared device stack across the cohort: deduped/ring stack
    # partition-major (scheme-independent), materialized faithful gathers
    # through the assignment — refuse mismatches rather than silently
    # training a different code than per-trajectory train() would
    layouts = [build_layout(c) for c in cfgs]
    stack0 = cache_lib.layout_stack_signature(
        layouts[0], worker_major=faithful
    )
    for c, lay in zip(cfgs[1:], layouts[1:]):
        if (
            cache_lib.layout_stack_signature(lay, worker_major=faithful)
            != stack0
        ):
            raise ValueError(
                f"trajectory {c.scheme.value!r} (seed {c.seed}) builds a "
                "different device data stack than the cohort's first "
                "trajectory; train_cohort shares one stack — group by "
                "cohort_signature (experiments.plan_cohorts) or run "
                "per-trajectory train()"
            )
    setup = _setup_run(cfg, dataset, mesh, faithful=faithful)
    layout, model, mesh, data = setup.layout, setup.model, setup.mesh, setup.data
    n_train = setup.n_train
    update_fn = setup.update_fn
    dtype = jnp.float32

    # per-trajectory control plane: arrivals + schedule + weight table
    # exactly as train() would build them for each config
    R, W = cfg.rounds, cfg.n_workers
    if arrivals is None:
        arr_list = [default_arrivals(c) for c in cfgs]
    elif isinstance(arrivals, (list, tuple)):
        if len(arrivals) != B:
            raise ValueError(
                f"got {len(arrivals)} arrival matrices for {B} trajectories"
            )
        arr_list = [np.asarray(a) for a in arrivals]
    else:
        arr_list = [np.asarray(arrivals)] * B
    schedules = [
        collect.build_schedule(
            c.scheme, a, lay, num_collect=c.num_collect,
            deadline=c.deadline, decode=c.decode,
        )
        for c, a, lay in zip(cfgs, arr_list, layouts)
    ]
    slot_ws = [
        np.asarray(
            step_lib.expand_slot_weights(
                s.message_weights, lay.coeffs, np.asarray(lay.slot_is_coded)
            )
        )
        for s, lay in zip(schedules, layouts)
    ]  # each [R, W, S] (S may differ only across stacks, refused above)

    ring_plan = None
    ring_pipe = setup.ring and step_lib.resolve_ring_pipeline(
        cfg.ring_pipeline, model, data.Xp
    )
    if faithful and setup.ring:
        ring_plan = plan_ring_transport(layout, _worker_axis_size(mesh))
        weights_seq = jnp.asarray(np.stack(slot_ws, axis=1), dtype)
        X, y = data.Xp, data.yp
    elif faithful:
        weights_seq = jnp.asarray(np.stack(slot_ws, axis=1), dtype)
        X, y = data.Xw, data.yw
    else:
        pws = [
            lay.fold_slot_weights(w) for lay, w in zip(layouts, slot_ws)
        ]
        weights_seq = jnp.asarray(np.stack(pws, axis=1), dtype)
        X, y = data.Xp, data.yp
    # weights_seq: [R, B, W, S] (faithful) or [R, B, Pn] (deduped) — round
    # axis leading for the ONE scan, trajectory axis next for the step

    # batched grad lowering: dense closed-form GLMs take the dedicated
    # cohort body (all B margins in one [N, F] x [F, B] matmul); other
    # stacks vmap the exact local bodies the sequential trainers use
    if cfg.flat_grad == "on" and not step_lib.supports_flat_grad(model, X):
        raise ValueError(
            "flat_grad='on' needs a closed-form GLM stack; "
            f"got model={getattr(model, 'name', type(model).__name__)!r}, "
            f"X={type(X).__name__}"
        )
    if cfg.layer_coding == "on" and not step_lib.supports_layer_coding(model):
        raise ValueError(
            "layer_coding='on' needs a model whose per-slot gradients are "
            "exact under the worker-axis step (no model-internal mesh "
            "axes; autodiff families need a jax without the implicit "
            "replicated-grad psum) — got "
            f"model={getattr(model, 'name', type(model).__name__)!r}"
        )
    if step_lib.resolve_layer_coding(cfg.layer_coding, model, X):
        # per-layer (blockwise) coded cohort: every trajectory's per-slot
        # gradient pytrees pack into the model's block table and decode
        # as one [B, P] x [P, L, width] einsum — DeepMLP layers and MoE
        # expert shards are the coded units (ops/blocks.py). The fused
        # block_decode lowering composes through the same vmap wrapper:
        # vmap(fused per-leaf contraction) is bitwise vmap(table einsum)
        # (tests/test_deep_coding.py pins the cohort pair too)
        from erasurehead_tpu.ops import blocks as blocks_lib

        spec = blocks_lib.model_block_spec(
            model, _init_params_f32(cfg, model, dataset.n_features)
        )
        contract = "ws" if faithful else "p"
        body = (
            step_lib._fused_layer_block_local_body(model, spec, contract)
            if step_lib.resolve_block_decode(cfg.block_decode, model, X)
            else step_lib._layer_block_local_body(model, spec, contract)
        )
        local_body = step_lib._batched_local_body(body)
        cohort_lowering = "layer_block_vmap"
    elif step_lib.supports_cohort_matmul(model, X):
        local_body = step_lib._cohort_matmul_local_body(model)
        cohort_lowering = "cohort_matmul"
    elif step_lib.resolve_flat_grad(cfg.flat_grad, model, X):
        local_body = step_lib._batched_local_body(
            step_lib._flat_local_body(model)
        )
        cohort_lowering = "flat_vmap"
    else:
        local_body = None  # the compute mode's default body, vmapped
        cohort_lowering = "per_slot_vmap"
    grad_fn = step_lib.make_cohort_grad_fn(
        model, mesh, faithful=faithful, ring_plan=ring_plan,
        local_body=local_body, ring_pipeline=ring_pipe,
    )

    # per-trajectory init + optimizer state, stacked on a leading [B] axis
    states = [
        optimizer.init_state(
            _init_params_f32(c, model, dataset.n_features), cfg.update_rule
        )
        for c in cfgs
    ]
    state0 = jax.tree.map(lambda *ls: jnp.stack(ls), *states)
    state0 = jax.tree.map(
        lambda l: put_global(np_global(l), replicated(mesh)), state0
    )
    lr_seq = jnp.asarray(
        np.stack([c.resolve_lr_schedule() for c in cfgs], axis=1), dtype
    )  # [R, B] — lr variants are first-class trajectory axes
    alpha_B = jnp.asarray([c.effective_alpha for c in cfgs], dtype)  # [B]
    iters = jnp.arange(cfg.rounds, dtype=dtype)

    # per-trajectory update: vmap over (state, grad, lr, alpha); the
    # round index and sample count are shared scalars
    b_update = jax.vmap(update_fn, in_axes=(0, 0, 0, 0, None, None))

    from erasurehead_tpu.utils.tracing import annotate

    def body(Xa, ya, alphas, state, xs):
        eta_t, w_t, i = xs
        with annotate("eh_scan/coded_step"):
            g = grad_fn(state.params, Xa, ya, w_t)
        with annotate("eh_scan/update"):
            new_state = b_update(state, g, eta_t, alphas, n_train, i)
        return new_state, new_state.params

    def _run(state, Xa, ya, alphas, lr_c, w_c, it_c):
        return jax.lax.scan(
            partial(body, Xa, ya, alphas), state, (lr_c, w_c, it_c),
            unroll=cfg.scan_unroll,
        )

    # buffer donation, cohort form: the [B]-stacked carry (argnum 0) and
    # the [R, B, ...] per-trajectory weight tables (argnum 5) are the
    # B-fold duplicated buffers that cap cohort width — donating them
    # frees that HBM for the dispatch. The shared data stack is never
    # donated (it may be the data cache's pinned upload).
    donate = _resolve_donate(cfg)
    run = jax.jit(_run, donate_argnums=(0, 5) if donate else ())

    platform = jax.devices()[0].platform
    from erasurehead_tpu.obs import decode as obs_decode
    from erasurehead_tpu.obs import detect as obs_detect
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.obs.metrics import REGISTRY as _metrics

    schemes = sorted({c.scheme.value for c in cfgs})
    run_id = obs_events.new_run_id() if obs_events.current() else None
    if run_id is not None:
        _emit_run_start(
            run_id, cfg, setup, platform,
            step_lib.lowering_signature(cfg, model, X), faithful,
        )
        obs_events.emit(
            "cohort",
            run_id=run_id,
            n_trajectories=B,
            schemes=schemes,
            seeds=[c.seed for c in cfgs],
            dispatches=1,
            lowering=cohort_lowering,
        )
    # dispatch-amortization counters (obs/metrics.py): what the smoke
    # target and the acceptance test read — N trajectories per dispatch
    _metrics.counter("cohort.dispatches").inc()
    _metrics.counter("cohort.trajectories").inc(B)

    # executable cache key: cohort stack signature rides in via the
    # data/weights shapes + mesh; B via batch_size; the lowering via
    # static_signature + the resolved cohort_lowering. Per-trajectory
    # alpha/lr/weights are traced ARGUMENTS — cohorts differing only in
    # hyperparameters share the compiled scan (the amortization point).
    sig_fields = _exec_signature_fields(
        "cohort_scan", platform, cfg, model, X, y, False, ring_plan,
        weights_seq.shape, mesh, state0, 0.0, n_train,
        ring_pipeline=ring_pipe, donation=donate,
        batch_size=B, chunk_rounds=cfg.rounds,
        cohort_lowering=cohort_lowering,
    )
    exec_sig = tuple(sig_fields.values())

    def _compile():
        t0 = time.perf_counter()
        with _quiet_donation_warnings():
            ex = run.lower(
                state0, X, y, alpha_B, lr_seq, weights_seq, iters
            ).compile()
        if measure:
            # the warm-up consumes its donated args (carry + weight
            # table); the timed dispatch below still needs the originals
            st = _donate_copy(state0) if donate else state0
            ws = _donate_copy(weights_seq) if donate else weights_seq
            _hard_sync(ex(st, X, y, alpha_B, lr_seq, ws, iters)[0])
        return ex, time.perf_counter() - t0

    t_cmp = time.perf_counter()
    ex, hit = cache_lib.get_or_compile(exec_sig, _compile)
    cmp_secs = time.perf_counter() - t_cmp
    if not hit:
        obs_detect.observe_and_warn(sig_fields, run_id)
    if run_id is not None:
        obs_events.emit(
            "compile",
            run_id=run_id,
            seconds=round(cmp_secs, 4),
            cache_hit=hit,
            chunk_rounds=cfg.rounds,
            memory_analysis=_memory_analysis(ex),
        )

    t0 = time.perf_counter()
    final_state, history = ex(
        state0, X, y, alpha_B, lr_seq, weights_seq, iters
    )
    _hard_sync(final_state)
    wall = time.perf_counter() - t0

    stats_after = cache_lib.stats().snapshot()
    cache_info = {
        "enabled": cache_lib.enabled(),
        "data_hit": setup.data_cache_hit,
        "exec_hits": int(hit),
        "exec_misses": int(not hit),
        "compile_seconds_saved": round(
            stats_after["compile_seconds_saved"]
            - stats_before["compile_seconds_saved"],
            4,
        ),
        "bytes_reused": stats_after["bytes_reused"]
        - stats_before["bytes_reused"],
        # seed-sweep-era names kept for compatibility + the cohort view
        "batch_size": B,
        "batch_dispatches": 1,
        "cohort_size": B,
        "cohort_dispatches": 1,
        "cohort_schemes": schemes,
        "cohort_lowering": cohort_lowering,
        "stack_mode": (
            "ring"
            if setup.ring
            else ("materialized" if faithful else "deduped")
        ),
        "stack_dtype": setup.stack_dtype,
        "ring_pipeline": (
            ("pipelined" if ring_pipe else "sequential")
            if setup.ring
            else None
        ),
        "donation": donate,
        "stack_bytes": cache_lib.device_nbytes(data),
        "memory_analysis": _memory_analysis(ex),
    }
    results = []
    agg_rate = cfg.rounds * B / wall if wall > 0 else 0.0
    batch_err = []
    for b, (c, sched, lay) in enumerate(zip(cfgs, schedules, layouts)):
        fs = jax.tree.map(lambda l: l[b], final_state)
        err = obs_decode.decode_error_series(lay, sched.message_weights)
        batch_err.append(err)
        results.append(
            TrainResult(
                # scan history leaves are [R, B, ...]: round axis leading
                params_history=jax.tree.map(lambda l: l[:, b], history),
                final_params=fs.params,
                final_state=fs,
                timeset=sched.sim_time,
                worker_times=sched.worker_times,
                collected=sched.collected,
                sim_total_time=float(sched.sim_time.sum()),
                wall_time=wall,
                steps_per_sec=agg_rate,
                n_train=n_train,
                config=c,
                layout=lay,
                decode_error=err,
                run_id=run_id,
                cache_info=dict(cache_info),
            )
        )
    if run_id is not None:
        # one run_end for the whole cohort (it WAS one dispatch);
        # per-trajectory round/decode series carry a trajectory tag, and
        # all arrival stats flow through arrival_summary, which masks the
        # -1 never-arrived sentinel (obs/events.py)
        for b, (c, sched, err) in enumerate(
            zip(cfgs, schedules, batch_err)
        ):
            obs_events.emit_round_chunks(
                run_id,
                start_round=0,
                timeset=sched.sim_time,
                worker_times=sched.worker_times,
                decode_error=err,
                trajectory=f"{b}:{c.scheme.value}:s{c.seed}",
            )
        obs_events.emit(
            "run_end",
            run_id=run_id,
            wall_time_s=round(wall, 6),
            steps_per_sec=round(agg_rate, 4),
            batch_size=B,
            cohort_size=B,
            exec_hits=int(hit),
            exec_misses=int(not hit),
            data_cache_hit=setup.data_cache_hit,
            compile_seconds=round(cmp_secs, 4),
            stack_bytes=cache_lib.device_nbytes(data),
            arrival=obs_events.arrival_summary(
                np.stack([s.worker_times for s in schedules])
            ),
            **obs_decode.summarize(np.concatenate(batch_err)),
        )
        from erasurehead_tpu.obs import critical_path as obs_cpath

        # one attribution for the one dispatch: the cohort's B schedules
        # concatenate along the round axis, so the sim ledger decomposes
        # the summed simulated clock while wall_s stays the cohort wall
        obs_cpath.emit_event(
            run_id,
            obs_cpath.attribute(
                np.concatenate([s.sim_time for s in schedules]),
                np.concatenate([s.worker_times for s in schedules]),
                np.concatenate([s.collected for s in schedules]),
                wall_s=wall,
                transport="ring" if setup.ring else "none",
            ),
        )
    return results


@_with_run_sparse_lanes
def _train_cohort_streamed(
    cfg, dataset, store, window, cfgs, mesh, arrivals, measure
):
    """Trajectory-batched WINDOWED scan over a shard store — the streamed
    counterpart of :func:`_train_cohort_impl` (ISSUE 17 tentpole part 3).

    A 7-scheme x 4-seed sweep over a disk-resident store used to
    dispatch as 28 sequential streamed runs, each re-staging every
    window; here the whole cohort rides ONE compiled windowed scan per
    chunk length — one prefetch stream, one window staging per chunk,
    B trajectories of arithmetic per staged window (the same B-fold
    intensity lever as the resident cohort engine, PR 4). Per-trajectory
    semantics are _train_streamed's block training exactly: same window
    plan, same chunk/window cycle, same per-window weight slices — the
    cohort-streamed rows == sequential-streamed rows pin
    (tests/test_outofcore.py) rests on that mirroring.

    Trainer-side mirror of _train_cohort_impl otherwise: per-trajectory
    control planes, [B]-stacked optimizer state, vmapped update, the
    cohort body ladder (minus the layer-block form — no windowed
    blockwise body; _check_streamed_compat refused the forced knob), one
    ``cohort`` event + dispatch counters, one run_end."""
    from math import gcd

    from erasurehead_tpu.data.prefetch import Prefetcher
    from erasurehead_tpu.data.sharding import plan_stream_windows
    from erasurehead_tpu.obs import decode as obs_decode
    from erasurehead_tpu.obs import detect as obs_detect
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.obs.metrics import REGISTRY as _metrics
    from erasurehead_tpu.parallel import mesh as mesh_lib
    from erasurehead_tpu.train import cache as cache_lib
    from erasurehead_tpu.utils import chaos as chaos_lib
    from erasurehead_tpu.utils.tracing import annotate

    # same chaos site as the resident cohort dispatch: a kill here is a
    # mid-cohort preemption (journal rehydration is the recovery), a
    # raise exercises the sweep guard's bisection/retry ladder
    chaos_lib.maybe_fire("cohort")
    _check_streamed_compat(cfg)
    stats_before = cache_lib.stats().snapshot()
    B = len(cfgs)
    faithful = cfg.compute_mode == ComputeMode.FAITHFUL

    layouts = [build_layout(c) for c in cfgs]
    stack0 = cache_lib.layout_stack_signature(
        layouts[0], worker_major=faithful
    )
    for c, lay in zip(cfgs[1:], layouts[1:]):
        if (
            cache_lib.layout_stack_signature(lay, worker_major=faithful)
            != stack0
        ):
            raise ValueError(
                f"trajectory {c.scheme.value!r} (seed {c.seed}) builds a "
                "different device data stack than the cohort's first "
                "trajectory; train_cohort shares one stack — group by "
                "cohort_signature (experiments.plan_cohorts) or run "
                "per-trajectory train()"
            )
    layout = layouts[0]
    model = build_model(cfg)
    Pn, rows = store.n_partitions, store.rows_per_partition
    mode = (
        ("ring" if _resolve_stream_ring(cfg, layout) else "materialized")
        if faithful
        else "deduped"
    )
    try:
        plan = plan_stream_windows(layout, window, mode=mode)
    except ValueError as e:
        raise ValueError(f"{e} — or {_stream_remedy(cfg)}") from None
    n_windows = plan.n_windows
    gw = plan.group_workers
    if mesh is None:
        if mode == "deduped":
            mesh = _auto_mesh(window)
        elif mode == "materialized":
            mesh = _auto_mesh(gw)
        else:
            mesh = _auto_mesh(gcd(gw, plan.staged_partitions))
    if mode == "deduped":
        mesh_lib.check_divisible(window, mesh, "stream_window")
    else:
        mesh_lib.check_divisible(gw, mesh, "stream slot-group workers")
        if mode == "ring":
            mesh_lib.check_divisible(
                plan.staged_partitions, mesh, "staged stream window"
            )
    if hasattr(model, "for_mesh"):
        model = model.for_mesh(mesh)
    stack_dtype = cfg.resolve_stack_dtype()
    if store.quantized and stack_dtype != "int8":
        raise ValueError(
            f"int8 shard store requires stack_dtype='int8' (resolved "
            f"{stack_dtype!r}): re-uploading a dequantized window would "
            "silently train on reconstructed values"
        )
    cast_dtype = jnp.dtype(
        cfg.dtype if stack_dtype == "int8" else stack_dtype
    )
    n_train = window * rows
    dtype = jnp.float32

    # per-trajectory control plane: exactly _train_cohort_impl's
    if arrivals is None:
        arr_list = [default_arrivals(c) for c in cfgs]
    elif isinstance(arrivals, (list, tuple)):
        if len(arrivals) != B:
            raise ValueError(
                f"got {len(arrivals)} arrival matrices for {B} trajectories"
            )
        arr_list = [np.asarray(a) for a in arrivals]
    else:
        arr_list = [np.asarray(arrivals)] * B
    schedules = [
        collect.build_schedule(
            c.scheme, a, lay, num_collect=c.num_collect,
            deadline=c.deadline, decode=c.decode,
        )
        for c, a, lay in zip(cfgs, arr_list, layouts)
    ]
    slot_ws = [
        np.asarray(
            step_lib.expand_slot_weights(
                s.message_weights, lay.coeffs, np.asarray(lay.slot_is_coded)
            )
        )
        for s, lay in zip(schedules, layouts)
    ]  # each [R, W, S]
    if mode == "deduped":
        pws = [
            lay.fold_slot_weights(w) for lay, w in zip(layouts, slot_ws)
        ]
        weights_np = np.stack(pws, axis=1)  # [R, B, P]
    elif n_windows > 1:
        # sub-full faithful windows: per-slot-group decode per trajectory
        # (_train_streamed's rule exactly — the cohort == sequential rows
        # pin needs the same weights)
        weights_np = np.stack(
            [
                _stream_group_slot_weights(lay, plan, s)
                for s, lay in zip(schedules, layouts)
            ],
            axis=1,
        )  # [R, B, K, gw, S]
    else:
        weights_np = np.stack(slot_ws, axis=1)  # [R, B, W, S]
    ring_pipe = mode == "ring" and step_lib.resolve_ring_pipeline(
        cfg.ring_pipeline
    )
    sub_ring = (
        plan_ring_transport(plan.sub_layout(), _worker_axis_size(mesh))
        if mode == "ring"
        else None
    )

    states = [
        optimizer.init_state(
            _init_params_f32(c, model, store.n_features), cfg.update_rule
        )
        for c in cfgs
    ]
    state0 = jax.tree.map(lambda *ls: jnp.stack(ls), *states)
    state0 = jax.tree.map(
        lambda l: put_global(np_global(l), replicated(mesh)), state0
    )
    lr_seq_np = np.stack(
        [c.resolve_lr_schedule() for c in cfgs], axis=1
    )  # [R, B]
    alpha_B = jnp.asarray([c.effective_alpha for c in cfgs], dtype)
    iters_np = np.arange(cfg.rounds)
    update_fn = optimizer.make_update_fn(cfg.update_rule)
    b_update = jax.vmap(update_fn, in_axes=(0, 0, 0, 0, None, None))

    # round chunks and the window cycle: byte-for-byte _train_streamed's
    # (the cohort == sequential rows pin needs the same block schedule)
    L = max(1, cfg.rounds // n_windows)
    bounds = list(range(0, cfg.rounds, L)) + [cfg.rounds]
    chunks = [
        (lo, hi) for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo
    ]
    win_of = [i % n_windows for i in range(len(chunks))]
    windows = [plan.ranges[k] for k in win_of]

    sharding = mesh_lib.worker_sharding(mesh)
    quantize = stack_dtype == "int8"
    put = _make_stream_put(plan, sharding, quantize, cast_dtype)

    def body(Xa, ya, state, xs):
        eta_t, w_t, i = xs
        with annotate("eh_scan/coded_step"):
            g = grad_fn(state.params, Xa, ya, w_t)
        with annotate("eh_scan/update"):
            new_state = b_update(state, g, eta_t, alpha_B, n_train, i)
        return new_state, new_state.params

    def _run(state, Xa, ya, lr_c, w_c, it_c):
        return jax.lax.scan(
            partial(body, Xa, ya), state, (lr_c, w_c, it_c),
            unroll=cfg.scan_unroll,
        )

    donate = _resolve_donate(cfg)
    run = jax.jit(_run, donate_argnums=(0, 4) if donate else ())

    def slices(lo, hi, k):
        if mode == "deduped":
            plo = k * window
            w_c = weights_np[lo:hi, :, plo:plo + window]
        elif n_windows > 1:
            w_c = weights_np[lo:hi, :, k]  # per-group decode [.., gw, S]
        else:
            w_c = weights_np[lo:hi, :, k * gw:(k + 1) * gw, :]
        return (
            jnp.asarray(lr_seq_np[lo:hi], dtype),
            jnp.asarray(w_c, dtype),
            jnp.asarray(iters_np[lo:hi], dtype),
        )

    platform = jax.devices()[0].platform
    schemes_list = sorted({c.scheme.value for c in cfgs})
    run_id = obs_events.new_run_id() if obs_events.current() else None
    exec_hits = exec_misses = 0
    compile_seconds = 0.0
    pieces = []
    wall = 0.0
    state = state0
    mem_info = None
    pf = Prefetcher(
        store, windows, put, run_id=run_id,
        plan_fields=plan.event_fields(),
    )
    try:
        X0, y0 = pf.get(0)
        window_nbytes = cache_lib.device_nbytes((X0, y0))
        # the cohort body ladder (minus layer-block), resolved on the
        # staged window's device types like _train_cohort_impl resolves
        # on the resident stack's
        if cfg.flat_grad == "on" and not step_lib.supports_flat_grad(
            model, X0
        ):
            raise ValueError(
                "flat_grad='on' needs a closed-form GLM stack; "
                f"got model="
                f"{getattr(model, 'name', type(model).__name__)!r}, "
                f"X={type(X0).__name__}"
            )
        if step_lib.supports_cohort_matmul(model, X0):
            local_body = step_lib._cohort_matmul_local_body(model)
            cohort_lowering = "cohort_matmul"
        elif step_lib.resolve_flat_grad(cfg.flat_grad, model, X0):
            local_body = step_lib._batched_local_body(
                step_lib._flat_local_body(model)
            )
            cohort_lowering = "flat_vmap"
        else:
            local_body = None  # the compute mode's default body, vmapped
            cohort_lowering = "per_slot_vmap"
        grad_fn = step_lib.make_cohort_grad_fn(
            model, mesh, faithful=faithful, ring_plan=sub_ring,
            local_body=local_body, ring_pipeline=ring_pipe,
        )
        if run_id is not None:
            _emit_run_start(
                run_id, cfg,
                _RunSetup(
                    layout=layout, model=model, mesh=mesh, data=(X0, y0),
                    state0=state0, update_fn=update_fn,
                    lr=cfg.resolve_lr_schedule(), alpha=0.0,
                    n_train=n_train, stack_dtype=stack_dtype,
                    ring=mode == "ring",
                ),
                platform, step_lib.lowering_signature(cfg, model, X0),
                faithful=faithful,
            )
            obs_events.emit(
                "cohort",
                run_id=run_id,
                n_trajectories=B,
                schemes=schemes_list,
                seeds=[c.seed for c in cfgs],
                dispatches=1,
                lowering=cohort_lowering,
            )
        # one cohort dispatch per window CYCLE, not per trajectory — the
        # amortization the smoke target asserts via these counters
        _metrics.counter("cohort.dispatches").inc()
        _metrics.counter("cohort.trajectories").inc(B)
        sig_fields = _exec_signature_fields(
            "cohort_scan_streamed", platform, cfg, model, X0, y0, False,
            sub_ring,
            (B, window) if mode == "deduped" else (B, gw, layout.n_slots),
            mesh, state0, 0.0, n_train, ring_pipeline=ring_pipe,
            donation=donate, batch_size=B,
            cohort_lowering=cohort_lowering,
            stream_plan=(mode, window, plan.halo, gw),
        )
        exec_sig = tuple(sig_fields.values())
        compiled = {}
        for idx, (lo, hi) in enumerate(chunks):
            n = hi - lo
            if n in compiled:
                continue

            def _compile(lo=lo, hi=hi, k=win_of[idx]):
                t0 = time.perf_counter()
                with _quiet_donation_warnings():
                    ex = run.lower(
                        state0, X0, y0, *slices(lo, hi, k)
                    ).compile()
                if measure:
                    lr_c, w_c, it_c = slices(lo, hi, k)
                    st = _donate_copy(state0) if donate else state0
                    _hard_sync(ex(st, X0, y0, lr_c, w_c, it_c)[0])
                return ex, time.perf_counter() - t0

            t_cmp = time.perf_counter()
            compiled[n], hit = cache_lib.get_or_compile(
                exec_sig + (n,), _compile
            )
            cmp_secs = time.perf_counter() - t_cmp
            compile_seconds += cmp_secs
            if hit:
                exec_hits += 1
            else:
                exec_misses += 1
                obs_detect.observe_and_warn(
                    {**sig_fields, "chunk_rounds": n}, run_id
                )
            if run_id is not None:
                obs_events.emit(
                    "compile",
                    run_id=run_id,
                    seconds=round(cmp_secs, 4),
                    cache_hit=hit,
                    chunk_rounds=n,
                    memory_analysis=_memory_analysis(compiled[n]),
                )

        for i, (lo, hi) in enumerate(chunks):
            # timed region includes the staging wait (same honesty rule
            # as _train_streamed: unhidden transfer time is overhead)
            t0 = time.perf_counter()
            Xd, yd = (X0, y0) if i == 0 else pf.get(i)
            state, hist = compiled[hi - lo](
                state, Xd, yd, *slices(lo, hi, win_of[i])
            )
            _hard_sync(state)
            wall += time.perf_counter() - t0
            pieces.append(hist)
        mem_info = _memory_analysis(next(iter(compiled.values())))
    finally:
        pf.close()
    pf_stats = pf.stats()
    final_state = state
    history = (
        pieces[0]
        if len(pieces) == 1
        else jax.tree.map(lambda *xs: jnp.concatenate(xs), *pieces)
    )
    stats_after = cache_lib.stats().snapshot()
    agg_rate = cfg.rounds * B / wall if wall > 0 else 0.0
    cache_info = {
        "enabled": cache_lib.enabled(),
        "data_hit": False,  # windows are transient by design
        "exec_hits": exec_hits,
        "exec_misses": exec_misses,
        "compile_seconds_saved": round(
            stats_after["compile_seconds_saved"]
            - stats_before["compile_seconds_saved"],
            4,
        ),
        "bytes_reused": stats_after["bytes_reused"]
        - stats_before["bytes_reused"],
        "batch_size": B,
        "batch_dispatches": 1,
        "cohort_size": B,
        "cohort_dispatches": 1,
        "cohort_schemes": schemes_list,
        "cohort_lowering": cohort_lowering,
        "stack_mode": mode,
        "stack_dtype": stack_dtype,
        "ring_pipeline": (
            ("pipelined" if ring_pipe else "sequential")
            if mode == "ring"
            else None
        ),
        "donation": donate,
        "stack_bytes": window_nbytes,
        "memory_analysis": mem_info,
        "residency": "streamed",
        "stream_window": window,
        "n_windows": n_windows,
        "stream_halo": plan.halo,
        "stream_group_workers": gw,
        "prefetch": pf_stats,
    }
    results = []
    batch_err = []
    for b, (c, sched, lay) in enumerate(zip(cfgs, schedules, layouts)):
        fs = jax.tree.map(lambda l: l[b], final_state)
        err = obs_decode.decode_error_series(lay, sched.message_weights)
        batch_err.append(err)
        results.append(
            TrainResult(
                params_history=jax.tree.map(lambda l: l[:, b], history),
                final_params=fs.params,
                final_state=fs,
                timeset=sched.sim_time,
                worker_times=sched.worker_times,
                collected=sched.collected,
                sim_total_time=float(sched.sim_time.sum()),
                wall_time=wall,
                steps_per_sec=agg_rate,
                n_train=n_train,
                config=c,
                layout=lay,
                decode_error=err,
                run_id=run_id,
                cache_info=dict(cache_info),
            )
        )
    if run_id is not None:
        for b, (c, sched, err) in enumerate(
            zip(cfgs, schedules, batch_err)
        ):
            obs_events.emit_round_chunks(
                run_id,
                start_round=0,
                timeset=sched.sim_time,
                worker_times=sched.worker_times,
                decode_error=err,
                trajectory=f"{b}:{c.scheme.value}:s{c.seed}",
            )
        obs_events.emit(
            "run_end",
            run_id=run_id,
            wall_time_s=round(wall, 6),
            steps_per_sec=round(agg_rate, 4),
            batch_size=B,
            cohort_size=B,
            exec_hits=exec_hits,
            exec_misses=exec_misses,
            data_cache_hit=False,
            compile_seconds=round(compile_seconds, 4),
            stack_bytes=window_nbytes,
            arrival=obs_events.arrival_summary(
                np.stack([s.worker_times for s in schedules])
            ),
            **obs_decode.summarize(np.concatenate(batch_err)),
        )
        from erasurehead_tpu.obs import critical_path as obs_cpath

        obs_cpath.emit_event(
            run_id,
            obs_cpath.attribute(
                np.concatenate([s.sim_time for s in schedules]),
                np.concatenate([s.worker_times for s in schedules]),
                np.concatenate([s.collected for s in schedules]),
                wall_s=wall,
                prefetch_stall_s=float(pf_stats.get("blocked_s", 0.0)),
                transport="ring" if mode == "ring" else "none",
            ),
        )
    return results


def train_batch(
    cfg: RunConfig,
    dataset: Dataset,
    seeds,
    mesh=None,
    measure: bool = True,
) -> list[TrainResult]:
    """Seed-sweep batched runner — now a thin wrapper over the
    trajectory-cohort engine (:func:`train_cohort`); see MIGRATION.md.

    Equivalent to ``[train(replace(cfg, seed=s), dataset) for s in
    seeds]`` as one compiled dispatch. Kept for compatibility with its
    original contract: schemes whose LAYOUT depends on the seed (cyclic
    MDS, random-regular, partial cyclic) are refused whenever the seeds
    actually produce different layouts — even in deduped mode, where
    ``train_cohort`` itself could batch them (its per-trajectory weight
    tables handle differing layouts over one partition-major stack).
    """
    seeds = [int(s) for s in seeds]
    if not seeds:
        raise ValueError("train_batch needs at least one seed")
    if cfg.arrival_mode != "simulated":
        raise ValueError(
            "train_batch batches the scan trainer; arrival_mode='measured' "
            "has no batched implementation"
        )
    if cfg.use_pallas == "on":
        raise ValueError(
            "train_batch has no batched fused-kernel dispatch; "
            "use use_pallas='auto' or 'off'"
        )
    cfgs = [dataclasses.replace(cfg, seed=s) for s in seeds]
    layouts = [build_layout(c) for c in cfgs]
    a0 = np.asarray(layouts[0].assignment)
    c0 = np.asarray(layouts[0].coeffs)
    for lay in layouts[1:]:
        if not (
            np.array_equal(a0, np.asarray(lay.assignment))
            and np.array_equal(c0, np.asarray(lay.coeffs))
        ):
            raise ValueError(
                f"scheme {cfg.scheme.value!r} builds a seed-dependent "
                "layout across these seeds; train_batch shares one data "
                "stack — run per-seed train() for seed-dependent codes"
            )
    return train_cohort(cfgs, dataset, mesh=mesh, measure=measure)


def _make_worker_msg(model):
    """One worker's transmitted message: its per-slot gradient stack.

    ``n`` (the work multiplier) folds INSIDE the executable as a
    fori_loop — n x the device compute in ONE dispatch, with a
    bitwise-identical message. Repeating the dispatch instead would make
    Python dispatch overhead the "work", which on fast backends finishes
    before any ordering is observable. Each iteration consumes the
    previous message through a multiplier that is always exactly 1.0 but
    not provably so (an optimization_barrier chain measured elided on
    the CPU backend; this dependence survives — verified n-linear cost).
    Shared by the single-process and multi-controller measured paths so
    the dependence hack can never drift between them."""

    @partial(jax.jit, static_argnames="n")
    def worker_msg(params, Xs, ys, n=1):
        def one(p):
            return jax.vmap(lambda X, y: model.grad_sum(p, X, y))(Xs, ys)

        if n == 1:
            return one(params)

        def body(_, m):
            s = jax.tree.leaves(m)[0].sum()
            dep = jnp.where(jnp.isnan(s), 1.0, jnp.sign(jnp.abs(s) + 1.0))
            return one(jax.tree.map(lambda l: l * dep, params))

        return jax.lax.fori_loop(0, n - 1, body, one(params))

    return worker_msg


@_with_run_sparse_lanes
def train_measured(
    cfg: RunConfig,
    dataset: Dataset,
    mesh=None,
    work_multiplier=None,
) -> TrainResult:
    """Measured-arrival mode (SURVEY §7.4's "real delay" mode).

    Every round, each logical worker's coded message is computed as its own
    executable dispatch and its real wall-clock is measured; those measured
    arrivals (plus the injected exponential delays when ``add_delay`` is on,
    matching the reference where worker latency = compute + sleep) feed the
    scheme's collection rule *online*, per round — so ``worker_times`` is a
    measurement again, like the reference's Waitany-stamped
    ``worker_timeset`` (src/naive.py:106), not a precomputed simulation.
    Under real per-worker imbalance the collected set genuinely differs
    from the homogeneous schedule (tests/test_measured.py).

    On a single device, workers are timed sequentially in isolation (pure
    compute heterogeneity — concurrency on one chip would be fake). On a
    >1-device ``mesh``, logical workers are pinned round-robin to devices
    and each device's queue is replayed on its own clock: a worker's
    measured arrival = queue wait behind the workers sharing its chip +
    its own compute, so per-DEVICE load imbalance genuinely changes the
    collected sets (VERDICT r2 item 6; tests/test_measured.py's
    multidevice cases).

    The cost model is honest but slow: one dispatch per (round, worker) is
    inherent to measuring workers separately. Use :func:`train` (one scan)
    for throughput benchmarking; this mode is for heterogeneity diagnosis
    and online-collection experiments.

    ``work_multiplier``: optional [W] ints — worker w recomputes its
    message that many times, inducing real compute imbalance (a stand-in
    for heterogeneous chips, and the test hook).
    """
    # configured *simulated* heterogeneity contradicts measuring the real
    # thing, and the other trainer knobs below have no measured-mode
    # implementation — refuse rather than silently run something else
    if cfg.pipeline_depth:
        # belt-and-braces: RunConfig already refuses measured+pipelined,
        # but train_measured is also callable with simulated-mode configs
        raise PipelineRefusal(
            "measured_arrivals",
            "pipeline_depth=1 has no measured-arrival implementation: "
            "online per-round collection cannot overlap rounds whose "
            "arrivals it has not measured yet",
        )
    if cfg.compute_time or cfg.worker_speed_spread:
        raise ValueError(
            "arrival_mode='measured' measures real per-worker compute; "
            "simulated heterogeneity (compute_time/worker_speed_spread) "
            "does not apply — unset it or use the simulated trainer"
        )
    if cfg.compute_mode != ComputeMode.FAITHFUL:
        raise ValueError(
            "arrival_mode='measured' times each worker's own (redundant) "
            "slot compute; only compute_mode='faithful' is meaningful"
        )
    if cfg.use_pallas == "on":
        raise ValueError(
            "arrival_mode='measured' has no fused-kernel path; "
            "use use_pallas='auto' or 'off'"
        )
    if cfg.flat_grad == "on":
        raise ValueError(
            "arrival_mode='measured' times each worker's own message "
            "separately; the flat-stack lowering fuses all slots into one "
            "matmul and cannot be timed per worker — use flat_grad='auto' "
            "or 'off'"
        )
    if cfg.margin_flat == "on":
        raise ValueError(
            "arrival_mode='measured' times each worker's own message "
            "separately; the flat-margin lowering fuses all slots' margins "
            "into one matmul and cannot be timed per worker — use "
            "margin_flat='auto' or 'off'"
        )
    if cfg.scan_unroll != 1:
        raise ValueError(
            "arrival_mode='measured' drives rounds from the host (no "
            "lax.scan to unroll); scan_unroll has no measured-mode "
            "implementation — leave it at 1"
        )
    from erasurehead_tpu import schemes as schemes_lib

    if not schemes_lib.get(cfg.scheme).supports_measured:
        # e.g. the partial schemes: the reference's partial worker really
        # sends its uncoded first part BEFORE computing the coded second
        # (src/partial_coded.py:226-234); this mode times ONE combined
        # message per worker, so it cannot observe the staggered two-part
        # arrival it exists to measure — refuse rather than silently
        # measure a different protocol (the descriptor's supports_measured
        # capability flag carries the same contract for extension schemes)
        raise ValueError(
            "arrival_mode='measured' has no two-part message timing: the "
            "partial schemes send their uncoded part before the coded part "
            "is computed, and timing one combined dispatch would "
            "misattribute the arrival the mode exists to measure — use the "
            "simulated trainer for partial schemes"
        )
    # ring_ok=False: this mode times each worker's own resident slot stack
    # per dispatch; the ring transport only exists inside the SPMD step
    setup = _setup_run(
        cfg, dataset, mesh, faithful=True, single_device=True, ring_ok=False
    )
    layout, model, data = setup.layout, setup.model, setup.data
    W = layout.n_workers
    mult = (
        np.ones(W, dtype=np.int64)
        if work_multiplier is None
        else np.asarray(work_multiplier, dtype=np.int64)
    )
    if mult.shape != (W,) or (mult < 1).any():
        raise ValueError(f"work_multiplier must be [W] ints >= 1, got {mult}")

    dtype = jnp.float32
    lr = setup.lr
    alpha = setup.alpha
    n_train = setup.n_train
    coeffs = np.asarray(layout.coeffs)
    slot_coded = np.asarray(layout.slot_is_coded)
    update_fn = setup.update_fn
    state = setup.state0

    if jax.process_count() > 1:
        # multi-controller: every process is a replica of the reference's
        # master, timing only ITS OWN devices' worker queues. An explicit
        # mesh narrows the device pool, as in the single-process path;
        # mesh=None means every device in the cluster.
        return _train_measured_cluster(
            cfg, dataset, setup, mult, dtype, mesh=mesh
        )

    worker_msg = _make_worker_msg(model)

    @jax.jit
    def decode_update(st, per_slot, slot_w, eta, i):
        g = step_lib._weighted_tree_sum(slot_w, per_slot, "ws")
        return update_fn(st, g, eta, alpha, n_train, i)

    def worker_slice(w):
        return (
            jax.tree.map(lambda l: l[w], data.Xw),
            jax.tree.map(lambda l: l[w], data.yw),
        )

    # injected delay component on top of real compute, like the reference's
    # post-compute sleep (src/naive.py:140-149)
    delays = straggler.arrival_schedule(
        cfg.rounds, W, cfg.add_delay, cfg.delay_mean
    )

    devices = list(np.asarray(setup.mesh.devices).flat)
    D = len(devices)
    dev_of = [devices[w % D] for w in range(W)]
    # hoist the constant per-worker slices out of the timed loop; on a
    # multi-device mesh each logical worker's stack is pinned round-robin
    # to its device so dispatches run concurrently across chips while
    # workers sharing a chip contend for real
    if D > 1:
        slices = [
            jax.device_put(worker_slice(w), dev_of[w]) for w in range(W)
        ]
    else:
        slices = [worker_slice(w) for w in range(W)]
    # warm up every per-worker executable (one per device) so measured
    # times are steady-state compute, not gather dispatch or compile/load;
    # committed-vs-uncommitted params placement must match the timed loop
    # or jit would recompile inside the timed region
    m0 = None
    if D > 1:
        for w, (Xs, ys) in enumerate(slices):
            m0 = worker_msg(
                jax.device_put(state.params, dev_of[w]), Xs, ys,
                n=int(mult[w]),
            )
            _hard_sync(m0)
        m0 = jax.device_put(m0, devices[0])
    else:
        for w, (Xs, ys) in enumerate(slices):
            m0 = worker_msg(state.params, Xs, ys, n=int(mult[w]))
            _hard_sync(m0)
    # warm decode_update too (same shapes as the loop's calls, zero decode
    # weights, result discarded): its first call would otherwise compile
    # inside the timed region and be charged to round 0's wall-clock
    per_slot0 = jax.tree.map(lambda *xs: jnp.stack(xs), *([m0] * W))
    _hard_sync(
        decode_update(
            state,
            per_slot0,
            jnp.zeros((W, coeffs.shape[1]), dtype),
            jnp.asarray(lr[0], dtype),
            jnp.asarray(0.0, dtype),
        )
    )

    from erasurehead_tpu.obs import decode as obs_decode
    from erasurehead_tpu.obs import events as obs_events

    run_id = obs_events.new_run_id() if obs_events.current() else None
    if run_id is not None:
        _emit_run_start(
            run_id, cfg, setup, jax.devices()[0].platform,
            ("measured",), True,
        )

    timeset = np.zeros(cfg.rounds)
    worker_times = np.zeros((cfg.rounds, W))
    collected = np.zeros((cfg.rounds, W), dtype=bool)
    mw_rows = []  # per-round decode weights -> decode-error telemetry
    history = []
    wall0 = time.perf_counter()
    for r in range(cfg.rounds):
        # make sure the previous round's decode_update is off the device
        # stream before timing worker 0, or its cost would be
        # misattributed as worker 0's compute every round
        _hard_sync(state)
        t_row = np.zeros(W)
        if D > 1:
            # per-device queue replay: each device's worker queue is
            # drained in dispatch order and timed on its OWN clock, so a
            # worker's arrival = its device-queue wait + its own compute —
            # a pod's semantics exactly (chips run concurrently and
            # independently; within a chip, dispatches serialize). Devices
            # are measured one after another because concurrent host-side
            # timing of N virtual/tunneled devices measures thread-
            # scheduling noise, not chips (the CPU test backend serializes
            # executions globally — measured 2.0x for 2-device concurrent
            # dispatch; the axon TPU tunnel is single-client). The params
            # fan-out is staged and synced BEFORE each device's clock
            # opens: decode_update leaves params resident on devices[0],
            # so timing the transfer would charge devices 1..D-1 a d2d
            # copy that device 0's workers never pay — a placement
            # artifact, not worker heterogeneity.
            msgs = [None] * W
            params_on = [
                jax.device_put(state.params, d) for d in devices
            ]
            for p_d in params_on:
                _hard_sync(p_d)
            for d_idx in range(D):
                ws = range(d_idx, W, D)  # this device's queue, in order
                t0 = time.perf_counter()
                for w in ws:
                    m = worker_msg(
                        params_on[d_idx], *slices[w], n=int(mult[w])
                    )
                    _hard_sync(m)
                    t_row[w] = time.perf_counter() - t0
                    msgs[w] = m
            # stage every message on the decode device before stacking
            msgs = [jax.device_put(m, devices[0]) for m in msgs]
        else:
            msgs = []
            for w in range(W):
                Xs, ys = slices[w]
                t0 = time.perf_counter()
                m = worker_msg(state.params, Xs, ys, n=int(mult[w]))
                _hard_sync(m)
                t_row[w] = time.perf_counter() - t0
                msgs.append(m)
        arrivals = (t_row + delays[r])[None, :]
        sched = collect.build_schedule(
            cfg.scheme, arrivals, layout, num_collect=cfg.num_collect,
            deadline=cfg.deadline, decode=cfg.decode,
        )
        slot_w = np.asarray(
            step_lib.expand_slot_weights(
                sched.message_weights, coeffs, slot_coded
            )
        )[0]
        per_slot = jax.tree.map(lambda *xs: jnp.stack(xs), *msgs)
        state = decode_update(
            state,
            per_slot,
            jnp.asarray(slot_w, dtype),
            jnp.asarray(lr[r], dtype),
            jnp.asarray(float(r), dtype),
        )
        timeset[r] = sched.sim_time[0]
        worker_times[r] = sched.worker_times[0]
        collected[r] = sched.collected[0]
        mw_rows.append(sched.message_weights[0])
        history.append(state.params)
    _hard_sync(state)
    wall = time.perf_counter() - wall0

    decode_err = obs_decode.decode_error_series(
        layout, np.stack(mw_rows) if mw_rows else np.zeros((0, W))
    )
    steps_per_sec = cfg.rounds / wall if wall > 0 else 0.0
    if run_id is not None:
        obs_events.emit_round_chunks(
            run_id,
            start_round=0,
            timeset=timeset,
            worker_times=worker_times,
            decode_error=decode_err,
        )
        obs_events.emit(
            "run_end",
            run_id=run_id,
            wall_time_s=round(wall, 6),
            steps_per_sec=round(steps_per_sec, 4),
            sim_total_time_s=float(timeset.sum()),
            arrival=obs_events.arrival_summary(worker_times),
            **obs_decode.summarize(decode_err),
        )
    return TrainResult(
        params_history=jax.tree.map(lambda *xs: jnp.stack(xs), *history),
        final_params=state.params,
        final_state=state,
        timeset=timeset,
        worker_times=worker_times,
        collected=collected,
        sim_total_time=float(timeset.sum()),
        wall_time=wall,
        steps_per_sec=steps_per_sec,
        n_train=n_train,
        config=cfg,
        layout=layout,
        decode_error=decode_err,
        run_id=run_id,
    )


def _partial_gather_tree(weighted, zero_g, gather_dtype=np.float32):
    """One process's decoded-gradient contribution, leaves in ONE fixed
    dtype on every branch (ADVICE r5 #1): with bf16 data and an uneven
    device/worker fold, a worker-holding process's einsum outputs can
    carry a different float dtype than a workerless process's
    params-dtype zeros, and process_allgather must see identical dtypes
    on every process. ``weighted`` is None on a process with no local
    workers."""
    gather_dtype = np.dtype(gather_dtype)
    if weighted is not None:
        return jax.tree.map(lambda l: np.asarray(l, gather_dtype), weighted)
    return jax.tree.map(
        lambda l: np.zeros(np.shape(l), gather_dtype), zero_g
    )


def _train_measured_cluster(cfg, dataset, setup, mult, dtype, mesh=None):
    """Measured-arrival mode in a multi-controller cluster.

    Every process is a REPLICA of the reference's master: it holds the
    full host dataset (the data-prep determinism put_global relies on),
    computes the identical collection schedule and update, and times only
    the worker queues on its OWN devices — a process cannot dispatch to or
    time another host's chips. Per round, the [W] arrival row and the
    processes' partial decoded gradients meet via host allgathers, the
    analogue of the reference's MPI Waitany stamps + Gather
    (src/naive.py:95-126). Determinism makes the replicas agree: seeded
    init, seeded delays, and identical schedule math on identical inputs.

    Logical workers are assigned round-robin over the GLOBAL device list
    (jax.devices() order, identical everywhere), so a worker's arrival =
    its device-queue wait + its own compute, with queues on different
    hosts genuinely concurrent — a pod's semantics.
    """
    from jax.experimental import multihost_utils

    layout, model = setup.layout, setup.model
    W = layout.n_workers
    lr, alpha, n_train = setup.lr, setup.alpha, setup.n_train
    coeffs = np.asarray(layout.coeffs)
    slot_coded = np.asarray(layout.slot_is_coded)
    update_fn = setup.update_fn
    me = jax.process_index()

    # host-side worker stacks: every process reconstructs the full
    # redundant assignment (setup.data's device copies live on a submesh
    # in cluster mode and are not per-worker addressable from here)
    Xp_h, yp_h = partition_stack(
        dataset, layout.n_partitions, sparse_format=cfg.sparse_format
    )
    Xw_h, yw_h = worker_stack(layout, Xp_h, yp_h)
    run_dtype = jnp.dtype(cfg.dtype)

    def _cast(leaf):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            return arr.astype(run_dtype)
        return arr

    # identical order on every process; an explicit mesh narrows the pool
    devices = (
        jax.devices() if mesh is None else list(np.asarray(mesh.devices).flat)
    )
    D = len(devices)
    dev_of = [devices[w % D] for w in range(W)]
    local_ws = [w for w in range(W) if dev_of[w].process_index == me]
    slices = {
        w: jax.device_put(
            (
                jax.tree.map(lambda l: _cast(l[w]), Xw_h),
                _cast(yw_h[w]),
            ),
            dev_of[w],
        )
        for w in local_ws
    }

    worker_msg = _make_worker_msg(model)

    @jax.jit
    def weighted_partial(stacked, w_sel):
        # stacked: [num_local, S, ...] per leaf; w_sel: [num_local, S]
        return jax.tree.map(
            lambda l: jnp.einsum("ws,ws...->...", w_sel, l), stacked
        )

    @jax.jit
    def apply_update(st, g, eta, i):
        return update_fn(st, g, eta, alpha, n_train, i)

    state = setup.state0  # seeded identically on every process
    local_devs = [d for d in devices if d.process_index == me]
    queue_of = {
        d: [w for w in local_ws if dev_of[w] is d] for d in local_devs
    }

    # warm every local executable outside the timed region
    for w in local_ws:
        _hard_sync(worker_msg(
            jax.device_put(state.params, dev_of[w]), *slices[w],
            n=int(mult[w]),
        ))
    zero_g = jax.tree.map(jnp.zeros_like, state.params)
    _hard_sync(apply_update(
        state, zero_g, jnp.asarray(lr[0], dtype), jnp.asarray(0.0, dtype)
    ))

    delays = straggler.arrival_schedule(
        cfg.rounds, W, cfg.add_delay, cfg.delay_mean
    )
    timeset = np.zeros(cfg.rounds)
    worker_times = np.zeros((cfg.rounds, W))
    collected = np.zeros((cfg.rounds, W), dtype=bool)
    mw_rows = []  # decode-error telemetry (identical on every replica)
    history = []
    wall0 = time.perf_counter()
    for r in range(cfg.rounds):
        _hard_sync(state)
        params_on = {d: jax.device_put(state.params, d) for d in local_devs}
        for p_d in params_on.values():
            _hard_sync(p_d)
        t_local = np.zeros(W)
        msgs = {}
        for d in local_devs:
            t0 = time.perf_counter()
            for w in queue_of[d]:
                m = worker_msg(params_on[d], *slices[w], n=int(mult[w]))
                _hard_sync(m)
                t_local[w] = time.perf_counter() - t0
                msgs[w] = m
        # one process timed each worker; the rest contributed zeros
        t_row = np.asarray(
            multihost_utils.process_allgather(t_local)
        ).sum(axis=0)
        arrivals = (t_row + delays[r])[None, :]
        sched = collect.build_schedule(
            cfg.scheme, arrivals, layout, num_collect=cfg.num_collect,
            deadline=cfg.deadline, decode=cfg.decode,
        )
        slot_w = np.asarray(
            step_lib.expand_slot_weights(
                sched.message_weights, coeffs, slot_coded
            )
        )[0]
        if local_ws:
            # stage every local message on one device before stacking
            staged = [
                jax.device_put(msgs[w], local_devs[0]) for w in local_ws
            ]
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *staged)
            weighted = weighted_partial(
                stacked, jnp.asarray(slot_w[local_ws], dtype)
            )
        else:
            weighted = None
        partial_g = _partial_gather_tree(weighted, zero_g)
        # sum the per-process partials: the distributed Gather + decode
        g = jax.tree.map(
            lambda l: np.asarray(l).sum(axis=0),
            multihost_utils.process_allgather(partial_g),
        )
        state = apply_update(
            state,
            jax.tree.map(lambda l: jnp.asarray(l, dtype), g),
            jnp.asarray(lr[r], dtype),
            jnp.asarray(float(r), dtype),
        )
        timeset[r] = sched.sim_time[0]
        worker_times[r] = sched.worker_times[0]
        collected[r] = sched.collected[0]
        mw_rows.append(sched.message_weights[0])
        history.append(state.params)
    _hard_sync(state)
    wall = time.perf_counter() - wall0

    from erasurehead_tpu.obs import decode as obs_decode

    # decode-error telemetry only (no event emission here: every replica
    # computes the identical schedule, and N processes appending to one
    # event file would interleave — the single-process path emits)
    decode_err = obs_decode.decode_error_series(
        layout, np.stack(mw_rows) if mw_rows else np.zeros((0, W))
    )
    return TrainResult(
        params_history=jax.tree.map(lambda *xs: jnp.stack(xs), *history),
        final_params=state.params,
        final_state=state,
        timeset=timeset,
        worker_times=worker_times,
        collected=collected,
        sim_total_time=float(timeset.sum()),
        wall_time=wall,
        steps_per_sec=cfg.rounds / wall if wall > 0 else 0.0,
        n_train=n_train,
        config=cfg,
        layout=layout,
        decode_error=decode_err,
    )


def _apply_margin_flat(
    cfg, model, mesh, X, grad_fn, ring_plan=None, ring_pipeline=False
):
    """Swap in the hybrid dense lowering (step.make_margin_flat_grad_fn)
    per cfg.margin_flat: flat 2-D margin matmul + batched per-slot
    transpose. "on" forces (raising off the dense closed-form path);
    "auto" defers to step.resolve_margin_flat (MARGIN_FLAT_DEFAULT,
    pending the dense_f32_marginflat race). With ``ring_plan`` set (the
    ring stack mode), the same per-device body runs behind the ring
    transport — the lowering choice composes with either transport (and
    with either transport schedule, ``ring_pipeline``)."""
    if cfg.margin_flat == "on" and not step_lib.supports_margin_flat(model, X):
        raise ValueError(
            "margin_flat='on' needs a closed-form GLM on a dense stack; "
            f"got model={getattr(model, 'name', type(model).__name__)!r}, "
            f"X={type(X).__name__}"
        )
    if step_lib.resolve_margin_flat(cfg.margin_flat, model, X):
        if ring_plan is not None:
            return step_lib.make_ring_faithful_grad_fn(
                model, mesh, ring_plan,
                local_body=step_lib._margin_flat_local_body(model),
                pipeline=ring_pipeline,
            )
        return step_lib.make_margin_flat_grad_fn(model, mesh)
    return grad_fn


def _apply_flat_grad(
    cfg, model, mesh, X, grad_fn, ring_plan=None, ring_pipeline=False
):
    """Swap in the flat-stack closed-form lowering (step.make_flat_grad_fn)
    per cfg.flat_grad: one matvec/rmatvec pair instead of the batched
    per-slot contraction. "on" forces (raising off the closed-form path),
    "auto" defers to step.resolve_flat_grad's measurement-pinned rules.
    Composes with the ring transport like _apply_margin_flat."""
    if cfg.flat_grad == "on" and not step_lib.supports_flat_grad(model, X):
        raise ValueError(
            "flat_grad='on' needs a closed-form GLM (logistic/linear) on a "
            "dense, PaddedRows, or FieldOnehot stack; "
            f"got model={getattr(model, 'name', type(model).__name__)!r}, "
            f"X={type(X).__name__}"
        )
    if step_lib.resolve_flat_grad(cfg.flat_grad, model, X):
        if ring_plan is not None:
            return step_lib.make_ring_faithful_grad_fn(
                model, mesh, ring_plan,
                local_body=step_lib._flat_local_body(model),
                pipeline=ring_pipeline,
            )
        return step_lib.make_flat_grad_fn(model, mesh)
    return grad_fn


def _apply_layer_coding(
    cfg, model, mesh, X, grad_fn, params_template,
    ring_plan=None, ring_pipeline=False, faithful=True,
):
    """Swap in the per-layer (blockwise) decode lowering
    (step.make_layer_block_grad_fn) per cfg.layer_coding: per-slot
    gradient pytrees pack into the model's [L, width] block table
    (ops/blocks.model_block_spec — DeepMLP layers / MoE expert shards are
    individual coded blocks) and decode as ONE batched einsum. "on"
    forces (raising where the model cannot take the path); "auto" defers
    to step.resolve_layer_coding (cached tune decision, else
    LAYER_CODING_DEFAULT). Composes with the ring transport like the
    other lowering swaps; bitwise-identical decode to the treewise form
    is test-pinned, so the swap is a pure lowering choice.

    Inside the blockwise path, cfg.block_decode picks the decode
    LOWERING (step.resolve_block_decode): treewise table einsum or the
    fused per-leaf contraction (ops/kernels.fused_block_decode — no
    materialized [M, L, width] grad table). Also bitwise-identical, also
    a pure lowering fork — both choices are keyed through
    step.lowering_signature so executables fork correctly."""
    if cfg.layer_coding == "on" and not step_lib.supports_layer_coding(model):
        raise ValueError(
            "layer_coding='on' needs a model whose per-slot gradients are "
            "exact under the worker-axis step (no model-internal mesh "
            "axes; autodiff families need a jax without the implicit "
            "replicated-grad psum) — got "
            f"model={getattr(model, 'name', type(model).__name__)!r}"
        )
    if not step_lib.resolve_layer_coding(cfg.layer_coding, model, X):
        return grad_fn
    from erasurehead_tpu.ops import blocks as blocks_lib

    fused = step_lib.resolve_block_decode(cfg.block_decode, model, X)
    spec = blocks_lib.model_block_spec(model, params_template)
    if ring_plan is not None:
        local_body = (
            step_lib._fused_layer_block_local_body(model, spec, "ws")
            if fused
            else step_lib._layer_block_local_body(model, spec, "ws")
        )
        return step_lib.make_ring_faithful_grad_fn(
            model, mesh, ring_plan,
            local_body=local_body,
            pipeline=ring_pipeline,
            check_vma=step_lib._vma_check(model),
        )
    return step_lib.make_layer_block_grad_fn(
        model, mesh, spec, faithful=faithful, fused=fused
    )


@_with_run_sparse_lanes
def train_dynamic(
    cfg: RunConfig,
    dataset: Dataset,
    mesh=None,
    initial_state: Optional[Any] = None,
    initial_round: int = 0,
) -> TrainResult:
    """Fully on-device run: arrivals, collection masks, and decode are
    traced values inside ONE jitted scan (parallel/dynamic.py) — no host
    control plane between rounds.

    The default :func:`train` is the reference-parity path (bit-matched
    MT19937 delay streams, float64 decode); this one trades numeric parity
    for a closed-loop on-device program — the shape an online scheduler
    fed by *measured* arrivals takes. Faithful compute mode only.

    ``initial_state``/``initial_round`` mirror :func:`train`'s mid-schedule
    restart contract (the elastic hook, failures.train_elastic): the scan
    covers rounds [initial_round, rounds); telemetry rows before that
    carry zero time / -1 clocks / nothing-collected, and params_history
    has ``rounds - initial_round`` entries.

    No event-log / decode-error telemetry (obs/): the collection weights
    are traced values inside the scan, so the host never sees them — use
    :func:`train` for instrumented runs.
    """
    from erasurehead_tpu.parallel import dynamic as dynamic_lib

    # mirror train()'s restart guard, before any device setup (ADVICE r4)
    if initial_round != 0 and initial_state is None:
        raise ValueError(
            f"initial_round={initial_round} requires initial_state: a "
            "mid-schedule restart resumes from donor state"
        )
    if cfg.decode == "optimal":
        raise ValueError(
            "decode='optimal' refits collection weights on the host "
            "control plane (a per-round float64 lstsq); train_dynamic's "
            "weights are traced values inside the scan — use "
            "trainer.train() for optimal decoding"
        )
    if cfg.pipeline_depth:
        raise PipelineRefusal(
            "dynamic_rule",
            "pipeline_depth=1 has no on-device dynamic implementation: "
            "the pipelined dispatch recurrence lives on the host control "
            "plane (parallel/pipeline.py) — use trainer.train()",
        )
    setup = _setup_run(cfg, dataset, mesh, faithful=True)
    layout, model, mesh, data = setup.layout, setup.model, setup.mesh, setup.data
    sched_fn = dynamic_lib.make_round_schedule_fn(
        cfg.scheme, layout, cfg.num_collect, cfg.delay_mean, cfg.add_delay,
        deadline=cfg.deadline,
    )
    ring_pipe = setup.ring and step_lib.resolve_ring_pipeline(
        cfg.ring_pipeline, model, data.Xp
    )
    if setup.ring:
        ring_plan = plan_ring_transport(layout, _worker_axis_size(mesh))
        base_fn = step_lib.make_ring_faithful_grad_fn(
            model, mesh, ring_plan, pipeline=ring_pipe
        )
        X, y = data.Xp, data.yp
    else:
        ring_plan = None
        base_fn = step_lib.make_faithful_grad_fn(model, mesh)
        X, y = data.Xw, data.yw
    grad_fn = _apply_flat_grad(
        cfg, model, mesh, X,
        _apply_margin_flat(
            cfg, model, mesh, X, base_fn, ring_plan, ring_pipe
        ),
        ring_plan,
        ring_pipe,
    )
    grad_fn = _apply_layer_coding(
        cfg, model, mesh, X, grad_fn, setup.state0.params,
        ring_plan, ring_pipe, faithful=True,
    )
    update_fn = setup.update_fn
    dtype = jnp.float32  # param/update dtype (cfg.dtype is the data dtype)
    coeffs = jnp.asarray(layout.coeffs, dtype)
    slot_coded = jnp.asarray(np.asarray(layout.slot_is_coded))
    lr_seq = jnp.asarray(setup.lr, dtype)
    alpha = setup.alpha
    n_train = setup.n_train

    state0 = setup.state0
    start = 0
    if initial_state is not None:
        if not 0 <= initial_round < cfg.rounds:
            raise ValueError(
                f"initial_round={initial_round} outside [0, {cfg.rounds})"
            )
        # strand off the donor phase's placement: an elastic restart carries
        # state across meshes with different worker counts (np_global: the
        # donor mesh may be a submesh of the cluster)
        state0 = jax.tree.map(
            lambda l: jnp.asarray(np_global(l)), initial_state
        )
        start = initial_round
    key = jax.random.key(cfg.seed + 1)

    def body(Xa, ya, state, xs):
        eta, i = xs
        rs = sched_fn(jax.random.fold_in(key, i.astype(jnp.int32)))
        slot_w = step_lib.expand_slot_weights(
            rs.message_weights.astype(dtype), coeffs, slot_coded
        )
        g = grad_fn(state.params, Xa, ya, slot_w)
        new_state = update_fn(state, g, eta, alpha, n_train, i.astype(dtype))
        return new_state, (
            new_state.params, rs.sim_time, rs.worker_times, rs.collected
        )

    @jax.jit
    def run(state, Xa, ya, lr_c, it_c):
        return jax.lax.scan(
            partial(body, Xa, ya), state, (lr_c, it_c),
            unroll=cfg.scan_unroll,
        )

    iters = jnp.arange(start, cfg.rounds)
    t0 = time.perf_counter()
    final_state, (hist, sim, wtimes, collected) = run(
        state0, X, y, lr_seq[start:], iters
    )
    _hard_sync(final_state)
    wall = time.perf_counter() - t0

    # telemetry padded to the full horizon (train()'s restart contract):
    # rows before ``start`` belong to the donor phase
    R, W = cfg.rounds, layout.n_workers
    timeset = np.zeros(R)
    timeset[start:] = np_global(sim, np.float64)
    wt = -np.ones((R, W))
    wt[start:] = np_global(wtimes, np.float64)
    col = np.zeros((R, W), dtype=bool)
    col[start:] = np_global(collected)
    return TrainResult(
        params_history=hist,
        final_params=final_state.params,
        final_state=final_state,
        timeset=timeset,
        worker_times=wt,
        collected=col,
        sim_total_time=float(timeset.sum()),
        wall_time=wall,
        steps_per_sec=(cfg.rounds - start) / wall if wall > 0 else 0.0,
        n_train=n_train,
        start_round=start,
        config=cfg,
        layout=layout,
    )
