"""Experiment harness: the AGC vs EGC vs uncoded comparisons.

The reference's experimental frame (BASELINE.md): for each scheme and
straggler count, train under the same seeded delay schedule and compare
(a) effective iteration rate and (b) time-to-target-loss, both measured on
the simulated master clock (the reference measured the same two quantities
with real injected sleeps; the schedules are identical streams).

``compare()`` runs a set of configs on one dataset under one shared arrival
schedule (paired comparison — the reference could only approximate this by
re-seeding per iteration, src/naive.py:141-148; we share the exact arrival
matrix across schemes). ``baseline_suite()`` reproduces the five BASELINE.json
configs at requested scale.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional, Sequence

import numpy as np

from erasurehead_tpu.data.synthetic import Dataset
from erasurehead_tpu.parallel import straggler
from erasurehead_tpu.train import evaluate, trainer
from erasurehead_tpu.utils.config import RunConfig


@dataclasses.dataclass
class RunSummary:
    label: str
    config: RunConfig
    sim_total_time: float
    sim_steps_per_sec: float
    real_steps_per_sec: float
    final_train_loss: float
    final_test_loss: float
    final_auc: float
    time_to_target: Optional[float]  # simulated seconds; None if never reached
    training_loss: np.ndarray
    timeset: np.ndarray

    def row(self) -> dict:
        return {
            "label": self.label,
            "scheme": self.config.scheme.value,
            "n_stragglers": self.config.n_stragglers,
            "num_collect": self.config.num_collect,
            "sim_total_time": round(self.sim_total_time, 4),
            "sim_steps_per_sec": round(self.sim_steps_per_sec, 4),
            "real_steps_per_sec": round(self.real_steps_per_sec, 2),
            "final_train_loss": round(self.final_train_loss, 6),
            "final_test_loss": round(self.final_test_loss, 6),
            "final_auc": round(self.final_auc, 6)
            if np.isfinite(self.final_auc)
            else None,
            "time_to_target": round(self.time_to_target, 4)
            if self.time_to_target is not None
            else None,
        }


def time_to_target_loss(
    training_loss: np.ndarray, timeset: np.ndarray, target: float
) -> Optional[float]:
    """Simulated wall-clock until train loss first reaches ``target``
    (cumulative sum of per-iteration times — the reference's total-elapsed
    clock, src/naive.py:155-156)."""
    reached = np.flatnonzero(training_loss <= target)
    if reached.size == 0:
        return None
    return float(np.cumsum(timeset)[reached[0]])


def compare(
    configs: dict[str, RunConfig],
    dataset: Dataset,
    target_loss: Optional[float] = None,
    arrivals: Optional[np.ndarray] = None,
) -> list[RunSummary]:
    """Train every config on ``dataset`` under one shared arrival schedule
    and summarize. ``target_loss`` default: 1.05x the uncoded baseline's
    final train loss (if a config labeled 'naive' is present), else the
    worst final loss across runs."""
    rounds = {c.rounds for c in configs.values()}
    workers = {c.n_workers for c in configs.values()}
    assert len(rounds) == 1 and len(workers) == 1, "configs must share shape"
    if arrivals is None:
        any_cfg = next(iter(configs.values()))
        arrivals = straggler.arrival_schedule(
            rounds.pop(), workers.pop(), add_delay=True, mean=any_cfg.delay_mean
        )

    raw = {}
    for label, cfg in configs.items():
        res = trainer.train(cfg, dataset, arrivals=arrivals)
        model = trainer.build_model(cfg)
        n = res.n_train
        ev = evaluate.replay(
            model,
            cfg.model,
            res.params_history,
            dataset.X_train[:n],
            dataset.y_train[:n],
            dataset.X_test,
            dataset.y_test,
        )
        raw[label] = (res, ev)

    if target_loss is None:
        if "naive" in raw:
            target_loss = 1.05 * float(raw["naive"][1].training_loss[-1])
        else:
            target_loss = float(
                max(ev.training_loss[-1] for _, ev in raw.values())
            )

    out = []
    for label, (res, ev) in raw.items():
        out.append(
            RunSummary(
                label=label,
                config=res.config,
                sim_total_time=res.sim_total_time,
                sim_steps_per_sec=(
                    res.config.rounds / res.sim_total_time
                    if res.sim_total_time > 0
                    else float("inf")  # zero arrival schedule (no delays)
                ),
                real_steps_per_sec=res.steps_per_sec,
                final_train_loss=float(ev.training_loss[-1]),
                final_test_loss=float(ev.testing_loss[-1]),
                final_auc=float(ev.auc[-1]),
                time_to_target=time_to_target_loss(
                    ev.training_loss, res.timeset, target_loss
                ),
                training_loss=ev.training_loss,
                timeset=res.timeset,
            )
        )
    return out


def straggler_sweep(
    base: RunConfig,
    dataset: Dataset,
    scheme_stragglers: dict[str, Sequence[int]],
    **compare_kw,
) -> list[RunSummary]:
    """The reference's headline figure: each scheme across straggler counts
    (time-to-target-loss vs n_stragglers, BASELINE.json metric)."""
    configs = {}
    for scheme, s_values in scheme_stragglers.items():
        for s in s_values:
            cfg = dataclasses.replace(base, scheme=scheme, n_stragglers=s)
            if scheme == "approx" and cfg.num_collect >= cfg.n_workers:
                # AGC's interesting regime collects fewer than all
                cfg = dataclasses.replace(cfg, num_collect=cfg.n_workers // 2)
            configs[f"{scheme}_s{s}"] = cfg
    return compare(configs, dataset, **compare_kw)


def save_summaries(summaries: list[RunSummary], path: str) -> None:
    with open(path, "w") as f:
        json.dump([s.row() for s in summaries], f, indent=2)


def format_table(summaries: list[RunSummary]) -> str:
    header = (
        f"{'label':22s} {'sim it/s':>9s} {'real it/s':>10s} "
        f"{'train loss':>11s} {'AUC':>7s} {'t->target':>10s}"
    )
    lines = [header, "-" * len(header)]
    for s in summaries:
        auc = f"{s.final_auc:7.4f}" if np.isfinite(s.final_auc) else "      -"
        ttt = (
            f"{s.time_to_target:10.3f}"
            if s.time_to_target is not None
            else "         -"
        )
        lines.append(
            f"{s.label:22s} {s.sim_steps_per_sec:9.3f} "
            f"{s.real_steps_per_sec:10.1f} {s.final_train_loss:11.6f} "
            f"{auc} {ttt}"
        )
    return "\n".join(lines)
