"""Experiment harness: the AGC vs EGC vs uncoded comparisons.

The reference's experimental frame (BASELINE.md): for each scheme and
straggler count, train under the same seeded delay schedule and compare
(a) effective iteration rate and (b) time-to-target-loss, both measured on
the simulated master clock (the reference measured the same two quantities
with real injected sleeps; the schedules are identical streams).

``compare()`` runs a set of configs on one dataset under one shared arrival
schedule (paired comparison — the reference could only approximate this by
re-seeding per iteration, src/naive.py:141-148; we share the exact arrival
matrix across schemes). ``baseline_suite()`` reproduces the five BASELINE.json
configs at requested scale.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Sequence

import numpy as np

from erasurehead_tpu.data.synthetic import Dataset
from erasurehead_tpu.parallel import straggler
from erasurehead_tpu.train import evaluate, trainer
from erasurehead_tpu.utils.config import RunConfig


@dataclasses.dataclass
class RunSummary:
    label: str
    config: RunConfig
    sim_total_time: float
    sim_steps_per_sec: float
    real_steps_per_sec: float
    final_train_loss: float
    final_test_loss: float
    final_auc: float
    time_to_target: Optional[float]  # simulated seconds; None if never reached
    training_loss: np.ndarray
    timeset: np.ndarray
    #: free-form caveat carried into the saved artifact (e.g. the synthetic
    #: stand-in's achievable-AUC ceiling) so a committed row can't be
    #: misread as divergent/random without its context (VERDICT r4 #6)
    note: Optional[str] = None
    #: suite config name (incl. any [synthetic(...)] substitution tag) —
    #: carried as its own artifact field so the flattened rows stay
    #: attributable without overloading the display label
    suite: Optional[str] = None
    #: sweep-engine cache telemetry for this run (train/cache.py via
    #: TrainResult.cache_info): data/exec hit-miss, compile seconds saved,
    #: bytes not re-uploaded — how much of the sweep the caches absorbed
    cache: Optional[dict] = None
    #: mean per-round AGC decode-error norm (obs/decode.py via
    #: TrainResult.decode_error): 0.0 for exact schemes, > 0 where the
    #: decode was genuinely approximate — the papers' central quantity,
    #: now a first-class sweep column
    decode_error_mean: Optional[float] = None

    def row(self) -> dict:
        out = {
            "label": self.label,
            "scheme": self.config.scheme.value,
            "n_stragglers": self.config.n_stragglers,
            "num_collect": self.config.num_collect,
            "sim_total_time": round(self.sim_total_time, 4),
            "sim_steps_per_sec": round(self.sim_steps_per_sec, 4),
            "real_steps_per_sec": round(self.real_steps_per_sec, 2),
            "final_train_loss": round(self.final_train_loss, 6),
            "final_test_loss": round(self.final_test_loss, 6),
            "final_auc": round(self.final_auc, 6)
            if np.isfinite(self.final_auc)
            else None,
            "time_to_target": round(self.time_to_target, 4)
            if self.time_to_target is not None
            else None,
            "decode_error_mean": round(self.decode_error_mean, 8)
            if self.decode_error_mean is not None
            else None,
        }
        if self.suite:
            out["suite"] = self.suite
        if self.note:
            out["note"] = self.note
        if self.cache is not None:
            out["cache"] = self.cache
        return out


def time_to_target_loss(
    training_loss: np.ndarray, timeset: np.ndarray, target: float
) -> Optional[float]:
    """Simulated wall-clock until train loss first reaches ``target``
    (cumulative sum of per-iteration times — the reference's total-elapsed
    clock, src/naive.py:155-156)."""
    reached = np.flatnonzero(training_loss <= target)
    if reached.size == 0:
        return None
    return float(np.cumsum(timeset)[reached[0]])


def plan_cohorts(
    configs: dict[str, RunConfig],
) -> list[tuple[list[str], bool]]:
    """Group config labels into trajectory cohorts for batched dispatch.

    Returns ``[(labels, batchable), ...]`` in first-seen order: every
    group with ``batchable=True`` maps to one :func:`trainer.
    cohort_signature` key (same data stack + lowering, so
    ``train_cohort`` can run it as ONE compiled scan); ineligible configs
    (measured mode, forced pallas) come back as their own
    ``batchable=False`` singletons. In deduped mode the partition-major
    stack is scheme-independent, so a whole 7-scheme x N-seed compare()
    collapses into a single cohort."""
    groups: dict = {}
    order: list = []
    for label, cfg in configs.items():
        key = trainer.cohort_signature(cfg)
        if key is None:
            key = ("__sequential__", label)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(label)
    return [
        (groups[k], k[0] != "__sequential__") for k in order
    ]


def _run_configs(
    configs: dict[str, RunConfig],
    dataset: Dataset,
    arrivals,
    batch: str,
) -> dict[str, "trainer.TrainResult"]:
    """Train every config, dispatching cohorts through train_cohort per
    the resolved ``batch`` mode ('on'/'off'/'auto'); returns label ->
    TrainResult. Sequential fallbacks (mode 'off', singletons under
    'auto', ineligible configs) go through plain train()."""
    from erasurehead_tpu.obs.metrics import REGISTRY as _metrics

    raw: dict = {}
    if batch == "off":
        plan = [([label], False) for label in configs]
    else:
        plan = plan_cohorts(configs)
    min_size = 1 if batch == "on" else 2
    for labels, batchable in plan:
        if batchable and len(labels) >= min_size:
            results = trainer.train_cohort(
                [configs[l] for l in labels], dataset, arrivals=arrivals
            )
            raw.update(zip(labels, results))
        else:
            for l in labels:
                _metrics.counter("cohort.sequential_runs").inc()
                raw[l] = trainer.train(
                    configs[l], dataset, arrivals=arrivals
                )
    return raw


def compare(
    configs: dict[str, RunConfig],
    dataset: Dataset,
    target_loss: Optional[float] = None,
    arrivals: Optional[np.ndarray] = None,
    batch: Optional[str] = None,
) -> list[RunSummary]:
    """Train every config on ``dataset`` under one shared arrival schedule
    and summarize. ``target_loss`` default: 1.05x the uncoded baseline's
    final train loss (if a config labeled 'naive' is present), else the
    worst final loss across runs.

    ``batch`` picks the trajectory-batched dispatch mode ('on'/'off'/
    'auto'; None = the --batch-trajectories flag/env default, see
    utils.config.resolve_batch_trajectories): under 'auto'/'on', configs
    sharing a device data stack (plan_cohorts) run as ONE compiled cohort
    scan — a deduped 7-scheme sweep streams X from HBM once per round for
    all schemes instead of once per scheme."""
    from erasurehead_tpu.utils.config import resolve_batch_trajectories

    rounds = {c.rounds for c in configs.values()}
    workers = {c.n_workers for c in configs.values()}
    assert len(rounds) == 1 and len(workers) == 1, "configs must share shape"
    if arrivals is None:
        any_cfg = next(iter(configs.values()))
        arrivals = straggler.arrival_schedule(
            rounds.pop(), workers.pop(), add_delay=True, mean=any_cfg.delay_mean
        )

    results = _run_configs(
        configs, dataset, arrivals, resolve_batch_trajectories(batch)
    )
    raw = {}
    for label in configs:
        res = results[label]
        cfg = configs[label]
        model = trainer.build_model(cfg)
        n = res.n_train
        ev = evaluate.replay(
            model,
            cfg.model,
            res.params_history,
            dataset.X_train[:n],
            dataset.y_train[:n],
            dataset.X_test,
            dataset.y_test,
        )
        raw[label] = (res, ev)

    if target_loss is None:
        if "naive" in raw:
            target_loss = 1.05 * float(raw["naive"][1].training_loss[-1])
        else:
            target_loss = float(
                max(ev.training_loss[-1] for _, ev in raw.values())
            )

    out = []
    for label, (res, ev) in raw.items():
        out.append(
            RunSummary(
                label=label,
                config=res.config,
                sim_total_time=res.sim_total_time,
                sim_steps_per_sec=(
                    res.config.rounds / res.sim_total_time
                    if res.sim_total_time > 0
                    else float("inf")  # zero arrival schedule (no delays)
                ),
                real_steps_per_sec=res.steps_per_sec,
                final_train_loss=float(ev.training_loss[-1]),
                final_test_loss=float(ev.testing_loss[-1]),
                final_auc=float(ev.auc[-1]),
                time_to_target=time_to_target_loss(
                    ev.training_loss, res.timeset, target_loss
                ),
                training_loss=ev.training_loss,
                timeset=res.timeset,
                cache=res.cache_info,
                decode_error_mean=(
                    float(np.mean(res.decode_error))
                    if res.decode_error is not None
                    and len(res.decode_error)
                    else None
                ),
            )
        )
    return out


def straggler_sweep(
    base: RunConfig,
    dataset: Dataset,
    scheme_stragglers: dict[str, Sequence[int]],
    **compare_kw,
) -> list[RunSummary]:
    """The reference's headline figure: each scheme across straggler counts
    (time-to-target-loss vs n_stragglers, BASELINE.json metric)."""
    configs = {}
    for scheme, s_values in scheme_stragglers.items():
        for s in s_values:
            cfg = dataclasses.replace(base, scheme=scheme, n_stragglers=s)
            if scheme == "approx" and cfg.num_collect >= cfg.n_workers:
                # AGC's interesting regime collects fewer than all
                cfg = dataclasses.replace(cfg, num_collect=cfg.n_workers // 2)
            configs[f"{scheme}_s{s}"] = cfg
    return compare(configs, dataset, **compare_kw)


def baseline_suite(
    scale: float = 1.0,
    data_dir: Optional[str] = None,
    rounds: int = 100,
    batch: Optional[str] = None,
) -> dict[str, list[RunSummary]]:
    """Reproduce the five BASELINE.json comparison configs.

    Real datasets (covtype / amazon / kc_house) are used when prepared under
    ``data_dir`` in the reference layout; otherwise each config falls back to
    a synthetic stand-in of the same structure (GMM for logistic tasks,
    linear-model data for least-squares) at ``scale`` x a canonical size, and
    the suite labels record the substitution. Returns {config_name: summaries}.
    ``batch`` is the trajectory-batched dispatch mode threaded into every
    compare() (see :func:`compare`; the suite's configs are mostly
    singletons, so 'auto' leaves them sequential).
    """
    from erasurehead_tpu.data.synthetic import (
        generate_gmm,
        generate_linear,
        generate_onehot,
    )
    from erasurehead_tpu.utils.config import ModelKind

    # reference nnz/row of the real one-hot matrices: covtype's binned
    # one-hot has 12 active categories per row (arrange_real_data.py:145-205
    # structure), amazon's hashed-interaction encoding has 44
    # (arrange_real_data.py:34-91; pinned in tests/test_data.py)
    ONEHOT_NNZ = {"covtype": 12, "amazon": 44}

    def _rows(rows, parts):
        n = max(parts * 8, int(rows * scale))
        return parts * max(1, round(n / parts))  # multiple of n_partitions

    _cache: dict = {}

    def get_data(name, parts, fallback):
        """Prepared real dataset if present under data_dir, else a synthetic
        stand-in of the same structure. Memoized per (name, parts)."""
        key = (name, parts)
        if key in _cache:
            return _cache[key]
        if data_dir is not None:
            import os

            from erasurehead_tpu.data import io as data_io

            path = os.path.join(data_dir, name, str(parts))
            if data_io.has_reference_layout(path):
                ds = data_io.read_reference_layout(path, parts)
                _cache[key] = (ds, name)
                return _cache[key]
        rows, cols = fallback
        if name in ONEHOT_NNZ:
            # structure-matched sparse stand-in: one-hot CSR with the real
            # dataset's nnz/row, so the suite exercises the PaddedRows path
            # the actual workload would take
            nnz = min(ONEHOT_NNZ[name], cols)
            ds = generate_onehot(
                _rows(rows, parts), cols, parts, n_fields=nnz, seed=0
            )
        else:
            maker = (
                generate_linear
                if name in ("kc_house_data", "synthetic-linear")
                else generate_gmm
            )
            ds = maker(_rows(rows, parts), cols, parts, seed=0)
        _cache[key] = (ds, f"synthetic({name}-shaped)")
        return _cache[key]

    def preset_cfg(dataset_name, ds, src=None, **kw):
        """Config carrying the dataset's reference lr preset (main.py:37-46)
        and alpha = 1/n_train for the data actually in use.

        When a synthetic stand-in substitutes for a real dataset
        (``src != dataset_name``), the reference preset lr — tuned to the
        real set's canonical scale — does not transfer: amazon's lr=10
        diverges and covtype's lr=0.1 stalls at the stand-in scale (the
        committed r3 artifact shipped exactly those rows, VERDICT r4 #6).
        Classification configs then run at a stand-in-convergent constant
        lr; ``artificial`` keeps its preset (the stand-in IS its dataset),
        and the linear preset transfers as-is."""
        n_train = ds.X_train.shape[0]
        cfg = RunConfig.for_dataset(
            dataset_name, rounds=rounds, add_delay=True,
            **{"n_rows": n_train, "n_cols": ds.X_train.shape[1], **kw},
        )
        is_standin = src is not None and src != dataset_name
        if (is_standin and dataset_name != "artificial"
                and cfg.model is not ModelKind.LINEAR
                and "lr_schedule" not in kw):
            # logistic curvature scales with the squared row norm = nnz/row
            # for one-hot data, so the stable constant lr scales as 1/nnz
            # (measured: nnz=12 converges at 1.0; nnz=44 diverges there)
            nnz = ONEHOT_NNZ.get(dataset_name)
            cfg = dataclasses.replace(
                cfg, lr_schedule=1.0 if nnz is None else min(1.0, 12.0 / nnz)
            )
        return cfg

    #: caveat attached to every synthetic-stand-in classification row so a
    #: committed artifact row can't be misread as divergent/random
    STANDIN_NOTE = (
        "synthetic stand-in: labels drawn from a unit-logit-variance "
        "logistic model (data/synthetic.generate_*), whose Bayes-optimal "
        "classifier has log-loss ~0.60 and AUC ~0.74 (Monte-Carlo) — "
        "train loss near 0.60 is AT the generator's floor, not underfit"
    )

    def tag(summaries, name, src=None, dataset_name=None):
        """Flatten-proof the rows: record the suite config name (incl. any
        [synthetic(...)] substitution) on each row, and annotate stand-in
        classification rows with the generator's ceiling — here, where
        ``src`` is known, so every save_summaries() caller gets the
        annotated rows, not just the CLI."""
        for s in summaries:
            s.suite = name
            if (src is not None and src != dataset_name
                    and s.config.model is not ModelKind.LINEAR):
                s.note = STANDIN_NOTE
        return summaries

    out: dict[str, list[RunSummary]] = {}

    # 1. Logistic on covtype, uncoded, 8 workers (BASELINE.json configs[0])
    W = 8
    ds, src = get_data("covtype", W, (2048, 64))
    cfg = preset_cfg(
        "covtype", ds, src, scheme="naive", n_workers=W, n_stragglers=0,
        update_rule="GD",
    )
    name = f"1_naive_covtype[{src}]"
    out[name] = tag(
        compare({"naive": cfg}, ds, batch=batch), name, src, "covtype"
    )

    # 2. Logistic on amazon, exact cyclic-MDS coding, s=2 (configs[1])
    ds, src = get_data("amazon", W, (2048, 64))
    cfg = preset_cfg(
        "amazon", ds, src, scheme="cyccoded", n_workers=W, n_stragglers=2,
        update_rule="AGD",
    )
    name = f"2_egc_amazon[{src}]"
    out[name] = tag(
        compare({"cyccoded_s2": cfg}, ds, batch=batch), name, src, "amazon"
    )

    # 3. Least-squares on kc_house, AGC with num_collect=N-3 (configs[2])
    W3 = 9  # AGC needs (s+1) | W
    ds, src = get_data("kc_house_data", W3, (2048, 64))
    cfg = preset_cfg(
        "kc_house_data", ds, src, scheme="approx", model=ModelKind.LINEAR,
        n_workers=W3, n_stragglers=2, num_collect=W3 - 3, update_rule="AGD",
    )
    name = f"3_agc_kc_house[{src}]"
    out[name] = tag(
        compare({"agc_collect_N-3": cfg}, ds, batch=batch), name, src,
        "kc_house_data"
    )

    # 4. Synthetic: partial_replication vs avoidstragg over n_stragglers
    #    (configs[3]) — partial and plain schemes need different partition
    #    counts, so run per-config compares sharing one arrival schedule,
    #    then re-anchor time_to_target on one shared loss target.
    W4 = 12
    arr = straggler.arrival_schedule(rounds, W4, add_delay=True, mean=0.5)
    sweep: list[RunSummary] = []
    for s in (1, 2, 3):
        for scheme, ppw in (
            ("avoidstragg", 0),
            # ppw = n_separate(2 unique) + (s+1) replicated slots
            ("partialrepcoded", s + 3),
        ):
            parts = (ppw - s) * W4 if ppw else W4
            d, _ = get_data("artificial", parts, (2048, 64))
            c = preset_cfg(
                "artificial", d, scheme=scheme, n_workers=W4, n_stragglers=s,
                update_rule="AGD", partitions_per_worker=ppw,
            )
            sweep.extend(
                compare({f"{scheme}_s{s}": c}, d, arrivals=arr, batch=batch)
            )
    shared_target = 1.05 * min(s.final_train_loss for s in sweep)
    for s in sweep:
        s.time_to_target = time_to_target_loss(
            s.training_loss, s.timeset, shared_target
        )
    out["4_partialrep_vs_avoidstragg_sweep"] = tag(
        sweep, "4_partialrep_vs_avoidstragg_sweep"
    )

    # 5. 2-layer MLP on covtype-shaped data, AGC, wide mesh (configs[4])
    ds, src = get_data("covtype", W, (2048, 64))
    cfg = preset_cfg(
        "covtype", ds, src, scheme="approx", model=ModelKind.MLP, n_workers=W,
        n_stragglers=1, num_collect=W - 2, update_rule="GD",
    )
    name = f"5_mlp_agc[{src}]"
    out[name] = tag(
        compare({"mlp_agc": cfg}, ds, batch=batch), name, src, "covtype"
    )
    return out


def save_summaries(summaries: list[RunSummary], path: str) -> None:
    with open(path, "w") as f:
        json.dump([s.row() for s in summaries], f, indent=2)


def format_table(summaries: list[RunSummary]) -> str:
    header = (
        f"{'label':22s} {'sim it/s':>9s} {'real it/s':>10s} "
        f"{'train loss':>11s} {'AUC':>7s} {'t->target':>10s} "
        f"{'dec err':>8s}"
    )
    lines = [header, "-" * len(header)]
    for s in summaries:
        auc = f"{s.final_auc:7.4f}" if np.isfinite(s.final_auc) else "      -"
        ttt = (
            f"{s.time_to_target:10.3f}"
            if s.time_to_target is not None
            else "         -"
        )
        derr = (
            f"{s.decode_error_mean:8.4f}"
            if s.decode_error_mean is not None
            else "       -"
        )
        lines.append(
            f"{s.label:22s} {s.sim_steps_per_sec:9.3f} "
            f"{s.real_steps_per_sec:10.1f} {s.final_train_loss:11.6f} "
            f"{auc} {ttt} {derr}"
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """`make compare` / `python -m erasurehead_tpu.train.experiments`:
    run the BASELINE.json suite (scaled down by default) and print tables."""
    import argparse

    import contextlib

    p = argparse.ArgumentParser(prog="erasurehead-tpu-experiments")
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--data-dir", default=None, help="prepared real data root")
    p.add_argument("--out", default=None, help="write summaries JSON here")
    p.add_argument("--figures", default=None,
                   help="render comparison PNGs into this directory")
    p.add_argument("--events", default=None,
                   help="write a run-telemetry events.jsonl for the whole "
                        "suite here (obs/; render with `erasurehead-tpu "
                        "report`)")
    p.add_argument("--batch-trajectories", default=None,
                   choices=["on", "off", "auto"],
                   help="trajectory-batched sweep dispatch "
                        "(trainer.train_cohort): configs sharing a device "
                        "data stack run as ONE compiled scan — a deduped "
                        "multi-scheme compare streams X once per round "
                        "for the whole cohort. Default: "
                        "ERASUREHEAD_BATCH_TRAJECTORIES env, else auto "
                        "(batch cohorts of >= 2)")
    ns = p.parse_args(argv)

    if ns.events:
        from erasurehead_tpu.obs import events as events_lib

        sink = events_lib.capture(ns.events)
    else:
        sink = contextlib.nullcontext()
    with sink:
        suite = baseline_suite(
            scale=ns.scale, data_dir=ns.data_dir, rounds=ns.rounds,
            batch=ns.batch_trajectories,
        )
    all_rows: list[RunSummary] = []
    for name, summaries in suite.items():
        print(f"\n== {name} ==")
        print(format_table(summaries))
        all_rows.extend(summaries)
        if ns.figures:
            from erasurehead_tpu.train import plots

            fig = plots.save_comparison_figure(
                summaries, os.path.join(ns.figures, f"{name}.png"), title=name
            )
            if fig:
                print(f"figure -> {fig}")
    if ns.out:
        save_summaries(all_rows, ns.out)
        print(f"\nsummaries -> {ns.out}")
    if ns.events:
        print(f"events -> {ns.events} (render: erasurehead-tpu report)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
