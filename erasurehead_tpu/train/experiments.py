"""Experiment harness: the AGC vs EGC vs uncoded comparisons.

The reference's experimental frame (BASELINE.md): for each scheme and
straggler count, train under the same seeded delay schedule and compare
(a) effective iteration rate and (b) time-to-target-loss, both measured on
the simulated master clock (the reference measured the same two quantities
with real injected sleeps; the schedules are identical streams).

``compare()`` runs a set of configs on one dataset under one shared arrival
schedule (paired comparison — the reference could only approximate this by
re-seeding per iteration, src/naive.py:141-148; we share the exact arrival
matrix across schemes). ``baseline_suite()`` reproduces the five BASELINE.json
configs at requested scale.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Optional, Sequence

import numpy as np

from erasurehead_tpu.data.synthetic import Dataset
from erasurehead_tpu.parallel import straggler
from erasurehead_tpu.train import evaluate, trainer
from erasurehead_tpu.utils import chaos as chaos_lib
from erasurehead_tpu.utils.config import RunConfig


@dataclasses.dataclass
class RunSummary:
    label: str
    config: RunConfig
    sim_total_time: float
    sim_steps_per_sec: float
    real_steps_per_sec: float
    final_train_loss: float
    final_test_loss: float
    final_auc: float
    time_to_target: Optional[float]  # simulated seconds; None if never reached
    training_loss: np.ndarray
    timeset: np.ndarray
    #: free-form caveat carried into the saved artifact (e.g. the synthetic
    #: stand-in's achievable-AUC ceiling) so a committed row can't be
    #: misread as divergent/random without its context (VERDICT r4 #6)
    note: Optional[str] = None
    #: suite config name (incl. any [synthetic(...)] substitution tag) —
    #: carried as its own artifact field so the flattened rows stay
    #: attributable without overloading the display label
    suite: Optional[str] = None
    #: sweep-engine cache telemetry for this run (train/cache.py via
    #: TrainResult.cache_info): data/exec hit-miss, compile seconds saved,
    #: bytes not re-uploaded — how much of the sweep the caches absorbed
    cache: Optional[dict] = None
    #: mean per-round AGC decode-error norm (obs/decode.py via
    #: TrainResult.decode_error): 0.0 for exact schemes, > 0 where the
    #: decode was genuinely approximate — the papers' central quantity,
    #: now a first-class sweep column
    decode_error_mean: Optional[float] = None
    #: trajectory outcome: "ok", or "diverged" when the final params / loss
    #: tail went NaN/Inf (divergence quarantine: the row is kept — rendered
    #: distinctly, excluded from target-loss aggregation — and the sweep
    #: continues instead of propagating NaNs into min()/time_to_target)
    status: str = "ok"

    def row(self) -> dict:
        def fin(v, nd):
            # diverged rows carry NaN losses; round(NaN) would make
            # save_summaries emit non-strict JSON (bare NaN tokens)
            return round(v, nd) if v is not None and np.isfinite(v) else None

        out = {
            "label": self.label,
            "scheme": self.config.scheme.value,
            "n_stragglers": self.config.n_stragglers,
            "num_collect": self.config.num_collect,
            "status": self.status,
            "sim_total_time": round(self.sim_total_time, 4),
            "sim_steps_per_sec": round(self.sim_steps_per_sec, 4),
            "real_steps_per_sec": round(self.real_steps_per_sec, 2),
            "final_train_loss": fin(self.final_train_loss, 6),
            "final_test_loss": fin(self.final_test_loss, 6),
            "final_auc": fin(self.final_auc, 6),
            "time_to_target": round(self.time_to_target, 4)
            if self.time_to_target is not None
            else None,
            "decode_error_mean": round(self.decode_error_mean, 8)
            if self.decode_error_mean is not None
            else None,
        }
        if self.suite:
            out["suite"] = self.suite
        if self.note:
            out["note"] = self.note
        if self.cache is not None:
            out["cache"] = self.cache
        return out


def time_to_target_loss(
    training_loss: np.ndarray, timeset: np.ndarray, target: float
) -> Optional[float]:
    """Simulated wall-clock until train loss first reaches ``target``
    (cumulative sum of per-iteration times — the reference's total-elapsed
    clock, src/naive.py:155-156)."""
    reached = np.flatnonzero(training_loss <= target)
    if reached.size == 0:
        return None
    return float(np.cumsum(timeset)[reached[0]])


def plan_cohorts(
    configs: dict[str, RunConfig],
) -> list[tuple[list[str], bool]]:
    """Group config labels into trajectory cohorts for batched dispatch.

    Returns ``[(labels, batchable), ...]`` in first-seen order: every
    group with ``batchable=True`` maps to one :func:`trainer.
    cohort_signature` key (same data stack + lowering, so
    ``train_cohort`` can run it as ONE compiled scan); ineligible configs
    (measured mode, forced pallas) come back as their own
    ``batchable=False`` singletons. In deduped mode the partition-major
    stack is scheme-independent, so a whole 7-scheme x N-seed compare()
    collapses into a single cohort."""
    groups: dict = {}
    order: list = []
    for label, cfg in configs.items():
        key = trainer.cohort_signature(cfg)
        if key is None:
            key = ("__sequential__", label)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(label)
    return [
        (groups[k], k[0] != "__sequential__") for k in order
    ]


# --------------------------------------------------------------------------
# graceful cohort degradation: a sweep must survive its dispatch engine.
# One cohort OOM (or a transient runtime failure) used to kill the whole
# multi-scheme/multi-seed sweep; now the dispatch guard retries transients
# with capped backoff, bisects failing cohorts into halves, and bottoms out
# at sequential train() — no trajectory is ever lost to a cohort failure.

#: max backoff retries per dispatch for TRANSIENT failures (OOM skips
#: straight to bisection — retrying the same allocation would fail again)
COHORT_MAX_RETRIES = 2
#: backoff base/cap in seconds (doubles per retry; tests shrink the base)
COHORT_BACKOFF_S = 0.05
COHORT_BACKOFF_CAP_S = 2.0

#: substrings classifying a runtime error as an out-of-memory failure
#: (bisection halves the cohort — and with it the dispatch's live set)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")
#: substrings classifying a runtime error as transient (retry with backoff
#: before degrading — remote-backend hiccups, preempted dispatch slots)
_TRANSIENT_MARKERS = (
    "DEADLINE_EXCEEDED", "UNAVAILABLE", "ABORTED", "CANCELLED", "INTERNAL",
)


def _guarded_error_types() -> tuple:
    """Exception types the dispatch guard may classify: XLA runtime errors
    (plus the chaos stand-in). Anything else — ValueError from config
    validation, user bugs — propagates untouched."""
    types: list = [chaos_lib.ChaosInjection]
    import jax

    err = getattr(jax.errors, "JaxRuntimeError", None)
    if err is not None:
        types.append(err)
    try:
        from jax._src.lib import xla_client

        types.append(xla_client.XlaRuntimeError)
    except Exception:  # noqa: BLE001 — optional import, version-dependent
        pass
    return tuple(types)


def _dispatch_error_kind(e: BaseException) -> Optional[str]:
    """"oom" / "transient" / None (= not ours to handle, re-raise)."""
    msg = str(e)
    if any(m in msg for m in _OOM_MARKERS):
        return "oom"
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return "transient"
    return None


def _backoff(attempt: int) -> float:
    return min(COHORT_BACKOFF_S * (2 ** (attempt - 1)), COHORT_BACKOFF_CAP_S)


def _arrivals_for(arrivals, label):
    """One trajectory's arrival matrix when ``arrivals`` may be a per-label
    dict (the serve daemon packs requests carrying their own schedules into
    one cohort); a shared matrix / None passes through untouched."""
    if isinstance(arrivals, dict):
        return arrivals[label]
    return arrivals


def _arrivals_arg(arrivals, labels):
    """The ``arrivals`` argument for a ``train_cohort`` dispatch of
    ``labels``: a per-label dict becomes the per-trajectory list
    train_cohort expects (in label order); anything else passes through."""
    if isinstance(arrivals, dict):
        return [arrivals[l] for l in labels]
    return arrivals


def _train_one_guarded(
    label: str, cfg: RunConfig, dataset: Dataset, arrivals
) -> "trainer.TrainResult":
    """Sequential train() with capped-backoff retry for transient runtime
    failures. OOM and persistent failures propagate — sequential is the
    bottom of the degradation ladder."""
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.obs.metrics import REGISTRY as _metrics

    attempts = 0
    while True:
        try:
            return trainer.train(
                cfg, dataset, arrivals=_arrivals_for(arrivals, label)
            )
        except _guarded_error_types() as e:
            if (
                _dispatch_error_kind(e) != "transient"
                or attempts >= COHORT_MAX_RETRIES
            ):
                raise
            attempts += 1
            _metrics.counter("cohort.retry").inc()
            obs_events.emit(
                "warning",
                kind="cohort_retry",
                message=(
                    f"sequential train of {label!r} hit a transient "
                    f"failure (attempt {attempts}): "
                    f"{str(e).splitlines()[0][:160]}"
                ),
            )
            time.sleep(_backoff(attempts))


def _dispatch_cohort(
    labels: list, configs: dict, dataset: Dataset, arrivals
) -> dict:
    """Guarded trajectory-batched dispatch: try the cohort as ONE compiled
    scan; on RESOURCE_EXHAUSTED bisect into halves (half the live set per
    dispatch), on transients retry with capped backoff first; bottom out
    at sequential train(). Every degradation step increments a counter
    (``cohort.retry`` / ``cohort.split`` / ``cohort.sequential_fallback``)
    and emits a ``warning`` event naming the failed cohort composition, so
    a degraded sweep is diagnosable from its event log.

    ``arrivals`` is a shared matrix, None, or a per-label dict (the serve
    daemon packs requests carrying their own schedules); the dict form
    threads correctly through bisection halves and sequential fallback."""
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.obs.metrics import REGISTRY as _metrics, warn_once

    attempts = 0
    while True:
        try:
            results = trainer.train_cohort(
                [configs[l] for l in labels], dataset,
                arrivals=_arrivals_arg(arrivals, labels),
            )
            return dict(zip(labels, results))
        except _guarded_error_types() as e:
            kind = _dispatch_error_kind(e)
            if kind is None:
                raise
            head = str(e).splitlines()[0][:160]
            obs_events.emit(
                "warning",
                kind="cohort_dispatch",
                message=(
                    f"cohort dispatch failed ({kind}) for "
                    f"{len(labels)} trajectories {list(labels)}: {head}"
                ),
            )
            warn_once(
                "cohort_dispatch",
                f"sweep: cohort dispatch failed ({kind}); degrading via "
                f"retry/bisection — first failure: {list(labels)}: {head}",
            )
            if kind == "oom":
                # release the data cache's HBM pins before the bisected
                # retries: the halves re-upload what they need, but they
                # don't contend with stacks no live run is using
                from erasurehead_tpu.train import cache as cache_lib

                cache_lib.drop_data_cache()
            if kind == "transient" and attempts < COHORT_MAX_RETRIES:
                attempts += 1
                _metrics.counter("cohort.retry").inc()
                time.sleep(_backoff(attempts))
                continue
            break  # degrade: bisect (or sequential for a singleton)
    if len(labels) == 1:
        _metrics.counter("cohort.sequential_fallback").inc()
        obs_events.emit(
            "warning",
            kind="cohort_fallback",
            message=(
                f"trajectory {labels[0]!r} falls back to sequential "
                f"train() after cohort dispatch failure"
            ),
        )
        return {
            labels[0]: _train_one_guarded(
                labels[0], configs[labels[0]], dataset, arrivals
            )
        }
    mid = len(labels) // 2
    lo, hi = list(labels[:mid]), list(labels[mid:])
    _metrics.counter("cohort.split").inc()
    obs_events.emit(
        "warning",
        kind="cohort_split",
        message=f"bisecting failed cohort {list(labels)} -> {lo} + {hi}",
    )
    out = _dispatch_cohort(lo, configs, dataset, arrivals)
    out.update(_dispatch_cohort(hi, configs, dataset, arrivals))
    return out


def _run_configs(
    configs: dict[str, RunConfig],
    dataset: Dataset,
    arrivals,
    batch: str,
    on_result: Optional[Callable] = None,
) -> dict[str, "trainer.TrainResult"]:
    """Train every config, dispatching cohorts through the guarded
    train_cohort path per the resolved ``batch`` mode ('on'/'off'/'auto');
    returns label -> TrainResult. Sequential fallbacks (mode 'off',
    singletons under 'auto', ineligible configs) go through plain train().

    ``on_result(label, result)`` is invoked as each trajectory's result
    becomes available (per member after a cohort dispatch lands; per run
    on the sequential path) — the journaling/quarantine hook: a sweep
    interrupted mid-plan keeps everything already handed over."""
    from erasurehead_tpu.obs.metrics import REGISTRY as _metrics

    raw: dict = {}

    def _finish(label, result):
        raw[label] = result
        if on_result is not None:
            on_result(label, result)

    if batch == "off":
        plan = [([label], False) for label in configs]
    else:
        plan = plan_cohorts(configs)
    min_size = 1 if batch == "on" else 2
    for labels, batchable in plan:
        if batchable and len(labels) >= min_size:
            results = _dispatch_cohort(
                list(labels), configs, dataset, arrivals
            )
            for l in labels:
                _finish(l, results[l])
        else:
            for l in labels:
                _metrics.counter("cohort.sequential_runs").inc()
                _finish(
                    l, _train_one_guarded(l, configs[l], dataset, arrivals)
                )
    return raw


def _diverged(result, ev, tail: int = 8) -> bool:
    """Did this trajectory diverge? NaN/Inf anywhere in the final params,
    or in the tail of the training-loss curve (a trajectory that blew up
    and 'recovered' to NaN stays NaN — checking only the last entry would
    miss an Inf overshoot that saturated)."""
    import jax

    for leaf in jax.tree.leaves(result.final_params):
        if not np.isfinite(np.asarray(leaf)).all():
            return True
    tail_losses = np.asarray(ev.training_loss)[-tail:]
    return bool(tail_losses.size) and not bool(
        np.isfinite(tail_losses).all()
    )


def _validate_shared_shape(configs: dict[str, RunConfig]) -> None:
    """compare()'s paired-schedule contract: every config shares rounds
    and n_workers. A ValueError (asserts vanish under ``python -O``)
    naming the offending labels, not just "configs must share shape"."""
    if not configs:
        raise ValueError("compare() needs at least one config")
    rounds = {c.rounds for c in configs.values()}
    workers = {c.n_workers for c in configs.values()}
    if len(rounds) != 1 or len(workers) != 1:
        detail = ", ".join(
            f"{label!r}: rounds={cfg.rounds}, workers={cfg.n_workers}"
            for label, cfg in configs.items()
        )
        raise ValueError(
            "compare() configs must share rounds and n_workers (one "
            f"arrival schedule pairs the whole set); got {detail}"
        )


def _default_target_loss(
    summaries: dict[str, RunSummary],
) -> Optional[float]:
    """compare()'s default loss target: 1.05x the uncoded baseline's final
    train loss when a 'naive' row exists (and converged), else the worst
    final loss across converged rows. Diverged rows are quarantined out —
    a NaN target would silently void every time_to_target. None when
    nothing converged."""
    ok = {
        label: s
        for label, s in summaries.items()
        if s.status == "ok" and np.isfinite(s.final_train_loss)
    }
    if "naive" in ok:
        return 1.05 * float(ok["naive"].final_train_loss)
    if ok:
        return float(max(s.final_train_loss for s in ok.values()))
    return None


def compare(
    configs: dict[str, RunConfig],
    dataset: Dataset,
    target_loss: Optional[float] = None,
    arrivals: Optional[np.ndarray] = None,
    batch: Optional[str] = None,
    journal=None,
) -> list[RunSummary]:
    """Train every config on ``dataset`` under one shared arrival schedule
    and summarize. ``target_loss`` default: 1.05x the uncoded baseline's
    final train loss (if a config labeled 'naive' is present), else the
    worst final loss across runs (diverged rows excluded — see below).

    ``batch`` picks the trajectory-batched dispatch mode ('on'/'off'/
    'auto'; None = the --batch-trajectories flag/env default, see
    utils.config.resolve_batch_trajectories): under 'auto'/'on', configs
    sharing a device data stack (plan_cohorts) run as ONE compiled cohort
    scan — a deduped 7-scheme sweep streams X from HBM once per round for
    all schemes instead of once per scheme. Cohort dispatch failures
    degrade gracefully (retry / bisect / sequential, ``_dispatch_cohort``)
    instead of killing the sweep.

    ``journal`` is a :class:`train.journal.SweepJournal` (None = the
    ambient ``ERASUREHEAD_SWEEP_JOURNAL`` journal, if any): every finished
    trajectory's summary row is journaled as it completes, and in resume
    mode trajectories whose (label, config, data, arrivals) key is already
    journaled are REHYDRATED instead of re-trained — a resumed sweep's
    rows are identical to an uninterrupted one's (time_to_target is
    re-derived from the journaled curves for fresh and rehydrated rows
    alike, so the shared target can never drift between them).

    Divergence quarantine: a trajectory whose final params or loss tail
    went NaN/Inf gets ``status="diverged"`` — kept in the output (rendered
    distinctly), excluded from target aggregation, ``time_to_target=None``
    — and the sweep continues.
    """
    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.obs.metrics import REGISTRY as _metrics
    from erasurehead_tpu.train import journal as journal_lib
    from erasurehead_tpu.utils.config import resolve_batch_trajectories

    _validate_shared_shape(configs)
    if arrivals is None:
        from erasurehead_tpu.utils.config import resolve_arrival_trace

        any_cfg = next(iter(configs.values()))
        # a recorded arrival trace (config field or env) replaces the
        # drawn exponential stream as the sweep's ONE shared schedule —
        # the paired-comparison contract holds either way
        arrivals = straggler.arrival_schedule(
            any_cfg.rounds, any_cfg.n_workers, add_delay=True,
            mean=any_cfg.delay_mean,
            trace=resolve_arrival_trace(any_cfg.arrival_trace),
        )

    if journal is None:
        journal = journal_lib.from_env()
    keys: dict[str, str] = {}
    summaries: dict[str, RunSummary] = {}
    pending: dict[str, RunConfig] = {}
    for label, cfg in configs.items():
        if journal is not None:
            keys[label] = journal_lib.trajectory_key(
                label, cfg, dataset, arrivals
            )
            rec = journal.lookup(keys[label])
            if rec is not None:
                summaries[label] = journal_lib.rehydrate_summary(
                    rec["row"], cfg
                )
                _metrics.counter("sweep_journal.resumed").inc()
                continue
        pending[label] = cfg

    def _finish(label, res):
        """Per-trajectory completion: eval replay, divergence quarantine,
        journal append, chaos hook — runs as each result lands, so an
        interruption mid-sweep loses at most the in-flight dispatch."""
        cfg = pending[label]
        model = trainer.build_model(cfg)
        n = res.n_train
        ev = evaluate.replay(
            model,
            cfg.model,
            res.params_history,
            dataset.X_train[:n],
            dataset.y_train[:n],
            dataset.X_test,
            dataset.y_test,
        )
        diverged = _diverged(res, ev)
        if diverged:
            _metrics.counter("sweep.diverged").inc()
            obs_events.emit(
                "warning",
                kind="divergence",
                message=(
                    f"trajectory {label!r} (scheme "
                    f"{res.config.scheme.value}, seed {res.config.seed}) "
                    "diverged (NaN/Inf final params or loss tail); row "
                    "quarantined as status=diverged, sweep continues"
                ),
            )
        summaries[label] = RunSummary(
            label=label,
            config=res.config,
            sim_total_time=res.sim_total_time,
            sim_steps_per_sec=(
                res.config.rounds / res.sim_total_time
                if res.sim_total_time > 0
                else float("inf")  # zero arrival schedule (no delays)
            ),
            real_steps_per_sec=res.steps_per_sec,
            final_train_loss=float(ev.training_loss[-1]),
            final_test_loss=float(ev.testing_loss[-1]),
            final_auc=float(ev.auc[-1]),
            time_to_target=None,  # assigned below, once the target exists
            training_loss=ev.training_loss,
            timeset=res.timeset,
            cache=res.cache_info,
            decode_error_mean=(
                float(np.mean(res.decode_error))
                if res.decode_error is not None
                and len(res.decode_error)
                else None
            ),
            status="diverged" if diverged else "ok",
        )
        if journal is not None:
            journal.record(keys[label], label, summaries[label])
        chaos_lib.maybe_fire("trajectory")

    if pending:
        _run_configs(
            pending, dataset, arrivals, resolve_batch_trajectories(batch),
            on_result=_finish,
        )

    # one shared target across rehydrated + fresh rows, re-derived every
    # time from the (bit-stable) journaled curves — a resumed sweep and an
    # uninterrupted one agree row for row
    if target_loss is None:
        target_loss = _default_target_loss(summaries)
    for s in summaries.values():
        s.time_to_target = (
            time_to_target_loss(s.training_loss, s.timeset, target_loss)
            if s.status == "ok" and target_loss is not None
            else None
        )
    return [summaries[label] for label in configs]


def straggler_sweep(
    base: RunConfig,
    dataset: Dataset,
    scheme_stragglers: dict[str, Sequence[int]],
    **compare_kw,
) -> list[RunSummary]:
    """The reference's headline figure: each scheme across straggler counts
    (time-to-target-loss vs n_stragglers, BASELINE.json metric).
    ``compare_kw`` passes through to :func:`compare` (``batch``,
    ``journal``, ``target_loss``, ...)."""
    if not scheme_stragglers or not any(scheme_stragglers.values()):
        raise ValueError(
            "straggler_sweep needs at least one (scheme, straggler-count) "
            f"entry; got {scheme_stragglers!r}"
        )
    from erasurehead_tpu import schemes as schemes_lib

    configs = {}
    for scheme, s_values in scheme_stragglers.items():
        for s in s_values:
            cfg = dataclasses.replace(base, scheme=scheme, n_stragglers=s)
            collect_override = schemes_lib.get(cfg.scheme).sweep_num_collect
            if (
                collect_override is not None
                and cfg.num_collect >= cfg.n_workers
            ):
                # e.g. AGC: its interesting regime collects fewer than all
                # (the descriptor's sweep_num_collect hook says how many)
                cfg = dataclasses.replace(
                    cfg, num_collect=collect_override(cfg.n_workers)
                )
            configs[f"{scheme}_s{s}"] = cfg
    return compare(configs, dataset, **compare_kw)


def baseline_suite(
    scale: float = 1.0,
    data_dir: Optional[str] = None,
    rounds: int = 100,
    batch: Optional[str] = None,
    journal=None,
) -> dict[str, list[RunSummary]]:
    """Reproduce the five BASELINE.json comparison configs.

    Real datasets (covtype / amazon / kc_house) are used when prepared under
    ``data_dir`` in the reference layout; otherwise each config falls back to
    a synthetic stand-in of the same structure (GMM for logistic tasks,
    linear-model data for least-squares) at ``scale`` x a canonical size, and
    the suite labels record the substitution. Returns {config_name: summaries}.
    ``batch`` is the trajectory-batched dispatch mode threaded into every
    compare() (see :func:`compare`; the suite's configs are mostly
    singletons, so 'auto' leaves them sequential). ``journal`` threads a
    sweep journal (train/journal.py) into every compare(), making the
    whole suite preemption-safe: trajectories persist as they finish and
    a resumed suite skips them.
    """
    from erasurehead_tpu.data.synthetic import (
        generate_gmm,
        generate_linear,
        generate_onehot,
    )
    from erasurehead_tpu.utils.config import ModelKind

    # reference nnz/row of the real one-hot matrices: covtype's binned
    # one-hot has 12 active categories per row (arrange_real_data.py:145-205
    # structure), amazon's hashed-interaction encoding has 44
    # (arrange_real_data.py:34-91; pinned in tests/test_data.py)
    ONEHOT_NNZ = {"covtype": 12, "amazon": 44}

    def _rows(rows, parts):
        n = max(parts * 8, int(rows * scale))
        return parts * max(1, round(n / parts))  # multiple of n_partitions

    _cache: dict = {}

    def get_data(name, parts, fallback):
        """Prepared real dataset if present under data_dir, else a synthetic
        stand-in of the same structure. Memoized per (name, parts)."""
        key = (name, parts)
        if key in _cache:
            return _cache[key]
        if data_dir is not None:
            import os

            from erasurehead_tpu.data import io as data_io

            path = os.path.join(data_dir, name, str(parts))
            if data_io.has_reference_layout(path):
                ds = data_io.read_reference_layout(path, parts)
                _cache[key] = (ds, name)
                return _cache[key]
        rows, cols = fallback
        if name in ONEHOT_NNZ:
            # structure-matched sparse stand-in: one-hot CSR with the real
            # dataset's nnz/row, so the suite exercises the PaddedRows path
            # the actual workload would take
            nnz = min(ONEHOT_NNZ[name], cols)
            ds = generate_onehot(
                _rows(rows, parts), cols, parts, n_fields=nnz, seed=0
            )
        else:
            maker = (
                generate_linear
                if name in ("kc_house_data", "synthetic-linear")
                else generate_gmm
            )
            ds = maker(_rows(rows, parts), cols, parts, seed=0)
        _cache[key] = (ds, f"synthetic({name}-shaped)")
        return _cache[key]

    def preset_cfg(dataset_name, ds, src=None, **kw):
        """Config carrying the dataset's reference lr preset (main.py:37-46)
        and alpha = 1/n_train for the data actually in use.

        When a synthetic stand-in substitutes for a real dataset
        (``src != dataset_name``), the reference preset lr — tuned to the
        real set's canonical scale — does not transfer: amazon's lr=10
        diverges and covtype's lr=0.1 stalls at the stand-in scale (the
        committed r3 artifact shipped exactly those rows, VERDICT r4 #6).
        Classification configs then run at a stand-in-convergent constant
        lr; ``artificial`` keeps its preset (the stand-in IS its dataset),
        and the linear preset transfers as-is."""
        n_train = ds.X_train.shape[0]
        cfg = RunConfig.for_dataset(
            dataset_name, rounds=rounds, add_delay=True,
            **{"n_rows": n_train, "n_cols": ds.X_train.shape[1], **kw},
        )
        is_standin = src is not None and src != dataset_name
        if (is_standin and dataset_name != "artificial"
                and cfg.model is not ModelKind.LINEAR
                and "lr_schedule" not in kw):
            # logistic curvature scales with the squared row norm = nnz/row
            # for one-hot data, so the stable constant lr scales as 1/nnz
            # (measured: nnz=12 converges at 1.0; nnz=44 diverges there)
            nnz = ONEHOT_NNZ.get(dataset_name)
            cfg = dataclasses.replace(
                cfg, lr_schedule=1.0 if nnz is None else min(1.0, 12.0 / nnz)
            )
        return cfg

    #: caveat attached to every synthetic-stand-in classification row so a
    #: committed artifact row can't be misread as divergent/random
    STANDIN_NOTE = (
        "synthetic stand-in: labels drawn from a unit-logit-variance "
        "logistic model (data/synthetic.generate_*), whose Bayes-optimal "
        "classifier has log-loss ~0.60 and AUC ~0.74 (Monte-Carlo) — "
        "train loss near 0.60 is AT the generator's floor, not underfit"
    )

    def tag(summaries, name, src=None, dataset_name=None):
        """Flatten-proof the rows: record the suite config name (incl. any
        [synthetic(...)] substitution) on each row, and annotate stand-in
        classification rows with the generator's ceiling — here, where
        ``src`` is known, so every save_summaries() caller gets the
        annotated rows, not just the CLI."""
        for s in summaries:
            s.suite = name
            if (src is not None and src != dataset_name
                    and s.config.model is not ModelKind.LINEAR):
                s.note = STANDIN_NOTE
        return summaries

    out: dict[str, list[RunSummary]] = {}

    # 1. Logistic on covtype, uncoded, 8 workers (BASELINE.json configs[0])
    W = 8
    ds, src = get_data("covtype", W, (2048, 64))
    cfg = preset_cfg(
        "covtype", ds, src, scheme="naive", n_workers=W, n_stragglers=0,
        update_rule="GD",
    )
    name = f"1_naive_covtype[{src}]"
    out[name] = tag(
        compare({"naive": cfg}, ds, batch=batch, journal=journal),
        name, src, "covtype"
    )

    # 2. Logistic on amazon, exact cyclic-MDS coding, s=2 (configs[1])
    ds, src = get_data("amazon", W, (2048, 64))
    cfg = preset_cfg(
        "amazon", ds, src, scheme="cyccoded", n_workers=W, n_stragglers=2,
        update_rule="AGD",
    )
    name = f"2_egc_amazon[{src}]"
    out[name] = tag(
        compare({"cyccoded_s2": cfg}, ds, batch=batch, journal=journal),
        name, src, "amazon"
    )

    # 3. Least-squares on kc_house, AGC with num_collect=N-3 (configs[2])
    W3 = 9  # AGC needs (s+1) | W
    ds, src = get_data("kc_house_data", W3, (2048, 64))
    cfg = preset_cfg(
        "kc_house_data", ds, src, scheme="approx", model=ModelKind.LINEAR,
        n_workers=W3, n_stragglers=2, num_collect=W3 - 3, update_rule="AGD",
    )
    name = f"3_agc_kc_house[{src}]"
    out[name] = tag(
        compare({"agc_collect_N-3": cfg}, ds, batch=batch,
                journal=journal),
        name, src, "kc_house_data"
    )

    # 4. Synthetic: partial_replication vs avoidstragg over n_stragglers
    #    (configs[3]) — partial and plain schemes need different partition
    #    counts, so run per-config compares sharing one arrival schedule,
    #    then re-anchor time_to_target on one shared loss target.
    W4 = 12
    arr = straggler.arrival_schedule(rounds, W4, add_delay=True, mean=0.5)
    sweep: list[RunSummary] = []
    for s in (1, 2, 3):
        for scheme, ppw in (
            ("avoidstragg", 0),
            # ppw = n_separate(2 unique) + (s+1) replicated slots
            ("partialrepcoded", s + 3),
        ):
            parts = (ppw - s) * W4 if ppw else W4
            d, _ = get_data("artificial", parts, (2048, 64))
            c = preset_cfg(
                "artificial", d, scheme=scheme, n_workers=W4, n_stragglers=s,
                update_rule="AGD", partitions_per_worker=ppw,
            )
            sweep.extend(
                compare({f"{scheme}_s{s}": c}, d, arrivals=arr, batch=batch,
                        journal=journal)
            )
    # diverged rows are quarantined out of the anchor: a NaN min() would
    # silently void every row's time_to_target (and min() over an empty
    # all-diverged sweep would crash the suite)
    anchors = [
        s.final_train_loss
        for s in sweep
        if s.status == "ok" and np.isfinite(s.final_train_loss)
    ]
    shared_target = 1.05 * min(anchors) if anchors else None
    for s in sweep:
        s.time_to_target = (
            time_to_target_loss(s.training_loss, s.timeset, shared_target)
            if shared_target is not None and s.status == "ok"
            else None
        )
    out["4_partialrep_vs_avoidstragg_sweep"] = tag(
        sweep, "4_partialrep_vs_avoidstragg_sweep"
    )

    # 5. 2-layer MLP on covtype-shaped data, AGC, wide mesh (configs[4])
    ds, src = get_data("covtype", W, (2048, 64))
    cfg = preset_cfg(
        "covtype", ds, src, scheme="approx", model=ModelKind.MLP, n_workers=W,
        n_stragglers=1, num_collect=W - 2, update_rule="GD",
    )
    name = f"5_mlp_agc[{src}]"
    out[name] = tag(
        compare({"mlp_agc": cfg}, ds, batch=batch, journal=journal),
        name, src, "covtype"
    )
    return out


def save_summaries(summaries: list[RunSummary], path: str) -> None:
    with open(path, "w") as f:
        json.dump([s.row() for s in summaries], f, indent=2)


def format_table(summaries: list[RunSummary]) -> str:
    header = (
        f"{'label':22s} {'sim it/s':>9s} {'real it/s':>10s} "
        f"{'train loss':>11s} {'AUC':>7s} {'t->target':>10s} "
        f"{'dec err':>8s}"
    )
    lines = [header, "-" * len(header)]
    for s in summaries:
        auc = f"{s.final_auc:7.4f}" if np.isfinite(s.final_auc) else "      -"
        ttt = (
            f"{s.time_to_target:10.3f}"
            if s.time_to_target is not None
            else "         -"
        )
        derr = (
            f"{s.decode_error_mean:8.4f}"
            if s.decode_error_mean is not None
            else "       -"
        )
        # quarantined rows render distinctly: a NaN printed as a number
        # reads like a measurement; "diverged" reads like the verdict it is
        loss = (
            f"{s.final_train_loss:11.6f}"
            if s.status == "ok" and np.isfinite(s.final_train_loss)
            else f"{'diverged' if s.status == 'diverged' else '-':>11s}"
        )
        lines.append(
            f"{s.label:22s} {s.sim_steps_per_sec:9.3f} "
            f"{s.real_steps_per_sec:10.1f} {loss} "
            f"{auc} {ttt} {derr}"
        )
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """`make compare` / `python -m erasurehead_tpu.train.experiments`:
    run the BASELINE.json suite (scaled down by default) and print tables."""
    import argparse

    import contextlib

    p = argparse.ArgumentParser(prog="erasurehead-tpu-experiments")
    p.add_argument("--scale", type=float, default=1.0)
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--data-dir", default=None, help="prepared real data root")
    p.add_argument("--out", default=None, help="write summaries JSON here")
    p.add_argument("--figures", default=None,
                   help="render comparison PNGs into this directory")
    p.add_argument("--events", default=None,
                   help="write a run-telemetry events.jsonl for the whole "
                        "suite here (obs/; render with `erasurehead-tpu "
                        "report`)")
    p.add_argument("--batch-trajectories", default=None,
                   choices=["on", "off", "auto"],
                   help="trajectory-batched sweep dispatch "
                        "(trainer.train_cohort): configs sharing a device "
                        "data stack run as ONE compiled scan — a deduped "
                        "multi-scheme compare streams X once per round "
                        "for the whole cohort. Default: "
                        "ERASUREHEAD_BATCH_TRAJECTORIES env, else auto "
                        "(batch cohorts of >= 2)")
    p.add_argument("--sweep-journal", default=None, metavar="DIR",
                   help="journal each trajectory's summary row into "
                        "DIR/sweep_journal.jsonl as it finishes "
                        "(train/journal.py) — the suite becomes "
                        "preemption-safe. Default: "
                        "ERASUREHEAD_SWEEP_JOURNAL env, else off")
    p.add_argument("--resume-sweep", action="store_true",
                   help="skip trajectories the sweep journal already "
                        "completed (matching config + data + arrival "
                        "digests), rehydrating their rows — a resumed "
                        "suite's output is row-for-row identical to an "
                        "uninterrupted one. Requires --sweep-journal (or "
                        "the env var); ERASUREHEAD_RESUME_SWEEP=1 does "
                        "the same")
    ns = p.parse_args(argv)

    from erasurehead_tpu.train import journal as journal_lib
    from erasurehead_tpu.utils.config import (
        resolve_resume_sweep,
        resolve_sweep_journal,
    )

    journal_dir = resolve_sweep_journal(ns.sweep_journal)
    resume = resolve_resume_sweep(True if ns.resume_sweep else None)
    if resume and journal_dir is None:
        p.error("--resume-sweep requires --sweep-journal DIR (or "
                "ERASUREHEAD_SWEEP_JOURNAL)")
    journal = (
        journal_lib.SweepJournal(journal_dir, resume=resume)
        if journal_dir
        else None
    )

    if ns.events:
        from erasurehead_tpu.obs import events as events_lib

        sink = events_lib.capture(ns.events)
    else:
        sink = contextlib.nullcontext()
    try:
        with sink:
            suite = baseline_suite(
                scale=ns.scale, data_dir=ns.data_dir, rounds=ns.rounds,
                batch=ns.batch_trajectories, journal=journal,
            )
    finally:
        if journal is not None:
            journal.close()
    if journal is not None:
        print(f"sweep journal -> {journal.path}")
    all_rows: list[RunSummary] = []
    for name, summaries in suite.items():
        print(f"\n== {name} ==")
        print(format_table(summaries))
        all_rows.extend(summaries)
        if ns.figures:
            from erasurehead_tpu.train import plots

            fig = plots.save_comparison_figure(
                summaries, os.path.join(ns.figures, f"{name}.png"), title=name
            )
            if fig:
                print(f"figure -> {fig}")
    if ns.out:
        save_summaries(all_rows, ns.out)
        print(f"\nsummaries -> {ns.out}")
    if ns.events:
        print(f"events -> {ns.events} (render: erasurehead-tpu report)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
