"""GD and accelerated-GD updates, generic over parameter pytrees.

The reference copy-pastes these two updates into every scheme file
(SURVEY.md §2.4); here they are one module, expressed over pytrees so the
same code trains a GLM vector and an MLP.

Update rules being matched (src/naive.py:113-122):
  GD:   beta <- (1 - 2*alpha*eta_i) * beta - (eta_i / n) * g
  AGD (Nesterov-style, theta_i = 2/(i+2)):
        y      = (1 - theta) * beta + theta * u
        beta+  = y - (eta_i / n) * g - 2*alpha*eta_i * beta
        u     <- beta + (beta+ - beta) / theta
where g is the *sum* gradient over collected samples and n is the total
sample count (the eta/n "grad_multiplier", src/naive.py:112; avoidstragg's
rescaled multiplier is folded into the collection weights instead,
parallel/collect.py).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from erasurehead_tpu.utils.config import UpdateRule

Params = Any


class OptState(NamedTuple):
    params: Params
    momentum: Params  # AGD's u sequence; unused by GD


class _PairLeaf(NamedTuple):
    """Per-leaf (params, momentum) bundle inside agd_update's mapped tree —
    a distinct type so unpacking can never mistake a user tuple for it."""

    p: Any
    u: Any


class _AdamLeaf(NamedTuple):
    """Per-leaf (params, mu, nu) bundle inside adam_update's mapped tree —
    a distinct type so unpacking can never mistake a user 3-tuple for it."""

    p: Any
    m: Any
    v: Any


def init_state(params: Params, rule: UpdateRule = UpdateRule.AGD) -> OptState:
    """``momentum`` holds AGD's u sequence; for ADAM it holds the
    (mu, nu) moment pair as a 2-tuple pytree (bias-correction count comes
    from the iteration index the trainer already passes in)."""
    zeros = jax.tree.map(jnp.zeros_like, params)
    if UpdateRule(rule) == UpdateRule.ADAM:
        return OptState(params=params, momentum=(zeros, zeros))
    return OptState(params=params, momentum=zeros)


def gd_update(
    state: OptState, g: Params, eta: jnp.ndarray, alpha: float, n_samples: int, i
) -> OptState:
    mult = eta / n_samples
    new = jax.tree.map(
        lambda b, gg: (1.0 - 2.0 * alpha * eta) * b - mult * gg, state.params, g
    )
    return OptState(params=new, momentum=state.momentum)


def agd_update(
    state: OptState, g: Params, eta: jnp.ndarray, alpha: float, n_samples: int, i
) -> OptState:
    mult = eta / n_samples
    theta = 2.0 / (i + 2.0)
    def leaf(b, u, gg):
        y = (1.0 - theta) * b + theta * u
        b_next = y - mult * gg - 2.0 * alpha * eta * b
        u_next = b + (b_next - b) / theta
        return b_next, u_next
    pairs = jax.tree.map(
        lambda *a: _PairLeaf(*leaf(*a)), state.params, state.momentum, g
    )
    is_pair = lambda t: isinstance(t, _PairLeaf)
    new_p = jax.tree.map(lambda t: t.p, pairs, is_leaf=is_pair)
    new_u = jax.tree.map(lambda t: t.u, pairs, is_leaf=is_pair)
    return OptState(params=new_p, momentum=new_u)


def adam_update(
    state: OptState, g: Params, eta: jnp.ndarray, alpha: float, n_samples: int, i
) -> OptState:
    """Adam (beyond the reference) on the same objective the GD rule
    descends: mean loss + alpha*||params||^2, so g/n + 2*alpha*params is
    the gradient fed to the moments. Bias correction uses the iteration
    index the scan already threads through (t = i+1)."""
    b1, b2, eps = 0.9, 0.999, 1e-8
    t = i + 1.0
    mu, nu = state.momentum

    def leaf(p, m, v, gg):
        grad = gg / n_samples + 2.0 * alpha * p
        m_new = b1 * m + (1.0 - b1) * grad
        v_new = b2 * v + (1.0 - b2) * grad * grad
        m_hat = m_new / (1.0 - b1**t)
        v_hat = v_new / (1.0 - b2**t)
        p_new = p - eta * m_hat / (jnp.sqrt(v_hat) + eps)
        return p_new, m_new, v_new

    triples = jax.tree.map(
        lambda *a: _AdamLeaf(*leaf(*a)), state.params, mu, nu, g
    )
    is_triple = lambda x: isinstance(x, _AdamLeaf)
    pick = lambda k: jax.tree.map(lambda x: x[k], triples, is_leaf=is_triple)
    return OptState(params=pick(0), momentum=(pick(1), pick(2)))


def make_update_fn(rule: UpdateRule):
    rule = UpdateRule(rule)
    if rule == UpdateRule.GD:
        return gd_update
    if rule == UpdateRule.ADAM:
        return adam_update
    return agd_update
