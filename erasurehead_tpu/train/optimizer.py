"""GD and accelerated-GD updates, generic over parameter pytrees.

The reference copy-pastes these two updates into every scheme file
(SURVEY.md §2.4); here they are one module, expressed over pytrees so the
same code trains a GLM vector and an MLP.

Update rules being matched (src/naive.py:113-122):
  GD:   beta <- (1 - 2*alpha*eta_i) * beta - (eta_i / n) * g
  AGD (Nesterov-style, theta_i = 2/(i+2)):
        y      = (1 - theta) * beta + theta * u
        beta+  = y - (eta_i / n) * g - 2*alpha*eta_i * beta
        u     <- beta + (beta+ - beta) / theta
where g is the *sum* gradient over collected samples and n is the total
sample count (the eta/n "grad_multiplier", src/naive.py:112; avoidstragg's
rescaled multiplier is folded into the collection weights instead,
parallel/collect.py).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from erasurehead_tpu.utils.config import UpdateRule

Params = Any


class OptState(NamedTuple):
    params: Params
    momentum: Params  # AGD's u sequence; unused by GD


def init_state(params: Params) -> OptState:
    return OptState(params=params, momentum=jax.tree.map(jnp.zeros_like, params))


def gd_update(
    state: OptState, g: Params, eta: jnp.ndarray, alpha: float, n_samples: int, i
) -> OptState:
    mult = eta / n_samples
    new = jax.tree.map(
        lambda b, gg: (1.0 - 2.0 * alpha * eta) * b - mult * gg, state.params, g
    )
    return OptState(params=new, momentum=state.momentum)


def agd_update(
    state: OptState, g: Params, eta: jnp.ndarray, alpha: float, n_samples: int, i
) -> OptState:
    mult = eta / n_samples
    theta = 2.0 / (i + 2.0)
    def leaf(b, u, gg):
        y = (1.0 - theta) * b + theta * u
        b_next = y - mult * gg - 2.0 * alpha * eta * b
        u_next = b + (b_next - b) / theta
        return b_next, u_next
    pairs = jax.tree.map(leaf, state.params, state.momentum, g)
    new_p = jax.tree.map(lambda t: t[0], pairs, is_leaf=lambda t: isinstance(t, tuple))
    new_u = jax.tree.map(lambda t: t[1], pairs, is_leaf=lambda t: isinstance(t, tuple))
    return OptState(params=new_p, momentum=new_u)


def make_update_fn(rule: UpdateRule):
    return gd_update if UpdateRule(rule) == UpdateRule.GD else agd_update
