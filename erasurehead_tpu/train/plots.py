"""Comparison figures: the reference's images/straggler.jpg, regenerated.

The reference ships one static figure claiming AGC "converges as quickly as
distributed GD and has faster overall runtime" (README.md:7-9). This module
renders that comparison from real run data (experiments.compare /
straggler_sweep output): training loss against *simulated cluster time* per
scheme, plus time-to-target bars — the two BASELINE.json north-star views.

Design notes (per the dataviz method): one axis per panel; categorical color
follows the *scheme* identity in a fixed slot order (never re-assigned when
a scheme is filtered out); 2px lines with direct end-labels plus a legend;
recessive grid; values readable from the saved .dat artifacts (the "table
view"). Palette: the validated reference instance (slots 1-8, light mode).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

# fixed categorical slots (validated adjacent-pair order; color follows the
# scheme entity — filtering schemes must not repaint survivors)
SCHEME_COLORS = {
    "naive": "#2a78d6",
    "approx": "#eb6834",
    "cyccoded": "#1baf7a",
    "repcoded": "#eda100",
    "avoidstragg": "#e87ba4",
    "partialcyccoded": "#008300",
    "partialrepcoded": "#4a3aa7",
    "randreg": "#e34948",
    "deadline": "#7a5f3a",
}
_FALLBACK = "#6b6a60"  # neutral "Other" gray for unknown labels
_INK = "#1a1a19"
_INK_2 = "#6b6a60"
_GRID = "#e8e7e0"


def _color(summary) -> str:
    return SCHEME_COLORS.get(summary.config.scheme.value, _FALLBACK)


def _style_axes(ax):
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(_GRID)
    ax.tick_params(colors=_INK_2, labelsize=8)
    ax.grid(True, color=_GRID, linewidth=0.6, zorder=0)
    ax.set_axisbelow(True)


def _end_labels(ax, ends: list[tuple[float, float, str]]) -> None:
    """Direct labels at line ends, de-conflicted: a label is nudged up only
    when another sits at BOTH a nearby x and a nearby y — labels far apart
    on the x axis don't fight and stay glued to their line ends."""
    y0, y1 = ax.get_ylim()  # full data range, not just the end points
    x0, x1 = ax.get_xlim()
    min_dy = 0.05 * ((y1 - y0) or 1.0)
    min_dx = 0.12 * ((x1 - x0) or 1.0)
    placed: list[tuple[float, float]] = []
    for x, y, label in sorted(ends, key=lambda e: e[1]):
        while any(
            abs(x - px) < min_dx and abs(y - py) < min_dy
            for px, py in placed
        ):
            y += min_dy
        placed.append((x, y))
        ax.annotate(
            label, (x, y), xytext=(6, 0), textcoords="offset points",
            fontsize=8, color=_INK, va="center",
        )


def save_comparison_figure(
    summaries: Sequence,
    path: str,
    title: Optional[str] = None,
) -> Optional[str]:
    """Loss-vs-simulated-time lines + time-to-target bars -> PNG.

    Returns the path, or None when matplotlib is unavailable (the numeric
    artifacts remain the source of truth either way).
    """
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:
        return None

    fig, (ax_loss, ax_ttt) = plt.subplots(
        1, 2, figsize=(10, 4), gridspec_kw={"width_ratios": [3, 2]}
    )
    fig.patch.set_facecolor("white")

    # panel A: training loss vs cumulative simulated cluster seconds
    ends = []
    for s in summaries:
        t = np.cumsum(s.timeset)
        c = _color(s)
        ax_loss.plot(t, s.training_loss, color=c, linewidth=2, zorder=3)
        ends.append((float(t[-1]), float(s.training_loss[-1]), s.label))
    _end_labels(ax_loss, ends)
    _style_axes(ax_loss)
    ax_loss.set_xlabel("simulated cluster time (s)", fontsize=9, color=_INK)
    ax_loss.set_ylabel("training loss", fontsize=9, color=_INK)
    ax_loss.margins(x=0.12)
    ax_loss.legend(
        [s.label for s in summaries],
        frameon=False,
        fontsize=8,
        labelcolor=_INK,
    )
    for line, s in zip(ax_loss.get_legend().legend_handles, summaries):
        line.set_color(_color(s))

    # panel B: simulated time to the shared target loss
    labels = [s.label for s in summaries]
    vals = [
        s.time_to_target if s.time_to_target is not None else np.nan
        for s in summaries
    ]
    ypos = np.arange(len(labels))
    for i, (v, s) in enumerate(zip(vals, summaries)):
        if np.isfinite(v):
            ax_ttt.barh(i, v, height=0.55, color=_color(s), zorder=3)
            ax_ttt.annotate(
                f"{v:.1f}s",
                (v, i),
                xytext=(4, 0),
                textcoords="offset points",
                fontsize=8,
                color=_INK,
                va="center",
            )
        else:
            ax_ttt.annotate(
                "target not reached",
                (0, i),
                xytext=(4, 0),
                textcoords="offset points",
                fontsize=8,
                color=_INK_2,
                va="center",
            )
    ax_ttt.set_yticks(ypos, labels)
    ax_ttt.invert_yaxis()
    _style_axes(ax_ttt)
    ax_ttt.grid(axis="y", visible=False)
    ax_ttt.set_xlabel(
        "simulated time to target loss (s)", fontsize=9, color=_INK
    )
    ax_ttt.margins(x=0.18)

    if title:
        fig.suptitle(title, fontsize=11, color=_INK)
    fig.tight_layout()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    fig.savefig(path, dpi=150, facecolor="white")
    plt.close(fig)
    return path


def save_sweep_figure(
    sweep: dict[str, Sequence], path: str, title: Optional[str] = None
) -> Optional[str]:
    """Time-to-target vs n_stragglers, one line per scheme — the
    BASELINE.json north-star curve."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception:
        return None

    fig, ax = plt.subplots(figsize=(6, 4))
    fig.patch.set_facecolor("white")
    ends = []
    for label, summaries in sweep.items():
        xs = [s.config.n_stragglers for s in summaries]
        ys = [
            s.time_to_target if s.time_to_target is not None else np.nan
            for s in summaries
        ]
        c = _color(summaries[0])
        ax.plot(xs, ys, color=c, linewidth=2, marker="o", markersize=5,
                zorder=3)
        ends.append((float(xs[-1]), float(ys[-1]), label))
    _end_labels(ax, ends)
    _style_axes(ax)
    ax.set_xlabel("injected stragglers s", fontsize=9, color=_INK)
    ax.set_ylabel("simulated time to target loss (s)", fontsize=9, color=_INK)
    ax.margins(x=0.15)
    ax.legend(list(sweep), frameon=False, fontsize=8, labelcolor=_INK)
    for line, (label, summaries) in zip(
        ax.get_legend().legend_handles, sweep.items()
    ):
        line.set_color(_color(summaries[0]))
    if title:
        ax.set_title(title, fontsize=11, color=_INK)
    fig.tight_layout()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    fig.savefig(path, dpi=150, facecolor="white")
    plt.close(fig)
    return path
