"""event-schema: every emit() call site matches obs/events.SCHEMA.

The event log's value is that its records can be trusted without running
the producer: the validator, the report renderer, the journal resume map
and the serve per-tenant accounting all key on SCHEMA's required fields.
Today a drifted emit site (a new record type, a renamed field) is caught
only at runtime by ``validate_lines`` — on whichever run first exercises
the site. This checker moves that to lint time, and cross-checks the
three schema surfaces against each other so a record type added to one
but not the others is a lint error, not a runtime surprise.

Rules:

  - **emit sites** (any module): for ``<events alias>.emit("type", ...)``
    and bare ``emit(...)`` imported from obs.events, the type string must
    be a SCHEMA key and every required field for that type must be among
    the keyword arguments (a ``**splat`` waives the field check — the
    payload is dynamic — but never the known-type check). For other
    ``*.emit(...)`` callees (logger objects), the same field check
    applies whenever the first argument is a SCHEMA type string.
  - **validator drift** (modules defining both ``SCHEMA`` and
    ``validate_lines``, i.e. obs/events.py and fixtures shaped like it):
    every record-type string literal the validator compares ``rtype``
    against must exist in that module's own SCHEMA — a per-type
    consistency check for a type SCHEMA doesn't declare is drift.
  - **CLI wrapper drift** (``tools/validate_events.py``): the wrapper
    must delegate to ``obs.events.validate_file``/``validate_lines`` and
    must not carry an independent record-type table (any dict literal
    with 2+ SCHEMA-type string keys) — the whole point of the shared
    validator is that the two can never drift.
  - **tune vocabulary** (ISSUE 19): an ``emit("tune", ...)`` site whose
    ``race``/``source`` keyword is a string constant must name a member
    of ``obs/events.TUNE_RACES``/``TUNE_SOURCES`` — the runtime
    validator's membership check, moved to lint time. And any module
    declaring the decision-plane's own vocabulary (a top-level
    ``TUNE_CHOICES`` dict, i.e. erasurehead_tpu/tune/__init__.py) must
    keep its keys equal to ``TUNE_RACES`` — a race added to the plane
    but not the schema (or vice versa) is drift, not a runtime surprise
    on the first resolved knob.
"""

from __future__ import annotations

import ast
import os

from erasurehead_tpu.analysis.core import Finding, SourceModule, dotted

CHECKER = "event-schema"


def parse_schema(source: str) -> dict:
    """type -> required-field tuple from an obs/events.py-shaped module
    (the top-level ``SCHEMA`` dict literal), parsed without importing."""
    tree = ast.parse(source)
    for node in tree.body:
        target = None
        if isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            target, value = node.target.id, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
        ):
            target, value = node.targets[0].id, node.value
        if target != "SCHEMA" or not isinstance(value, ast.Dict):
            continue
        schema = {}
        for key, val in zip(value.keys, value.values):
            if not (
                isinstance(key, ast.Constant) and isinstance(key.value, str)
            ):
                continue
            fields = tuple(
                e.value
                for e in getattr(val, "elts", [])
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
            schema[key.value] = fields
        return schema
    return {}


def parse_tune_vocab(source: str) -> tuple:
    """(TUNE_RACES, TUNE_SOURCES) string tuples from an obs/events.py-
    shaped module, parsed without importing; empty tuples when absent."""
    tree = ast.parse(source)
    vocab = {"TUNE_RACES": (), "TUNE_SOURCES": ()}
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id in vocab
        ):
            continue
        vocab[node.targets[0].id] = tuple(
            e.value
            for e in getattr(node.value, "elts", [])
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return vocab["TUNE_RACES"], vocab["TUNE_SOURCES"]


def _parse_tune_choices_keys(mod: SourceModule):
    """Keys of a top-level ``TUNE_CHOICES`` dict literal (the autotune
    plane's own race vocabulary), or None when the module has none."""
    for node in mod.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "TUNE_CHOICES"
            and isinstance(node.value, ast.Dict)
        ):
            keys = tuple(
                k.value
                for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            )
            return node, keys
    return None


def _module_defines_validator(mod: SourceModule) -> bool:
    return "validate_lines" in mod.module_scope.functions


def _emit_type(call: ast.Call):
    """The event-type argument when it is a string constant, else None."""
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    for kw in call.keywords:
        if kw.arg == "type" and isinstance(kw.value, ast.Constant) and (
            isinstance(kw.value.value, str)
        ):
            return kw.value.value
    return None


def _check_emit_sites(
    mod: SourceModule, schema: dict, findings: list, tune_vocab=((), ())
):
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if name is None:
            continue
        is_events_call = False
        if name == "emit":
            # a lexically-resolvable local helper named emit is not the
            # event sink (train/artifacts.py's artifact writer)
            if mod.module_scope.resolve_function("emit") is not None:
                continue
            is_events_call = mod.emit_is_events
            if not is_events_call:
                continue
        elif name.endswith(".emit"):
            base = name[: -len(".emit")]
            is_events_call = base in mod.events_aliases
        else:
            continue
        etype = _emit_type(node)
        if etype is None:
            continue  # dynamic type expression; runtime validation owns it
        if etype not in schema:
            if is_events_call:
                findings.append(
                    Finding(
                        CHECKER, mod.path, node.lineno, node.col_offset,
                        f"emit of unknown event type {etype!r}; "
                        "obs/events.SCHEMA declares "
                        f"{sorted(schema) if schema else 'no types'} — "
                        "add the type to SCHEMA first",
                    )
                )
            continue
        kwargs = {kw.arg for kw in node.keywords if kw.arg is not None}
        has_splat = any(kw.arg is None for kw in node.keywords)
        missing = [f for f in schema[etype] if f not in kwargs]
        if missing and not has_splat:
            findings.append(
                Finding(
                    CHECKER, mod.path, node.lineno, node.col_offset,
                    f"emit({etype!r}) missing required field(s) "
                    f"{missing}; SCHEMA declares {list(schema[etype])}",
                )
            )
        if etype == "tune":
            _check_tune_emit(mod, node, tune_vocab, findings)


def _check_tune_emit(
    mod: SourceModule, node: ast.Call, tune_vocab, findings: list
):
    """Constant ``race``/``source`` kwargs on a tune emit must be members
    of TUNE_RACES/TUNE_SOURCES — the validator's membership check at
    lint time (dynamic values stay runtime-validated)."""
    races, sources = tune_vocab
    for kw in node.keywords:
        if kw.arg not in ("race", "source") or not (
            isinstance(kw.value, ast.Constant)
            and isinstance(kw.value.value, str)
        ):
            continue
        vocab, table = (
            (races, "TUNE_RACES") if kw.arg == "race"
            else (sources, "TUNE_SOURCES")
        )
        if vocab and kw.value.value not in vocab:
            findings.append(
                Finding(
                    CHECKER, mod.path, kw.value.lineno,
                    kw.value.col_offset,
                    f"emit('tune') {kw.arg}={kw.value.value!r} is not in "
                    f"obs/events.{table} {list(vocab)} — extend the "
                    "vocabulary before emitting it",
                )
            )


def _check_tune_choices_drift(
    mod: SourceModule, tune_vocab, findings: list
):
    """A module declaring the autotune plane's TUNE_CHOICES must keep its
    keys equal to obs/events.TUNE_RACES — the two vocabulary surfaces
    (decision plane and event schema) may never drift."""
    races, _ = tune_vocab
    if not races:
        return
    parsed = _parse_tune_choices_keys(mod)
    if parsed is None:
        return
    node, keys = parsed
    if set(keys) != set(races):
        findings.append(
            Finding(
                CHECKER, mod.path, node.lineno, node.col_offset,
                f"TUNE_CHOICES races {sorted(keys)} != obs/events."
                f"TUNE_RACES {sorted(races)} — the decision plane and "
                "the event schema declare different race vocabularies",
            )
        )


def _check_validator_drift(mod: SourceModule, findings: list):
    own_schema = parse_schema(mod.source)
    if not own_schema:
        return
    validator = mod.module_scope.functions.get("validate_lines")
    if validator is None:
        return
    for node in ast.walk(validator):
        if not isinstance(node, ast.Compare):
            continue
        sides = [node.left] + list(node.comparators)
        if not any(
            isinstance(s, ast.Name) and s.id == "rtype" for s in sides
        ):
            continue
        for side in sides:
            literals = (
                [side]
                if isinstance(side, ast.Constant)
                else list(getattr(side, "elts", []))
            )
            for lit in literals:
                if isinstance(lit, ast.Constant) and isinstance(
                    lit.value, str
                ) and lit.value not in own_schema:
                    findings.append(
                        Finding(
                            CHECKER, mod.path, lit.lineno, lit.col_offset,
                            f"validate_lines checks record type "
                            f"{lit.value!r} which SCHEMA does not declare "
                            "— schema/validator drift",
                        )
                    )


def _check_cli_wrapper(mod: SourceModule, schema: dict, findings: list):
    if os.path.basename(mod.path) != "validate_events.py":
        return
    delegates = any(
        isinstance(node, (ast.Name, ast.Attribute))
        and (
            getattr(node, "id", None) in ("validate_file", "validate_lines")
            or getattr(node, "attr", None)
            in ("validate_file", "validate_lines")
        )
        for node in ast.walk(mod.tree)
    )
    if not delegates:
        findings.append(
            Finding(
                CHECKER, mod.path, 1, 0,
                "validate_events.py does not delegate to obs.events."
                "validate_file/validate_lines; an independent validator "
                "drifts from SCHEMA",
            )
        )
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Dict):
            type_keys = [
                k.value
                for k in node.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and k.value in schema
            ]
            if len(type_keys) >= 2:
                findings.append(
                    Finding(
                        CHECKER, mod.path, node.lineno, node.col_offset,
                        f"independent record-type table {sorted(type_keys)} "
                        "in the CLI wrapper; the schema lives in "
                        "obs/events.SCHEMA only",
                    )
                )


def check(mod: SourceModule, context) -> list:
    findings: list = []
    own_schema = parse_schema(mod.source)
    schema = own_schema or context.schema
    tune_vocab = (
        parse_tune_vocab(mod.source)
        if own_schema
        else (context.tune_races, context.tune_sources)
    )
    if schema:
        _check_emit_sites(mod, schema, findings, tune_vocab)
    _check_validator_drift(mod, findings)
    _check_tune_choices_drift(mod, tune_vocab, findings)
    if context.schema:
        _check_cli_wrapper(mod, context.schema, findings)
    return findings
