"""donation-safety: donated buffers are never read after the donating call.

The PR 6 ``_donate_copy`` bug class: ``jax.jit(fn, donate_argnums=...)``
lets XLA reuse the donated argument's HBM in place — after the call the
original array is INVALID. Reading it afterwards raises a
RuntimeError on real hardware but can silently *work* on CPU backends,
so the bug ships green from a CPU-only tier-1 run. Every warm-up path in
the trainers feeds ``_donate_copy(...)`` clones for exactly this reason.

The checker tracks, per function scope:

  - names bound to ``jax.jit(..., donate_argnums=...)`` results (the
    donated positions are the union of integer constants inside the
    ``donate_argnums`` expression — a conditional like ``(0, 4) if donate
    else ()`` is treated as donating, the conservative reading);
  - names bound to AOT chains off those (``ex =
    run.lower(...).compile()``), which execute with the same aliasing;

then flags any donating call whose argument at a donated position is a
plain name that is READ again later in the same function body without an
intervening rebind. Arguments that are expressions (``_donate_copy(x)``,
slices, constructor calls) produce fresh values per call and are skipped;
assignment targets of the donating call itself count as rebinds
(``state, hist = run(state, ...)`` is the sanctioned consume-and-replace
idiom).

Static limits, stated honestly: executables that travel through
factories or caches (``cache_lib.get_or_compile``) are not tracked, and
loop-carried reads that textually precede the call are not seen. The
checker is a tripwire for the direct patterns — the ones the PR 6
regression actually shipped.
"""

from __future__ import annotations

import ast

from erasurehead_tpu.analysis.core import (
    Finding,
    SourceModule,
    dotted,
    walk_own,
)
from erasurehead_tpu.analysis.core import JIT_NAMES

CHECKER = "donation-safety"


def _donated_positions(call: ast.Call):
    """The union of integer constants inside this jit call's
    ``donate_argnums`` value, or None when it doesn't donate."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        nums = sorted(
            {
                n.value
                for n in ast.walk(kw.value)
                if isinstance(n, ast.Constant) and isinstance(n.value, int)
                and not isinstance(n.value, bool)
            }
        )
        if nums:
            return tuple(nums)
    return None


def _is_jit_call(call: ast.Call) -> bool:
    return dotted(call.func) in JIT_NAMES


def _assign_single_name(stmt):
    """The bound name of ``name = <expr>`` (plain single-target), else
    None."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
        isinstance(stmt.targets[0], ast.Name)
    ):
        return stmt.targets[0].id
    return None


def _stmt_store_names(stmt) -> set:
    """Every name the statement (re)binds."""
    return {
        n.id
        for n in ast.walk(stmt)
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del))
    }


def _check_scope(mod: SourceModule, fn, findings: list) -> None:
    """Analyze one function (or module) body."""
    donating: dict = {}  # name -> donated positions

    # pass 1: donating bindings — direct jit results and AOT chains
    # (in source-line order, so `ex = run.lower(...).compile()` sees the
    # earlier `run = jax.jit(...)` bind)
    assigns = sorted(
        (node for node in walk_own(fn) if _assign_single_name(node)),
        key=lambda n: n.lineno,
    )
    for node in assigns:
        name = _assign_single_name(node)
        value = node.value
        if isinstance(value, ast.Call) and _is_jit_call(value):
            pos = _donated_positions(value)
            if pos:
                donating[name] = pos
        elif isinstance(value, ast.Call):
            # ex = run.lower(...).compile() — same aliasing at execution
            # (dotted renders the chain as "run.lower().compile")
            chain = dotted(value.func) or ""
            root = chain.split(".", 1)[0]
            if root in donating and chain.endswith(".compile") and (
                ".lower()" in chain
            ):
                donating[name] = donating[root]

    if not donating:
        return

    # pass 2: donating call sites + later reads of donated names
    body_nodes = list(walk_own(fn))
    for node in body_nodes:
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Name
        ):
            continue
        pos = donating.get(node.func.id)
        if not pos:
            continue
        for p in pos:
            if p >= len(node.args):
                continue
            arg = node.args[p]
            if not isinstance(arg, ast.Name):
                continue  # fresh expression per call (copy/slice/ctor)
            _flag_late_reads(mod, fn, node, arg.id, p, findings)


def _flag_late_reads(mod, fn, call, name, position, findings):
    """Is ``name`` loaded after ``call`` without an intervening rebind?"""
    call_line = call.lineno
    rebind_lines = []
    for node in walk_own(fn):
        # statements that rebind the name (including the donating call's
        # own assignment targets — the consume-and-replace idiom)
        if isinstance(
            node, (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.For)
        ) and name in _stmt_store_names(node):
            rebind_lines.append(node.lineno)
    for node in walk_own(fn):
        if (
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Load)
            and node.lineno > call_line
        ):
            rebound = any(
                call_line <= rl <= node.lineno for rl in rebind_lines
            )
            if not rebound:
                findings.append(
                    Finding(
                        CHECKER, mod.path, node.lineno, node.col_offset,
                        f"{name!r} is read after being donated at "
                        f"position {position} of the jitted call on line "
                        f"{call_line}; donated buffers are invalid after "
                        "the call — pass a copy (_donate_copy) or rebind "
                        "from the result",
                    )
                )
                return  # one finding per donated arg is enough


def check(mod: SourceModule, context) -> list:
    findings: list = []
    seen = set()
    scopes = [mod.tree] + [
        node
        for node in ast.walk(mod.tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for fn in scopes:
        if id(fn) not in seen:
            seen.add(id(fn))
            _check_scope(mod, fn, findings)
    return findings
