"""Shared AST infrastructure for the `erasurehead-tpu lint` checkers.

The framework's correctness rests on a handful of contracts that no type
system sees: jitted closures must not read config fields outside the
executable-cache signature (the PR 2 exec-cache-collision class), telemetry
emission must stay host-side and outside jit (the PR 3 observation-only
contract), scheme dispatch must go through the registry (PR 8), event
payloads must match obs/events.SCHEMA, and donated buffers must not be read
after the donating call (the PR 6 ``_donate_copy`` class). Each checker in
this package enforces one of those contracts by walking module ASTs — no
imports of the checked code, no jax, so the whole tree lints in well under
a second and rides inside the tier-1 loop.

This module provides what every checker needs:

  - :class:`SourceModule` — one parsed file: AST, lexical scopes
    (module / class / function) with statement-level def indexing, import
    aliases, and suppression comments;
  - traced-call-graph resolution (:func:`traced_functions`) — find the
    function bodies passed to ``jax.jit`` / ``lax.scan`` / ``shard_map``
    (as arguments, decorators, or through ``partial``) and the local
    functions reachable from them by direct call;
  - :func:`dotted` — render a callee/attribute chain as a dotted string
    ("obs_events.emit", "REGISTRY.counter().inc") for pattern matching;
  - suppression handling — ``# lint: allow(<checker>): <reason>`` on (or
    directly above) a line, ``# lint: allow-file(<checker>): <reason>``
    anywhere for the whole file. A suppression without a reason string is
    itself a finding: every whitelisted exception must say why.

Static resolution is deliberately conservative: a callee that is a local
``def`` (or a ``self.`` method of the enclosing class) is followed;
callables passed in as VALUES (``grad_fn`` arguments, closures bound by
assignment) are not — the factories that build them register their own
``shard_map``/``jit`` entries, so their bodies are still covered where
they are defined.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Iterable, Iterator, Optional

#: callables whose first argument (or decorated function) becomes a traced
#: computation — the roots of the traced call graph
JIT_NAMES = frozenset({"jax.jit", "jit", "pjit", "jax.pjit"})
SCAN_NAMES = frozenset({"jax.lax.scan", "lax.scan"})
SHARD_MAP_NAMES = frozenset(
    {"shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map"}
)
TRACING_NAMES = JIT_NAMES | SCAN_NAMES | SHARD_MAP_NAMES
PARTIAL_NAMES = frozenset({"partial", "functools.partial"})


@dataclasses.dataclass(frozen=True)
class Finding:
    """One checker hit. Sort order = report order (deterministic)."""

    checker: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    suppress_reason: Optional[str] = None

    def sort_key(self):
        return (self.path, self.line, self.col, self.checker, self.message)

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"[{self.checker}]{tag} {self.message}"
        )


#: suppression comment grammar (module docstring). The reason after ":" is
#: REQUIRED — an unexplained whitelist entry is a finding of its own.
_ALLOW_RE = re.compile(
    r"#\s*lint:\s*allow(?P<scope>-file)?\(\s*(?P<checker>[A-Za-z0-9_-]+)\s*\)"
    r"(?:\s*:\s*(?P<reason>\S.*?))?\s*$"
)


@dataclasses.dataclass
class Suppressions:
    """Parsed ``# lint: allow(...)`` comments of one file."""

    #: checker -> (line, reason) of a file-wide allow
    file_allows: dict
    #: (line, checker) -> reason; a comment-only line also covers line + 1
    line_allows: dict
    #: malformed / reason-less suppression comments -> Finding list
    problems: list

    def lookup(self, checker: str, line: int):
        """(suppressed?, reason) for a finding of ``checker`` at ``line``."""
        if checker in self.file_allows:
            return True, self.file_allows[checker][1]
        for ln in (line, line - 1):
            reason = self.line_allows.get((ln, checker))
            if reason is not None:
                return True, reason
        return False, None


class Scope:
    """One lexical scope: module, class body, or function body.

    ``functions``/``classes`` index statement-level defs (including defs
    nested inside if/for/while/with/try blocks, which are still
    statement-level bindings at runtime)."""

    def __init__(self, node, parent: Optional["Scope"]):
        self.node = node
        self.parent = parent
        self.functions: dict = {}
        self.classes: dict = {}
        #: name -> value expr of statement-level ``name = <expr>`` binds
        #: (callable-tracking only: lambdas, factory calls, aliases)
        self.assigns: dict = {}

    def is_class(self) -> bool:
        return isinstance(self.node, ast.ClassDef)

    def resolve_function(self, name: str):
        """Resolve a bare callee name lexically. Class scopes are skipped
        (Python name resolution skips them; methods need ``self.``)."""
        scope = self
        while scope is not None:
            if not scope.is_class() and name in scope.functions:
                return scope.functions[name]
            scope = scope.parent
        return None

    def resolve_method(self, name: str):
        """Resolve ``self.<name>`` against the nearest enclosing class."""
        scope = self
        while scope is not None:
            if scope.is_class():
                return scope.functions.get(name)
            scope = scope.parent
        return None

    def nearest_function_scope(self) -> Optional["Scope"]:
        scope = self
        while scope is not None and not isinstance(
            scope.node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            scope = scope.parent
        return scope


def _index_statements(body, scope: Scope) -> None:
    """Register statement-level function/class defs of ``body`` into
    ``scope``, descending into compound statements but not into nested
    function/class bodies (those open their own scopes)."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope.functions[stmt.name] = stmt
        elif isinstance(stmt, ast.ClassDef):
            scope.classes[stmt.name] = stmt
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
            isinstance(stmt.targets[0], ast.Name)
        ):
            scope.assigns[stmt.targets[0].id] = stmt.value
        elif isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            _index_statements(stmt.body, scope)
            _index_statements(stmt.orelse, scope)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            _index_statements(stmt.body, scope)
        elif isinstance(stmt, ast.Try):
            _index_statements(stmt.body, scope)
            for handler in stmt.handlers:
                _index_statements(handler.body, scope)
            _index_statements(stmt.orelse, scope)
            _index_statements(stmt.finalbody, scope)


def dotted(node) -> Optional[str]:
    """Render a Name/Attribute/Call chain as a dotted string, or None.

    Calls in the middle of a chain render as ``()``:
    ``REGISTRY.counter("x").inc`` -> ``"REGISTRY.counter().inc"`` — so
    suffix patterns like ``.inc`` still match through builder chains."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return None if base is None else f"{base}.{node.attr}"
    if isinstance(node, ast.Call):
        base = dotted(node.func)
        return None if base is None else f"{base}()"
    return None


def walk_own(node) -> Iterator[ast.AST]:
    """Yield ``node`` and descendants, NOT descending into nested
    function/class definitions (they are separate traced-or-not units);
    lambdas ARE descended into (an inline lambda in a traced body runs
    traced)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            stack.append(child)


class SourceModule:
    """One parsed source file plus the derived indexes checkers share."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.module_scope = Scope(self.tree, None)
        #: ast function/class node -> its own Scope
        self.scopes: dict = {id(self.tree): self.module_scope}
        #: function node -> the Scope it was DEFINED in (for resolution)
        self.def_scope: dict = {}
        self._build_scopes(self.tree, self.module_scope)
        self.events_aliases, self.imported_modules, self.emit_is_events = (
            self._scan_imports()
        )
        self.suppressions = parse_suppressions(path, source)
        self._traced = None

    # ---- scopes ----------------------------------------------------------

    def _build_scopes(self, node, scope: Scope) -> None:
        body = getattr(node, "body", None)
        if isinstance(body, list):
            _index_statements(body, scope)
        for fn in list(scope.functions.values()) + list(
            scope.classes.values()
        ):
            child = Scope(fn, scope)
            self.scopes[id(fn)] = child
            self.def_scope[id(fn)] = scope
            self._build_scopes(fn, child)

    def scope_of(self, fn_node) -> Scope:
        return self.scopes.get(id(fn_node), self.module_scope)

    # ---- imports ---------------------------------------------------------

    def _scan_imports(self):
        """(events-module aliases, top-level imported module names,
        bare-``emit``-is-events?) — the schema checker's resolution inputs
        and the purity checker's stdlib-``random`` disambiguator."""
        events_aliases = set()
        modules = set()
        emit_is_events = False
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    modules.add(alias.asname or alias.name.split(".")[0])
                    if alias.name == "erasurehead_tpu.obs.events":
                        events_aliases.add(alias.asname or "erasurehead_tpu")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if mod.endswith("obs") and alias.name == "events":
                        events_aliases.add(bound)
                    if mod.endswith("obs.events") and alias.name == "emit":
                        emit_is_events = True
        return events_aliases, modules, emit_is_events

    # ---- traced call graph ----------------------------------------------

    def traced_functions(self) -> dict:
        """Map of traced function/lambda nodes -> entry description.

        Roots: callables passed to jit/scan/shard_map (directly or through
        ``partial``) and functions decorated with jit (bare, called, or
        partial-wrapped). From each root, local functions reachable by
        direct call (bare name or ``self.`` method) are traced too."""
        if self._traced is not None:
            return self._traced
        roots: dict = {}

        def note(target, scope, why):
            for fn in self.callable_defs(target, scope):
                roots.setdefault(id(fn), (fn, why))

        def visit(node, scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_expr(dec):
                        roots.setdefault(
                            id(node),
                            (node, f"@{dotted(dec) or 'jit'} line {node.lineno}"),
                        )
                scope = self.scope_of(node)
            elif isinstance(node, ast.Lambda):
                fn_scope = Scope(node, scope)
                self.scopes[id(node)] = fn_scope
                scope = fn_scope
            elif isinstance(node, ast.Call):
                name = dotted(node.func)
                if name in TRACING_NAMES and node.args:
                    note(node.args[0], scope, f"{name} line {node.lineno}")
            for child in ast.iter_child_nodes(node):
                visit(child, scope)

        visit(self.tree, self.module_scope)

        # transitive closure over locally-resolvable calls
        traced: dict = {}
        queue = list(roots.values())
        while queue:
            fn, why = queue.pop()
            if id(fn) in traced:
                continue
            traced[id(fn)] = (fn, why)
            scope = self.scope_of(fn)
            for node in walk_own(fn):
                if not isinstance(node, ast.Call):
                    continue
                for callee in self.call_targets(node, scope):
                    if id(callee) not in traced:
                        queue.append((callee, why))
        self._traced = traced
        return traced

    # ---- callable resolution ---------------------------------------------

    def callable_defs(self, expr, scope: Scope, _seen=None) -> list:
        """Resolve a callable EXPRESSION to the local function/lambda
        definitions it may denote. Follows: bare names (defs, and simple
        ``name = <expr>`` rebinds), ``self.`` methods, ``partial(f, ...)``,
        ``a or b`` / ternary alternatives, and — the factory idiom the
        step/trainer modules are built on — CALLS of local factories,
        resolving to whatever the factory ``return``s plus any callable
        arguments threaded through it (``shard_map(_dq(_body(model)))``
        traces the wrapper AND the wrapped body)."""
        if _seen is None:
            _seen = set()
        key = id(expr)
        if key in _seen or expr is None:
            return []
        _seen.add(key)
        if isinstance(expr, ast.Lambda):
            return [expr]
        if isinstance(expr, ast.Name):
            fn = scope.resolve_function(expr.id)
            if fn is not None:
                return [fn]
            # simple value bind: follow the bound expression lexically
            s = scope
            while s is not None:
                if not s.is_class() and expr.id in s.assigns:
                    return self.callable_defs(
                        s.assigns[expr.id], s, _seen
                    )
                s = s.parent
            return []
        if isinstance(expr, ast.Attribute):
            if dotted(expr.value) == "self":
                fn = scope.resolve_method(expr.attr)
                return [fn] if fn is not None else []
            return []
        if isinstance(expr, ast.BoolOp):
            out = []
            for v in expr.values:
                out += self.callable_defs(v, scope, _seen)
            return out
        if isinstance(expr, ast.IfExp):
            return self.callable_defs(
                expr.body, scope, _seen
            ) + self.callable_defs(expr.orelse, scope, _seen)
        if isinstance(expr, ast.Call):
            fname = dotted(expr.func)
            if fname in PARTIAL_NAMES and expr.args:
                return self.callable_defs(expr.args[0], scope, _seen)
            out = []
            factories = self.callable_defs(expr.func, scope, set(_seen))
            for factory in factories:
                fscope = self.scope_of(factory)
                for node in walk_own(factory):
                    if isinstance(node, ast.Return) and node.value is not None:
                        out += self.callable_defs(node.value, fscope, _seen)
            # callables threaded through the factory's arguments are part
            # of the traced graph too (wrapper factories like _dq)
            if factories or fname in PARTIAL_NAMES:
                for arg in expr.args:
                    out += self.callable_defs(arg, scope, _seen)
            return out
        return []

    def call_targets(self, call: ast.Call, scope: Scope) -> list:
        """Locally-resolvable defs this Call may invoke (reachability
        step): the callee itself plus partial-forwarded callables. The
        callee being a factory CALL is handled by callable_defs."""
        targets = []
        if isinstance(call.func, (ast.Name, ast.Attribute)):
            targets += self.callable_defs(call.func, scope)
        fname = dotted(call.func)
        if fname in PARTIAL_NAMES and call.args:
            targets += self.callable_defs(call.args[0], scope)
        return targets


def _is_jit_expr(expr) -> bool:
    """Is this decorator/callee expression a jit wrapper? Covers
    ``jax.jit``, ``jit``, ``jax.jit(...)`` and ``partial(jax.jit, ...)``."""
    name = dotted(expr)
    if name in JIT_NAMES:
        return True
    if isinstance(expr, ast.Call):
        fname = dotted(expr.func)
        if fname in JIT_NAMES:
            return True
        if fname in PARTIAL_NAMES and expr.args:
            return dotted(expr.args[0]) in JIT_NAMES
    return False


def parse_suppressions(path: str, source: str) -> Suppressions:
    """Extract ``# lint: allow(...)`` comments via the tokenizer (so
    string literals containing the pattern are never misread)."""
    file_allows: dict = {}
    line_allows: dict = {}
    problems: list = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError):  # pragma: no cover
        tokens = []
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        text = tok.string
        if "lint:" not in text:
            continue
        m = _ALLOW_RE.search(text)
        line = tok.start[0]
        if m is None:
            problems.append(
                Finding(
                    "suppression", path, line, tok.start[1],
                    "malformed lint suppression comment; want "
                    "'# lint: allow(<checker>): <reason>' or "
                    "'# lint: allow-file(<checker>): <reason>'",
                )
            )
            continue
        checker, reason = m.group("checker"), m.group("reason")
        if not reason:
            problems.append(
                Finding(
                    "suppression", path, line, tok.start[1],
                    f"suppression allow({checker}) has no reason string; "
                    "every whitelisted exception must say why",
                )
            )
            reason = "<no reason given>"
        if m.group("scope"):
            file_allows.setdefault(checker, (line, reason))
        else:
            line_allows[(line, checker)] = reason
            # a comment-only line suppresses the line below it
            if text.strip() == tok.line.strip():
                line_allows.setdefault((line + 1, checker), reason)
    return Suppressions(file_allows, line_allows, problems)


def apply_suppressions(
    findings: Iterable[Finding], modules: dict
) -> list:
    """Mark findings suppressed per their file's allow comments and append
    the suppression-hygiene problems; returns a sorted list."""
    out = []
    for f in findings:
        mod = modules.get(f.path)
        if mod is not None:
            ok, reason = mod.suppressions.lookup(f.checker, f.line)
            if ok:
                f = dataclasses.replace(
                    f, suppressed=True, suppress_reason=reason
                )
        out.append(f)
    for mod in modules.values():
        out.extend(mod.suppressions.problems)
    return sorted(out, key=Finding.sort_key)
