"""registry-dispatch: scheme behavior lives in the registry, nowhere else.

PR 8 replaced the if/elif scheme spine with the declarative registry
(erasurehead_tpu/schemes/): a scheme is one SchemeDescriptor, and every
consumer — trainer, collection, failures, CLI, serve packing — looks
behavior up via ``schemes.get()``. The old guard was a grep for
``if ... scheme ==`` lines (tests/test_schemes.py), which misses every
other dispatch form; this checker is the AST-grade replacement.

Outside ``erasurehead_tpu/schemes/``, flags:

  - **comparison dispatch** — ``scheme``-valued expressions (``scheme``,
    ``cfg.scheme``, ``arm.scheme``, ``...scheme.value``) compared with
    ``==``/``!=``/``in``/``not in`` against hard-coded values (string
    constants or ``Scheme.<MEMBER>`` attributes), in ANY expression
    position: if/elif, ternaries, comprehension filters, boolean
    operands, assert conditions — the forms the old grep missed.
    Comparing two scheme VALUES (``a.scheme == b.scheme``) is not
    dispatch and stays legal (cohort-compatibility checks).
  - **dict-keyed dispatch** — subscripting with a scheme-valued key
    (``TABLE[cfg.scheme.value]``): a lookup table is an if/elif spine in
    data clothing, and one that silently KeyErrors for every scheme
    registered after it was written.
  - **match dispatch** — ``match scheme:`` with constant-valued cases.

Capability queries through the registry (``schemes.get(s).partial``) are
the sanctioned replacement and are untouched.
"""

from __future__ import annotations

import ast

from erasurehead_tpu.analysis.core import Finding, SourceModule, dotted

CHECKER = "registry-dispatch"

_OPS = (ast.Eq, ast.NotEq, ast.In, ast.NotIn)


def _scheme_valued(expr) -> bool:
    """Does this expression carry a scheme value? ``scheme``,
    ``*.scheme``, and either with a trailing ``.value``."""
    name = dotted(expr)
    if name is None:
        return False
    parts = name.split(".")
    if parts[-1] == "value" and len(parts) > 1:
        parts = parts[:-1]
    return parts[-1] == "scheme"


def _hardcoded(expr) -> bool:
    """A hard-coded scheme label: a string constant, a tuple/list/set of
    them, or a ``Scheme.<MEMBER>`` enum attribute."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return True
    if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
        return any(_hardcoded(e) for e in expr.elts)
    name = dotted(expr)
    return name is not None and "Scheme." in f".{name}."


def check(mod: SourceModule, context) -> list:
    if "/schemes/" in mod.path.replace("\\", "/"):
        return []
    findings = []

    def flag(node, what):
        findings.append(
            Finding(
                CHECKER,
                mod.path,
                node.lineno,
                node.col_offset,
                f"{what} outside erasurehead_tpu/schemes/; scheme behavior "
                "belongs on its SchemeDescriptor (schemes.get(...))",
            )
        )

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if any(_scheme_valued(s) for s in sides) and any(
                _hardcoded(s) for s in sides
            ) and any(isinstance(op, _OPS) for op in node.ops):
                flag(node, "hard-coded scheme comparison")
        elif isinstance(node, ast.Subscript) and _scheme_valued(node.slice):
            flag(node, "dict-keyed scheme dispatch")
        elif isinstance(node, ast.Match) and _scheme_valued(node.subject):
            if any(
                isinstance(p, ast.MatchValue) and _hardcoded(p.value)
                for case in node.cases
                for p in ast.walk(case.pattern)
            ):
                flag(node, "match-statement scheme dispatch")
    return findings
