"""trace-purity: no host effects reachable from traced function bodies.

The PR 3 observation-only contract: telemetry emission (events, metrics
counters) is strictly host-side and outside jit, and traced code — function
bodies passed to ``jax.jit`` / ``lax.scan`` / ``shard_map`` — must be pure
(same trace, same program, bitwise-reproducible trajectories). A host
effect inside a traced body is at best silently frozen into the compiled
program at trace time (``time.time()`` becomes a constant; ``np.random``
draws once and bakes the sample in) and at worst breaks the
bitwise-reproducibility pin the whole sweep engine keys on.

Flags, inside the traced call graph (core.traced_functions):

  - event emission: any ``*.emit(...)`` call, and bare ``emit(...)`` when
    the module imports it from obs.events;
  - metrics mutation: ``*.inc(...)`` / ``*.observe(...)`` (the
    obs/metrics counter-and-histogram surface; ``.set`` is excluded —
    ``x.at[i].set(v)`` is the jax functional-update idiom);
  - host clocks: ``time.time/perf_counter/monotonic/process_time/sleep``;
  - host randomness: ``np.random.*`` / ``numpy.random.*`` (and stdlib
    ``random.*`` when the module imports ``random`` — ``jax.random`` stays
    legal, it is traced-pure by design);
  - console/file I/O: ``print``, ``open``, ``input``, ``breakpoint``,
    ``sys.stdout/stderr.write``, ``os.remove/rename/makedirs/unlink``.
"""

from __future__ import annotations

import ast

from erasurehead_tpu.analysis.core import Finding, SourceModule, dotted, walk_own

CHECKER = "trace-purity"

_BARE_CALLS = frozenset({"print", "open", "input", "breakpoint"})
_EXACT_DOTTED = frozenset(
    {
        "time.time",
        "time.perf_counter",
        "time.monotonic",
        "time.process_time",
        "time.sleep",
        "sys.stdout.write",
        "sys.stderr.write",
        "os.remove",
        "os.rename",
        "os.makedirs",
        "os.unlink",
        "os.open",
    }
)
_NUMPY_RANDOM_PREFIXES = ("np.random.", "numpy.random.")
_EFFECT_SUFFIXES = (".emit", ".inc", ".observe")


def _effect(name: str, mod: SourceModule) -> str | None:
    """A short label when ``name`` is a host effect, else None."""
    if name in _BARE_CALLS:
        return f"host I/O call {name}()"
    if name in _EXACT_DOTTED:
        return f"host call {name}()"
    if name.startswith(_NUMPY_RANDOM_PREFIXES):
        return f"host RNG {name}() (use jax.random inside traced code)"
    if name.startswith("random.") and "random" in mod.imported_modules:
        return f"host RNG {name}()"
    if name == "emit" and mod.emit_is_events:
        return "event emission emit()"
    for suffix in _EFFECT_SUFFIXES:
        if name.endswith(suffix):
            kind = (
                "event emission"
                if suffix == ".emit"
                else "metrics mutation"
            )
            return f"{kind} {name}()"
    return None


def check(mod: SourceModule, context) -> list:
    findings = []
    for fn, why in mod.traced_functions().values():
        scope = mod.scope_of(fn)
        for node in walk_own(fn):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None:
                continue
            if name == "emit" and scope.resolve_function("emit") is not None:
                continue  # a local helper def named emit, not the event sink
            label = _effect(name, mod)
            if label is not None:
                findings.append(
                    Finding(
                        CHECKER,
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"{label} inside traced code (traced via {why}); "
                        "host effects must stay outside jit/scan/shard_map",
                    )
                )
    return findings
