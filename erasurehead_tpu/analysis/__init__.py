"""Static analysis for the framework's trace/cache/telemetry contracts.

``erasurehead-tpu lint [paths]`` (or ``python -m
erasurehead_tpu.analysis``) runs five AST checkers over the tree — no
imports of the checked code, no jax, sub-second on the full package:

  =======================  ==============================================
  checker                  contract enforced
  =======================  ==============================================
  trace-purity             no host effects (emit, metrics, clocks, host
                           RNG, print/file I/O) reachable from bodies
                           traced by jit / lax.scan / shard_map (the PR 3
                           observation-only contract)
  signature-completeness   every RunConfig field a jitted closure reads
                           is in static_signature_fields() — the PR 2
                           exec-cache-collision class
  registry-dispatch        no hard-coded scheme comparisons, lookup
                           tables, or match-dispatch outside
                           erasurehead_tpu/schemes/ (the PR 8 registry
                           contract; AST-grade successor of the grep
                           test)
  event-schema             every emit() call site carries the fields
                           obs/events.SCHEMA requires; SCHEMA, the
                           validator, and tools/validate_events.py
                           cannot drift apart
  donation-safety          values at donate_argnums positions are never
                           read after the donating call (the PR 6
                           _donate_copy class)
  =======================  ==============================================

Violations fail tier-1 (tests/test_analysis.py pins the shipped tree at
zero unsuppressed findings). Intentional exceptions are whitelisted in
place with ``# lint: allow(<checker>): <reason>`` (line) or ``# lint:
allow-file(<checker>): <reason>`` (file); a suppression without a reason
is itself a finding, and ``lint --strict`` reports suppression counts
per checker.
"""

from erasurehead_tpu.analysis.core import Finding, SourceModule  # noqa: F401
from erasurehead_tpu.analysis.runner import (  # noqa: F401
    CHECKERS,
    LintContext,
    LintReport,
    lint_paths,
    main,
)
