"""signature-completeness: jitted closures read only signature-keyed cfg.

The PR 2 bug class: the sweep engine's executable cache
(train/cache.py) keys compiled programs on
``RunConfig.static_signature()`` plus argument shapes/dtypes. A jitted
closure that reads a config field NOT in that signature bakes the field's
current value into the compiled program as a constant — and a later run
with a different value silently *hits the cache* and executes the stale
program (a real exec-cache collision was found exactly this way when the
ring transport landed). The recompile detector (obs/detect.py) can only
name knobs the signature carries.

The checker resolves the ``RunConfig`` dataclass field set and the
``static_signature_fields()`` key set from utils/config.py BY AST (no
import, no jax), then flags every ``cfg.<field>`` / ``self.cfg.<field>``
attribute read inside the traced call graph where ``<field>`` is a config
field missing from the signature.

Fields whose value is fully determined by traced ARGUMENT shapes are
exempt (:data:`SHAPE_CAPTURED`): ``rounds`` shows up as the schedule
length, ``n_rows``/``n_cols`` as the data stack shape, ``n_workers`` as
the mesh — a changed value changes the shapes and re-keys the cache by
construction. Value-like fields (``num_collect``, ``deadline``,
``delay_mean``, ...) get no such free ride: reading one inside a traced
body without a signature entry is exactly the collision class.
"""

from __future__ import annotations

import ast

from erasurehead_tpu.analysis.core import Finding, SourceModule, dotted, walk_own

CHECKER = "signature-completeness"

#: attribute-chain bases treated as a RunConfig value inside closures
CONFIG_BASES = frozenset(
    {"cfg", "config", "run_config", "arm_cfg", "self.cfg", "self.config"}
)

#: config fields captured by traced-argument SHAPES (see module docstring);
#: everything else must be in static_signature_fields() to be read traced
SHAPE_CAPTURED = frozenset(
    {"rounds", "n_rows", "n_cols", "n_workers", "partitions_per_worker"}
)


def parse_config_info(source: str):
    """(dataclass field names, static-signature keys) from utils/config.py
    source. Fields = annotated assignments in ``class RunConfig``; keys =
    string keys of the dict literal returned by
    ``static_signature_fields``. Parsed, not imported — the linter never
    executes the code it checks."""
    tree = ast.parse(source)
    fields: set = set()
    keys: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "RunConfig":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    fields.add(stmt.target.id)
                if (
                    isinstance(stmt, ast.FunctionDef)
                    and stmt.name == "static_signature_fields"
                ):
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Dict):
                            for key in sub.keys:
                                if isinstance(
                                    key, ast.Constant
                                ) and isinstance(key.value, str):
                                    keys.add(key.value)
    return fields, keys


def check(mod: SourceModule, context) -> list:
    fields = context.config_fields
    keys = context.signature_keys
    if not fields or not keys:
        return []
    findings = []
    for fn, why in mod.traced_functions().values():
        for node in walk_own(fn):
            if not isinstance(node, ast.Attribute) or not isinstance(
                node.ctx, ast.Load
            ):
                continue
            base = dotted(node.value)
            if base not in CONFIG_BASES:
                continue
            attr = node.attr
            if attr in fields and attr not in keys and attr not in SHAPE_CAPTURED:
                findings.append(
                    Finding(
                        CHECKER,
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"traced closure (via {why}) reads {base}.{attr}, "
                        "which is not in RunConfig."
                        "static_signature_fields(); the executable cache "
                        "cannot key on it — add it to the signature or "
                        "pass it as a traced argument",
                    )
                )
    return findings
