"""`erasurehead-tpu lint` driver: load files, run checkers, render.

Deterministic by construction (the tests pin it byte-for-byte): files are
walked in sorted order, findings sort on (path, line, col, checker,
message), and the report carries no timestamps — wall time goes to
stderr only. Pure stdlib + AST: no jax import anywhere on this path, so
the full tree lints in well under the 5 s tier-1 budget
(bench.py's ``lint`` extra measures it).
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Iterable, Optional

from erasurehead_tpu.analysis import (
    dispatch,
    donation,
    purity,
    schema,
    signature,
)
from erasurehead_tpu.analysis.core import (
    Finding,
    SourceModule,
    apply_suppressions,
)

#: checker name -> check(module, context) -> [Finding]; registration order
#: is stable but reports sort findings, so order never shows
CHECKERS = {
    purity.CHECKER: purity.check,
    signature.CHECKER: signature.check,
    dispatch.CHECKER: dispatch.check,
    schema.CHECKER: schema.check,
    donation.CHECKER: donation.check,
}

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclasses.dataclass
class LintContext:
    """Cross-file knowledge the checkers share: the RunConfig field and
    static-signature sets (signature-completeness) and the canonical
    event SCHEMA (event-schema). Parsed once per lint run from the
    package's own sources; tests inject doctored sources to exercise
    drift without touching the shipped tree."""

    config_fields: frozenset
    signature_keys: frozenset
    schema: dict
    strict: bool = False
    # autotune vocab (ISSUE 19): TUNE_RACES/TUNE_SOURCES from the same
    # schema source, for the tune-emit membership + TUNE_CHOICES drift
    # checks (empty tuples disable them — doctored test sources)
    tune_races: tuple = ()
    tune_sources: tuple = ()

    @classmethod
    def load(
        cls,
        config_source: Optional[str] = None,
        schema_source: Optional[str] = None,
        strict: bool = False,
    ) -> "LintContext":
        if config_source is None:
            with open(os.path.join(_PKG_ROOT, "utils", "config.py")) as f:
                config_source = f.read()
        if schema_source is None:
            with open(os.path.join(_PKG_ROOT, "obs", "events.py")) as f:
                schema_source = f.read()
        fields, keys = signature.parse_config_info(config_source)
        races, sources = schema.parse_tune_vocab(schema_source)
        return cls(
            config_fields=frozenset(fields),
            signature_keys=frozenset(keys),
            schema=schema.parse_schema(schema_source),
            strict=strict,
            tune_races=races,
            tune_sources=sources,
        )


def iter_python_files(paths: Iterable[str]):
    """Expand files/directories into a sorted, de-duplicated .py list."""
    out = set()
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.add(os.path.join(dirpath, fn))
        elif path.endswith(".py"):
            out.add(path)
    return sorted(out)


@dataclasses.dataclass
class LintReport:
    findings: list  # sorted, suppressions applied
    n_files: int

    @property
    def unsuppressed(self) -> list:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list:
        return [f for f in self.findings if f.suppressed]

    def suppression_counts(self) -> dict:
        counts: dict = {}
        for f in self.suppressed:
            counts[f.checker] = counts.get(f.checker, 0) + 1
        return dict(sorted(counts.items()))

    def render(self, strict: bool = False) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(
            f"{len(self.unsuppressed)} finding(s), "
            f"{len(self.suppressed)} suppressed, "
            f"{self.n_files} file(s) checked"
        )
        if strict:
            counts = self.suppression_counts()
            if counts:
                lines.append("suppressions by checker:")
                lines.extend(f"  {k}: {v}" for k, v in counts.items())
            else:
                lines.append("suppressions by checker: none")
        return "\n".join(lines) + "\n"


def lint_paths(
    paths: Iterable[str],
    checkers: Optional[Iterable[str]] = None,
    context: Optional[LintContext] = None,
) -> LintReport:
    """Run the (selected) checkers over ``paths``; the library entry the
    CLI, the tier-1 pin, and bench.py's lint extra all share."""
    ctx = context if context is not None else LintContext.load()
    selected = list(CHECKERS) if checkers is None else list(checkers)
    unknown = [c for c in selected if c not in CHECKERS]
    if unknown:
        raise ValueError(
            f"unknown checker(s) {unknown}; known: {sorted(CHECKERS)}"
        )
    findings: list = []
    modules: dict = {}
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            mod = SourceModule(path, source)
        except (OSError, SyntaxError, ValueError) as e:
            findings.append(
                Finding(
                    "parse", path, getattr(e, "lineno", 1) or 1, 0,
                    f"cannot analyze: {e}",
                )
            )
            continue
        modules[path] = mod
        for name in selected:
            findings.extend(CHECKERS[name](mod, ctx))
    return LintReport(
        findings=apply_suppressions(findings, modules),
        n_files=len(modules),
    )


def main(argv: Optional[list] = None) -> int:
    """``erasurehead-tpu lint [--strict] [--checker NAME ...] [paths]``.

    Exit 0: no unsuppressed findings; 1: findings; 2: usage error."""
    argv = list(sys.argv[1:] if argv is None else argv)
    strict = False
    checkers: Optional[list] = None
    paths: list = []
    it = iter(argv)
    for arg in it:
        if arg == "--strict":
            strict = True
        elif arg == "--checker":
            name = next(it, None)
            if name is None:
                print("lint: --checker needs a name", file=sys.stderr)
                return 2
            checkers = (checkers or []) + [name]
        elif arg in ("-h", "--help"):
            print(
                "usage: erasurehead-tpu lint [--strict] "
                "[--checker NAME ...] [paths]\n"
                f"checkers: {', '.join(sorted(CHECKERS))}\n"
                "default path: the installed erasurehead_tpu package",
            )
            return 0
        elif arg.startswith("-"):
            print(f"lint: unknown flag {arg!r}", file=sys.stderr)
            return 2
        else:
            paths.append(arg)
    if not paths:
        paths = [_PKG_ROOT]
    t0 = time.perf_counter()
    try:
        report = lint_paths(paths, checkers=checkers)
    except ValueError as e:
        print(f"lint: {e}", file=sys.stderr)
        return 2
    sys.stdout.write(report.render(strict=strict))
    print(
        f"lint: {report.n_files} file(s) in "
        f"{time.perf_counter() - t0:.2f}s",
        file=sys.stderr,
    )
    return 1 if report.unsuppressed else 0
