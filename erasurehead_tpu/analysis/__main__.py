"""``python -m erasurehead_tpu.analysis [paths]`` — the lint CLI without
the full console entry point (no jax import on this path; the Makefile's
``lint`` target uses it so the tier-1 loop pays AST-walk time only)."""

import sys

from erasurehead_tpu.analysis.runner import main

if __name__ == "__main__":
    sys.exit(main())
