"""Gradient block tables: pytree <-> padded [L, width] layer-block views.

Per-layer (blockwise) gradient coding codes each layer's flattened
gradient block independently against the same layout matrix, so decode is
one batched ``[k, P] x [P, L, width]`` einsum instead of a per-leaf
gather-and-combine over the full pytree (parallel/step.
_layer_block_local_body). This module owns the pure shape logic of that
view: a :class:`BlockSpec` describes how a model's parameter/gradient
pytree flattens into a zero-padded block table and back, bijectively —
``blocks_to_tree(tree_to_blocks(g)) == g`` exactly (padding lanes are
zeros; values are moved, never transformed, so the blockwise decode is
bitwise-identical to the treewise decode, test-pinned).

Block granularity is per LEAF by default (one block per parameter
tensor — "per layer" for models whose layers are separate leaves).
Models whose depth lives inside a stacked leaf opt leaves into
row-splitting via a ``block_split_leaves`` class attribute naming the
top-level dict keys whose leading axis should split into one block per
slice: DeepMLP's ``[n_layers, H, H]`` hidden stack becomes one block per
layer, and MoE's ``[n_experts, ...]`` expert stacks become one coded
block per expert — the expert shards are the natural coded units
(ROADMAP item 4).

Everything here is static shape metadata computed once at setup from a
parameter template; ``tree_to_blocks``/``blocks_to_tree`` are
jit/vmap-compatible (reshape + pad + concatenate only).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "BlockSpec",
    "block_spec",
    "model_block_spec",
    "tree_to_blocks",
    "blocks_to_tree",
    "partition_block_table",
]


@dataclasses.dataclass(frozen=True)
class BlockSpec:
    """Static description of a pytree's layer-block table view.

    Leaf ``i`` contributes ``rows_per_leaf[i]`` consecutive blocks of
    ``sizes_per_leaf[i]`` elements each (1 row = the whole leaf for
    unsplit leaves; split leaves contribute one row per leading-axis
    slice). Blocks are ordered leaf-major in treedef flattening order,
    each zero-padded to ``width`` = max block size."""

    treedef: Any
    leaf_shapes: Tuple[Tuple[int, ...], ...]
    rows_per_leaf: Tuple[int, ...]
    sizes_per_leaf: Tuple[int, ...]
    #: per block: (leaf index, row within the leaf) — the MoE test pins
    #: this as the expert-shard -> coded-block mapping
    block_of: Tuple[Tuple[int, int], ...]
    width: int

    @property
    def n_blocks(self) -> int:
        return len(self.block_of)

    def leaf_offsets(self) -> np.ndarray:
        """[n_leaves + 1] block-row offsets of each leaf's slice."""
        return np.cumsum([0, *self.rows_per_leaf])


def block_spec(tree, split_leaves: Tuple[str, ...] = ()) -> BlockSpec:
    """Build the :class:`BlockSpec` for a parameter/gradient template.

    ``split_leaves`` names top-level dict keys whose leading axis splits
    into one block per slice (models declare theirs via
    ``block_split_leaves``; non-dict pytrees and unnamed leaves stay one
    block per leaf)."""
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    split_set = set(split_leaves)
    shapes, rows, sizes, block_of = [], [], [], []
    for li, (path, leaf) in enumerate(paths_leaves):
        shape = tuple(int(d) for d in np.shape(leaf))
        key = getattr(path[0], "key", None) if path else None
        split = key in split_set and len(shape) >= 1 and shape[0] >= 1
        n_rows = shape[0] if split else 1
        size = int(np.prod(shape[1:] if split else shape, dtype=np.int64))
        if size == 0 or n_rows == 0:
            raise ValueError(
                f"block_spec: leaf {key or li} has zero-size shape {shape}"
            )
        shapes.append(shape)
        rows.append(n_rows)
        sizes.append(size)
        block_of.extend((li, r) for r in range(n_rows))
    return BlockSpec(
        treedef=treedef,
        leaf_shapes=tuple(shapes),
        rows_per_leaf=tuple(rows),
        sizes_per_leaf=tuple(sizes),
        block_of=tuple(block_of),
        width=max(sizes),
    )


def model_block_spec(model, params) -> BlockSpec:
    """The model's coded-block view of its parameter pytree: per-leaf
    blocks, with the model's ``block_split_leaves`` (DeepMLP layers, MoE
    experts) split along their leading axis."""
    return block_spec(params, getattr(model, "block_split_leaves", ()))


def tree_to_blocks(tree, spec: BlockSpec) -> jnp.ndarray:
    """Pytree -> zero-padded ``[n_blocks, width]`` block table
    (jit/vmap-safe; inverse of :func:`blocks_to_tree`)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) != len(spec.leaf_shapes):
        raise ValueError(
            f"tree_to_blocks: {len(leaves)} leaves vs spec's "
            f"{len(spec.leaf_shapes)}"
        )
    rows = []
    for leaf, n_rows, size in zip(
        leaves, spec.rows_per_leaf, spec.sizes_per_leaf
    ):
        flat = jnp.reshape(leaf, (n_rows, size))
        if size < spec.width:
            flat = jnp.pad(flat, ((0, 0), (0, spec.width - size)))
        rows.append(flat)
    return jnp.concatenate(rows, axis=0)


def blocks_to_tree(table: jnp.ndarray, spec: BlockSpec):
    """``[n_blocks, width]`` block table -> pytree (drops the zero
    padding; inverse of :func:`tree_to_blocks`)."""
    if table.shape[-2:] != (spec.n_blocks, spec.width):
        raise ValueError(
            f"blocks_to_tree: table shape {table.shape} vs spec "
            f"[{spec.n_blocks}, {spec.width}]"
        )
    offsets = spec.leaf_offsets()
    leaves = []
    for i, (shape, n_rows, size) in enumerate(
        zip(spec.leaf_shapes, spec.rows_per_leaf, spec.sizes_per_leaf)
    ):
        rows = table[offsets[i]:offsets[i + 1], :size]
        leaves.append(jnp.reshape(rows, shape))
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def partition_block_table(model, spec: BlockSpec, params, Xp, yp) -> np.ndarray:
    """Host-side ``[P, L, width]`` table of per-partition gradient blocks
    at ``params`` — the reference matrix behind the decode-error-vs-depth
    telemetry (obs/decode.block_decode_error): the decoded gradient of
    block l under fold weights pw is ``pw @ table[:, l, :]`` and the
    exact full gradient is the same contraction with ``pw == 1``.

    ``Xp``/``yp`` are the partition-major stacks ([P, rows, F] /
    [P, rows]); one ``grad_sum`` per partition, packed through the same
    :func:`tree_to_blocks` the step decode uses."""
    out = []
    for p in range(int(np.shape(yp)[0])):
        g = model.grad_sum(
            params,
            jax.tree.map(lambda l: l[p], Xp),
            jax.tree.map(lambda l: l[p], yp),
        )
        out.append(np.asarray(tree_to_blocks(g, spec), dtype=np.float64))
    return np.stack(out, axis=0)
