"""Feature-matrix ops over dense and TPU-friendly sparse representations.

The reference stores dense text matrices for synthetic data and scipy CSR for
the real one-hot datasets (src/util.py:13-24). scipy CSR cannot live on a TPU;
the idiomatic TPU representation for bounded-nnz one-hot data is a *padded
row-sparse* matrix: fixed ``nnz_per_row`` column-index and value arrays, so
every op is a static-shape gather / scatter-add that XLA maps onto the
hardware (embedding-lookup style) — no dynamic shapes, no host round-trips.

All model code routes matrix products through :func:`matvec` / :func:`rmatvec`
so dense ndarray and PaddedRows inputs are interchangeable.

Precision: this environment's XLA lowers fp32 matmuls to bf16-style MXU passes
by default (measured ~1.5e-2 relative error), which is fine for neural-net
training but corrupts the convex-GLM loss-curve science and is catastrophic
when amplified by large MDS decode weights. All products here therefore
default to ``HIGHEST`` precision; perf-oriented callers can opt down with
:func:`set_default_precision`.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_DEFAULT_PRECISION = lax.Precision.HIGHEST


def set_default_precision(p: Union[str, lax.Precision, None]) -> None:
    """Set the module-wide matmul precision (HIGHEST / HIGH / DEFAULT)."""
    global _DEFAULT_PRECISION
    _DEFAULT_PRECISION = lax.Precision(p) if p is not None else None


def get_default_precision():
    return _DEFAULT_PRECISION


# Dense margin-matvec lowering width (profile_dense's margin_cols8
# candidate): None = direct matvec (einsum rf,f->r). A width C replicates
# the vector operand to [F, C] behind an optimization barrier so XLA must
# lower a real (8,128)-tileable matmul instead of a cross-lane reduction;
# column 0 is the answer. EXACT: every column computes the identical dot
# product at the same precision, and the output slice costs C x a [rows]
# vector write — noise next to streaming X. Off by default pending the
# TPU measurement (tools/profile_dense.py margin variants, VERDICT r2
# item 2); bench.py exposes BENCH_MARGIN_COLS to measure the full
# production path.
_DENSE_MARGIN_COLS: Optional[int] = None


def validate_margin_cols(C: Optional[int]) -> Optional[int]:
    """Normalize/validate a margin-cols width: None, or an int in [2, 128].
    Single home for the rule — RunConfig validation calls this too."""
    if C is None:
        return None
    C = int(C)
    if C < 2 or C > 128:
        raise ValueError(f"dense margin cols must be in [2, 128], got {C}")
    return C


def set_dense_margin_cols(C: Optional[int]) -> None:
    global _DENSE_MARGIN_COLS
    _DENSE_MARGIN_COLS = validate_margin_cols(C)


def get_dense_margin_cols() -> Optional[int]:
    return _DENSE_MARGIN_COLS


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PaddedRows:
    """Row-sparse matrix with a fixed number of stored entries per row.

    ``values[r, k]`` sits at column ``indices[r, k]``; padding entries carry
    value 0.0 (their index may repeat a real one — zero value makes them
    inert in both gather and scatter directions).
    """

    indices: jnp.ndarray  # [n_rows, nnz] int32
    values: jnp.ndarray  # [n_rows, nnz] float
    n_cols: int

    @property
    def shape(self):
        return (self.indices.shape[0], self.n_cols)

    def tree_flatten(self):
        return (self.indices, self.values), self.n_cols

    @classmethod
    def tree_unflatten(cls, n_cols, children):
        return cls(children[0], children[1], n_cols)

    @classmethod
    def from_scipy(cls, csr, nnz: int | None = None) -> "PaddedRows":
        """Convert a scipy CSR matrix, padding every row to ``nnz`` entries."""
        csr = csr.tocsr()
        counts = np.diff(csr.indptr)
        width = int(counts.max()) if nnz is None else nnz
        if counts.max() > width:
            raise ValueError(f"row with {counts.max()} nnz exceeds width {width}")
        n = csr.shape[0]
        idx = np.zeros((n, width), dtype=np.int32)
        val = np.zeros((n, width), dtype=csr.data.dtype)
        # vectorized scatter: entry k of row r lands at padded column
        # k - indptr[r]
        rows = np.repeat(np.arange(n), counts)
        cols = np.arange(csr.indptr[-1]) - np.repeat(csr.indptr[:-1], counts)
        idx[rows, cols] = csr.indices
        val[rows, cols] = csr.data
        return cls(jnp.asarray(idx), jnp.asarray(val), int(csr.shape[1]))

    @classmethod
    def from_dense(cls, dense: np.ndarray, nnz: int) -> "PaddedRows":
        import scipy.sparse as sps

        return cls.from_scipy(sps.csr_matrix(dense), nnz)

    def to_dense(self) -> jnp.ndarray:
        n, width = self.indices.shape
        out = jnp.zeros((n, self.n_cols), self.values.dtype)
        rows = jnp.repeat(jnp.arange(n), width)
        return out.at[rows, self.indices.reshape(-1)].add(self.values.reshape(-1))


# Max entries of one fused pair table, applied to BOTH directions. The
# binding constraint is the scatter side: pair accumulators are per-slot
# state, so a vmapped grad_sum materializes [n_slots, Bi*Bj] before
# marginalizing — 2M entries = 8 MB/slot = ~720 MB transient at the
# faithful covtype stack's 90 slots (covtype's ~1292^2 = 1.67M fits; the
# deduped mode's 30 slots cut it to ~240 MB). The gather side's tables are
# beta-only and hoist out of the slot vmap, but jax.grad of the forward
# matvec (grad_sum_auto, any future model family) turns each gather into
# exactly the per-slot scatter the budget exists for — one shared cap
# keeps every differentiation path inside it. Oversized pairs fall back to
# per-field singles (same lookup count as PaddedRows, no value payload):
# amazon-class ~5.5k-category fields (30M-entry tables) always do.
PAIR_TABLE_CAP = 1 << 21

# Budget for one lane-replicated margin table ([entries, L] f32 behind an
# optimization barrier — XLA cannot fold it away). Separate from
# PAIR_TABLE_CAP, which budgets the scatter side's per-slot accumulators:
# the gather table is a single transient, so it tolerates a much larger
# byte budget, but lane width multiplies it — at L=1024 an uncapped
# covtype pair table would be 1.67M x 1024 x 4B ~= 6.8 GB. Oversized
# pairs fall back to lane-replicated singles (same fallback rule as the
# scalar path, narrower tables).
LANE_TABLE_BYTES_CAP = 1 << 28  # 256 MB


def fields_margin_plan(field_sizes, lanes=None, itemsize=4):
    """The pairing plan the margin matvec will use at a given lane width.

    Lane replication shrinks the effective pair-table cap so one
    [entries, L] table stays within LANE_TABLE_BYTES_CAP. ``itemsize`` is
    the table element width in bytes (tables inherit the param dtype; 4 =
    the f32 default) — the same width the runtime over-cap guard in
    _lanes_fields_matvec charges, so plan and guard agree for any dtype.
    Exposed so traffic models (tools/bench_sparse.py) can count the true
    number of margin lookups per row instead of assuming all-pairs.
    """
    cap = PAIR_TABLE_CAP
    if lanes is not None:
        cap = min(cap, LANE_TABLE_BYTES_CAP // (itemsize * lanes))
    return _greedy_pairing(tuple(field_sizes), cap=cap)


def _greedy_pairing(field_sizes, cap=PAIR_TABLE_CAP):
    """Static pairing plan: adjacent fields fuse when their pair table fits.

    Returns a tuple of ("pair", i, j) / ("single", i) entries covering every
    field exactly once. Computed once per (field_sizes, cap) — the plan is
    static python structure baked into the jitted program.
    """
    plan, k, K = [], 0, len(field_sizes)
    while k < K:
        if k + 1 < K and field_sizes[k] * field_sizes[k + 1] <= cap:
            plan.append(("pair", k, k + 1))
            k += 2
        else:
            plan.append(("single", k))
            k += 1
    return tuple(plan)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class FieldOnehot:
    """Exactly-one-hot-per-field sparse rows: the structure of the
    reference's real workloads (covtype bins every column into one-hot
    categories, src/arrange_real_data.py:145-205; amazon one-hot-encodes
    hashed interaction terms, :34-91). Row r activates exactly one column
    (value 1.0) inside each of K disjoint field blocks.

    Exploiting the structure beats the generic PaddedRows lowering twice
    over on TPU, where the measured bound is scalar-lookup *count*
    (~7 ns/element, tools/profile_sparse.py), not HBM:

      - storage halves: ``local[r, k]`` (category within field k) is the
        only array — no values payload (all ones) and no global indices;
      - the margin needs K/2 gathers per row instead of K: fields are
        fused pairwise into per-iteration sum tables
        ``T[a, b] = beta_i[a] + beta_j[b]`` (a vectorized outer add, tiny
        vs the gathers it replaces), indexed by the fused code
        ``local_i * B_j + local_j``; the gradient scatter likewise targets
        pair accumulators then marginalizes (row/col sums).

    ``field_sizes`` are static (part of the pytree aux data): the pairing
    plan and every table shape are baked into the compiled program.
    Numerics: pair-table sums reassociate the per-row adds, so results
    agree with PaddedRows to float tolerance, not bitwise.
    """

    local: jnp.ndarray  # [n, K] int32, category index within field k
    field_sizes: tuple  # static, len K
    n_cols: int

    @property
    def offsets(self):
        return np.concatenate([[0], np.cumsum(self.field_sizes)]).astype(int)

    @property
    def shape(self):
        return (self.local.shape[0], self.n_cols)

    def tree_flatten(self):
        return (self.local,), (tuple(self.field_sizes), self.n_cols)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], aux[0], aux[1])

    @classmethod
    def from_scipy(cls, csr, field_sizes=None) -> "FieldOnehot":
        """Build from a CSR matrix; infers the field blocks when not given.

        Raises ValueError if the matrix is not exactly-one-hot-per-field
        (callers wanting graceful fallback use :func:`infer_field_sizes`
        first).
        """
        # copy before canonicalizing: tocsr() on a CSR returns the same
        # object, and sum_duplicates would mutate the caller's matrix
        csr = csr.tocsr().copy()
        csr.sum_duplicates()
        if field_sizes is None:
            field_sizes = infer_field_sizes(csr)
            if field_sizes is None:
                raise ValueError(
                    "matrix is not field-structured one-hot "
                    "(uniform nnz/row, all-ones values, k-th entry of every "
                    "row inside the k-th disjoint column block)"
                )
        sizes = tuple(int(b) for b in field_sizes)
        K = len(sizes)
        n = csr.shape[0]
        counts = np.diff(csr.indptr)
        if not np.all(counts == K):
            raise ValueError(f"every row must have exactly {K} entries")
        idx = np.sort(csr.indices.reshape(n, K), axis=1)
        offs = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        local = idx - offs[:-1][None, :]
        if (local < 0).any() or (local >= np.asarray(sizes)[None, :]).any():
            raise ValueError("row entries fall outside their field blocks")
        if not np.all(csr.data == 1.0):
            raise ValueError("field-structured one-hot requires unit values")
        # host numpy leaf: data prep must not bounce partitions through the
        # device — the stack's single sharded device_put happens later
        # (data/sharding.put_global), same as the PaddedRows path
        return cls(np.asarray(local, np.int32), sizes, int(csr.shape[1]))

    def to_dense(self) -> jnp.ndarray:
        n, K = self.local.shape
        out = jnp.zeros((n, self.n_cols), jnp.float32)
        offs = self.offsets
        cols = self.local + jnp.asarray(offs[:-1], jnp.int32)[None, :]
        rows = jnp.repeat(jnp.arange(n), K)
        return out.at[rows, cols.reshape(-1)].add(1.0)


def infer_field_sizes(csr) -> Optional[tuple]:
    """Detect the one-hot field structure of a CSR matrix, or None.

    Checks: uniform nnz/row K, all values 1.0, and (after per-row sorting)
    the k-th entry of every row lives in a column range disjoint from and
    left of the (k+1)-th's. Observed ranges become the field blocks — a
    tighter cover than the encoder's true blocks is fine (local indices and
    table sizes shrink; any column no row touches carries zero gradient).
    Returns field block sizes measured from offset 0 (leading unused
    columns fold into field 0's block).
    """
    csr = csr.tocsr()
    n = csr.shape[0]
    if n == 0 or csr.nnz == 0 or csr.nnz % n:
        return None
    K = csr.nnz // n
    counts = np.diff(csr.indptr)
    if not np.all(counts == K) or not np.all(csr.data == 1.0):
        return None
    idx = np.sort(csr.indices.reshape(n, K), axis=1)
    lo, hi = idx.min(axis=0), idx.max(axis=0)
    if np.any(hi[:-1] >= lo[1:]):
        return None
    # block k spans [prev_hi+1 .. hi[k]]: gaps between observed ranges are
    # dead columns and fold left so the blocks tile [0, hi[-1]]
    bounds = np.concatenate([[-1], hi])
    return tuple(int(b) for b in np.diff(bounds))


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedStack:
    """int8-compressed dense feature stack with per-partition scale tables
    (``stack_dtype="int8"``, utils/config.RunConfig).

    ``q[..., r, f]`` stores ``round(X[..., r, f] / scale[..., f])`` clipped
    to [-127, 127]; ``scale`` is the per-(leading-block, feature) symmetric
    absmax/127 table. The leading axes are the stack's partition axes
    ([P] partition-major, [W, S] worker-major after the assignment
    gather), so the scale table rides the same shardings, gathers, and
    ring ``ppermute`` hops as the payload (both leaves lead with the
    block axis).

    The compression is *storage-side*: HBM residency, upload bytes, and
    the per-step stream shrink ~4x vs f32 (the scale table is O(P*F),
    noise next to the O(P*rows*F) payload); :meth:`dequantize` runs inside
    the per-device grad body (parallel/step._dq), so the f32 values exist
    only as an on-chip temporary. Lossy by construction — the fidelity
    cost per scheme is measured, not assumed (bench.py fidelity extra,
    tools/roofline_smoke.py).
    """

    q: jnp.ndarray  # [..., rows, F] int8
    scale: jnp.ndarray  # [..., F] float32

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1])

    def dequantize(self) -> jnp.ndarray:
        """[..., rows, F] float reconstruction — q * scale, broadcast over
        the rows axis. Exact for the values the quantizer produced; the
        loss happened at :meth:`quantize` time."""
        return self.q.astype(self.scale.dtype) * self.scale[..., None, :]

    @classmethod
    def quantize(cls, X) -> "QuantizedStack":
        """Symmetric per-(block, feature) int8 quantization of a dense
        [..., rows, F] stack (host numpy in, host numpy leaves out —
        quantization happens before upload, like the dtype cast it
        replaces). All-zero (block, feature) columns get scale 1.0 so the
        division is defined and they reconstruct to exact zeros."""
        X = np.asarray(X)
        if not np.issubdtype(X.dtype, np.floating):
            raise ValueError(
                f"stack_dtype='int8' quantizes float stacks; got {X.dtype}"
            )
        absmax = np.abs(X).max(axis=-2)  # [..., F]
        scale = (np.where(absmax > 0, absmax, 1.0) / 127.0).astype(
            np.float32
        )
        q = np.clip(
            np.rint(X / scale[..., None, :]), -127, 127
        ).astype(np.int8)
        return cls(q, scale)


def maybe_dequantize(X):
    """Identity for ordinary stacks; f32 reconstruction for a
    :class:`QuantizedStack`. The per-device grad bodies call this first
    (parallel/step._dq), so every lowering downstream sees the same dense
    array it would for an uncompressed run."""
    return X.dequantize() if isinstance(X, QuantizedStack) else X


Features = Union[jnp.ndarray, PaddedRows, FieldOnehot, QuantizedStack]

# Sparse margin-gather lane width. TPU scalar gather/scatter throughput is
# ~7 ns/element (measured, tools/profile_sparse.py) — each of the nnz
# lookups moves 4 bytes through a path sized for 512-byte vector rows. With
# lanes=L, matvec gathers L-wide rows from a lane-replicated [F, L] table
# (all lanes identical; the lane reduction recovers the exact scalar
# answer), trading L x gather traffic for vectorized addressing — measured
# 2.6x on the margin at L=8. The scatter direction is deliberately scalar:
# lane scatter measured as a net loss (see rmatvec). None = plain scalar
# lowering (CPU default; exact same arithmetic).
_SPARSE_LANES: Optional[int] = None


def validate_lanes(L: Optional[int]) -> Optional[int]:
    """Normalize/validate a lane width: None, or a power of two in [1, 1024].
    Single home for the rule — RunConfig validation calls this too."""
    if L is None:
        return None
    L = int(L)
    if L < 1 or L > 1024 or (L & (L - 1)):
        raise ValueError(
            f"sparse lane width must be a power of two in [1, 1024], got {L}"
        )
    return L


def set_sparse_lanes(L: Optional[int]) -> None:
    """Set the sparse margin-gather lane width (None = scalar path).

    Applies to the matvec (margin) direction only — for both PaddedRows
    value gathers and FieldOnehot pair-table gathers: the v5e profile
    (tools/profile_sparse.py) measured the lane gather at 2.6x the scalar
    gather but the lane scatter as a net loss, so rmatvec always uses the
    scalar scatter-add.

    L must be a power of two: the lane reduction ``sum(lanes) / L`` is then
    exactly a single lane's value (all lanes are identical; summing L equal
    f32 values is an exponent shift). The full op still agrees with the
    scalar path only to f32 reduction tolerance — XLA may reassociate the
    per-row contraction differently per shape. A lane-0 slice instead of
    the reduction would invite XLA to narrow the gather back into the
    scalar form this path exists to avoid.
    """
    global _SPARSE_LANES
    _SPARSE_LANES = validate_lanes(L)


def get_sparse_lanes() -> Optional[int]:
    return _SPARSE_LANES


# FieldOnehot gradient-scatter lowering:
#   "pairs"  — scatter-add into fused pair accumulators, then marginalize
#              (halves the serialized lookup count vs per-field; measured
#              58.0 vs 102.0 ms at the covtype stack, v5e round 3);
#   "onehot" — segment-sum as one-hot MATMUL: per field, g[b] =
#              sum_n [local_n == b] * r_n is a [C] x [C, B] product over
#              row chunks — the compare builds an exact 0/1 one-hot, the
#              MXU does the reduction, and a chunk scan bounds the live
#              one-hot. Attacks the scatter-add's read-modify-write
#              serialization (~7 ns/element) structurally; exact (f32
#              one-hot, HIGHEST precision, f32 accumulation) up to sum
#              reassociation.
_FIELDS_SCATTER = "pairs"

# one-hot chunk byte budget: the chunk row count C is sized so one
# [C, B_max] f32 chunk stays within this, rounded down to a multiple of
# 512 for tile alignment (covtype B~1292 -> C=6144; amazon B~5.5k ->
# C=1024; floor 512)
_ONEHOT_CHUNK_BYTES = 1 << 25  # 32 MB


def set_fields_scatter(mode: str) -> None:
    """Select the FieldOnehot rmatvec lowering ("pairs" / "onehot")."""
    global _FIELDS_SCATTER
    if mode not in ("pairs", "onehot"):
        raise ValueError(
            f"fields scatter mode must be pairs/onehot, got {mode!r}"
        )
    _FIELDS_SCATTER = mode


def get_fields_scatter() -> str:
    return _FIELDS_SCATTER


# FieldOnehot margin (matvec) lowering:
#   "tables" — fused pair-table gathers (default; composes with
#              set_sparse_lanes lane replication);
#   "onehot" — the mirror of the one-hot scatter: per field,
#              p += onehot [C, B] @ beta_k on the MXU — same compare
#              cost, zero serialized gathers. sparse_lanes is ignored in
#              this mode (there is no gather to widen).
_FIELDS_MARGIN = "tables"


def set_fields_margin(mode: str) -> None:
    """Select the FieldOnehot matvec lowering ("tables" / "onehot")."""
    global _FIELDS_MARGIN
    if mode not in ("tables", "onehot"):
        raise ValueError(
            f"fields margin mode must be tables/onehot, got {mode!r}"
        )
    _FIELDS_MARGIN = mode


def get_fields_margin() -> str:
    return _FIELDS_MARGIN


def _plan_tables(plan, sizes, local, v):
    """Yield one (table, code) per plan entry: the fused sum table over a
    pair's (or single's) categories and each row's index into it. The single
    home for the fused-code layout — the scalar and lane margin lowerings
    must gather from identical tables."""
    offs = np.concatenate([[0], np.cumsum(sizes)]).astype(int)
    for entry in plan:
        if entry[0] == "pair":
            _, i, j = entry
            bi = v[offs[i] : offs[i + 1]]
            bj = v[offs[j] : offs[j + 1]]
            table = (bi[:, None] + bj[None, :]).reshape(-1)
            code = local[:, i] * sizes[j] + local[:, j]
        else:
            _, i = entry
            table = v[offs[i] : offs[i + 1]]
            code = local[:, i]
        yield table, code


def _fields_matvec(X: "FieldOnehot", v: jnp.ndarray) -> jnp.ndarray:
    """sum_k v[off_k + local[:, k]] via fused pair tables (see FieldOnehot)."""
    offs = X.offsets
    sizes = X.field_sizes
    if v.ndim > 1:
        # matrix rhs (MLP first layer): pair tables would be [Bi*Bj, H] —
        # the table build then rivals the gathers. Per-field row gathers
        # of H-wide rows are already vectorized; use them directly.
        out = 0.0
        for k in range(len(sizes)):
            out = out + jnp.take(
                v[offs[k] : offs[k + 1]], X.local[:, k], axis=0
            )
        return out
    if _FIELDS_MARGIN == "onehot":
        return _onehot_fields_matvec(X, v)
    L = _SPARSE_LANES
    if L is not None:
        return _lanes_fields_matvec(sizes, X.n_cols, L, X.local, v)
    out = 0.0
    for table, code in _plan_tables(_greedy_pairing(sizes), sizes, X.local, v):
        out = out + jnp.take(table, code, axis=0)
    return out


def _onehot_fields_matvec(X: "FieldOnehot", v: jnp.ndarray) -> jnp.ndarray:
    """X @ v via per-field one-hot matmuls (see set_fields_margin).

    Per chunk, p += onehot [C, B_k] @ v_k for each field — the compare
    builds an exact 0/1 one-hot and the MXU does the contraction; no
    serialized gathers. Autodiff needs no custom rule: the matmul's own
    transpose is onehot.T @ g, the one-hot scatter form, with the same
    [C, B] chunk bound.
    """
    offs = X.offsets
    sizes = X.field_sizes
    lf, C, n = _onehot_chunks(X)

    def chunk(l):
        p = jnp.zeros(C, jnp.float32)
        for k, B in enumerate(sizes):
            oh = _field_onehot(l[:, k], B, v.dtype, X.local.dtype)
            p = p + jnp.matmul(
                oh, v[offs[k] : offs[k + 1]],
                precision=lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32,
            )
        return p

    return lax.map(chunk, lf).reshape(-1)[:n].astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _lanes_fields_matvec(sizes, n_cols, L, local, v):
    """Composed margin lowering: pair tables (half the lookup count) x lane
    replication (vectorized addressing, measured 2.6x on the scalar gather
    — see set_sparse_lanes). Each table replicates to [entries, L] behind a
    barrier; gathers return [n, L] rows whose lanes are identical, so the
    per-lane accumulator reduces exactly (power-of-two L) at the end. The
    pairing plan is lane-aware: pairs whose replicated table would exceed
    LANE_TABLE_BYTES_CAP fall back to singles, and a single whose own
    replicated [B, L] table would still exceed it (a >65536-category field
    at L=1024) falls back to the scalar gather for that field only.

    custom_vjp: the forward lane gather's automatic transpose would be a
    lane-wide scatter into the [entries, L] table — exactly the op the v5e
    profile measured as a net loss, and far outside the 8 MB/table scatter
    budget PAIR_TABLE_CAP enforces. The op is linear in v with transpose
    X^T r, so the backward pass is pinned to _fields_rmatvec — the
    pair-accumulator scalar scatter, or the one-hot matmul when
    set_fields_scatter("onehot") is active: autodiff through the lane
    path costs the same as through the scalar path, and never emits a
    lane-wide table scatter.
    """
    acc = 0.0
    scalar_acc = 0.0
    for table, code in _plan_tables(
        fields_margin_plan(sizes, L, itemsize=jnp.dtype(v.dtype).itemsize),
        sizes, local, v,
    ):
        if table.shape[0] * L * table.dtype.itemsize > LANE_TABLE_BYTES_CAP:
            # a single field too large even unreplicated to fit the lane
            # budget (pairs are already excluded by the lane-aware plan):
            # scalar-gather it rather than build an over-cap [B, L] table
            scalar_acc = scalar_acc + jnp.take(table, code)  # [n]
            continue
        wide = jax.lax.optimization_barrier(
            jnp.broadcast_to(table[:, None], (table.shape[0], L))
        )
        acc = acc + jnp.take(wide, code, axis=0)  # [n, L]
    if not isinstance(acc, float):  # at least one lane table was built
        scalar_acc = scalar_acc + acc.sum(axis=1) * (1.0 / L)
    return scalar_acc


def _lanes_fields_matvec_fwd(sizes, n_cols, L, local, v):
    return _lanes_fields_matvec(sizes, n_cols, L, local, v), local


def _lanes_fields_matvec_bwd(sizes, n_cols, L, local, g):
    grad_v = _fields_rmatvec(FieldOnehot(local, sizes, n_cols), g)
    return np.zeros(local.shape, jax.dtypes.float0), grad_v


_lanes_fields_matvec.defvjp(_lanes_fields_matvec_fwd, _lanes_fields_matvec_bwd)


def _onehot_chunks(X: "FieldOnehot"):
    """Shared chunking scaffold for the one-hot matmul lowerings: rows
    padded to a multiple of the chunk size C (sized so one [C, B_max] f32
    one-hot stays within _ONEHOT_CHUNK_BYTES, 512-aligned) and reshaped to
    [n_chunks, C, K]. Returns (chunked_local, C, n)."""
    n = X.local.shape[0]
    C = max(512, _ONEHOT_CHUNK_BYTES // (4 * max(X.field_sizes)) // 512 * 512)
    Np = -(-n // C) * C
    lf = jnp.pad(X.local, ((0, Np - n), (0, 0))).reshape(-1, C, X.local.shape[1])
    return lf, C, n


def _field_onehot(l_col, B, dtype, index_dtype):
    """Exact 0/1 one-hot [C, B] from an integer compare."""
    iota = jnp.arange(B, dtype=index_dtype)
    return (l_col[:, None] == iota[None, :]).astype(dtype)


def _onehot_fields_rmatvec(X: "FieldOnehot", r: jnp.ndarray) -> jnp.ndarray:
    """X.T @ r via per-field one-hot matmuls (see set_fields_scatter).

    Exact 0/1 one-hots from an integer compare; f32 HIGHEST-precision
    matmul so the reduction is true f32 accumulation (the one-hot factor
    is exact in any dtype; only the reduction order differs from the
    scatter path). Rows are chunk-scanned so the live one-hot stays within
    _ONEHOT_CHUNK_BYTES; padded rows carry r=0 and land on code 0 with
    zero weight.
    """
    offs = X.offsets
    sizes = X.field_sizes
    lf, C, n = _onehot_chunks(X)
    rc = jnp.pad(r, (0, lf.shape[0] * C - n)).reshape(-1, C)

    def chunk(xs):
        l, rv = xs  # [C, K], [C]
        outs = []
        for k, B in enumerate(sizes):
            oh = _field_onehot(l[:, k], B, r.dtype, X.local.dtype)
            outs.append(
                jnp.matmul(
                    rv, oh,
                    precision=lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32,
                )
            )
        # lax.map (scan with an empty carry) rather than a scan carry:
        # under shard_map a zeros-initialized carry is axis-unvarying
        # while the body output varies, and the types must match
        return tuple(outs)

    g = lax.map(chunk, (lf, rc))  # tuple of [n_chunks, B_k]
    out = jnp.zeros(X.n_cols, r.dtype)
    for k in range(len(sizes)):
        out = out.at[offs[k] : offs[k + 1]].add(g[k].sum(axis=0).astype(r.dtype))
    return out


def _fields_rmatvec(X: "FieldOnehot", r: jnp.ndarray) -> jnp.ndarray:
    """X.T @ r: scatter into per-pair accumulators, then marginalize —
    or per-field one-hot matmuls when set_fields_scatter("onehot")."""
    offs = X.offsets
    sizes = X.field_sizes
    if r.ndim == 1 and _FIELDS_SCATTER == "onehot":
        return _onehot_fields_rmatvec(X, r)
    if r.ndim > 1:
        out = jnp.zeros((X.n_cols, r.shape[1]), r.dtype)
        for k in range(len(sizes)):
            blk = jnp.zeros((sizes[k], r.shape[1]), r.dtype).at[
                X.local[:, k]
            ].add(r)
            out = out.at[offs[k] : offs[k + 1]].add(blk)
        return out
    out = jnp.zeros(X.n_cols, r.dtype)
    for entry in _greedy_pairing(sizes):
        if entry[0] == "pair":
            _, i, j = entry
            code = X.local[:, i] * sizes[j] + X.local[:, j]
            acc = jnp.zeros(sizes[i] * sizes[j], r.dtype).at[code].add(r)
            t = acc.reshape(sizes[i], sizes[j])
            out = out.at[offs[i] : offs[i + 1]].add(t.sum(axis=1))
            out = out.at[offs[j] : offs[j + 1]].add(t.sum(axis=0))
        else:
            _, i = entry
            blk = jnp.zeros(sizes[i], r.dtype).at[X.local[:, i]].add(r)
            out = out.at[offs[i] : offs[i + 1]].add(blk)
    return out


def flatten_rows(X: Features) -> Features:
    """Collapse every leading (slot) axis of a stacked Features into the
    row axis: dense [..., R, F] -> [M*R, F], PaddedRows leaves
    [..., R, nnz] -> [M*R, nnz], FieldOnehot local [..., R, K] -> [M*R, K].

    The flat-stack gradient lowering (parallel/step.make_flat_grad_fn)
    uses this so the whole local stack is ONE matvec/rmatvec call: for
    dense the margin becomes a single 2-D matmul (measured at the
    raw-stream floor); for the sparse formats the gradient scatter targets
    ONE accumulator instead of a vmapped per-slot batch of them — the
    [n_slots, table] transient the PAIR_TABLE_CAP comment budgets simply
    never exists.
    """
    if isinstance(X, FieldOnehot):
        K = X.local.shape[-1]
        return FieldOnehot(X.local.reshape(-1, K), X.field_sizes, X.n_cols)
    if isinstance(X, PaddedRows):
        nnz = X.indices.shape[-1]
        return PaddedRows(
            X.indices.reshape(-1, nnz), X.values.reshape(-1, nnz), X.n_cols
        )
    return X.reshape(-1, X.shape[-1])


def matvec(X: Features, v: jnp.ndarray, precision=None) -> jnp.ndarray:
    """X @ v for dense [n, F], PaddedRows, or FieldOnehot; v may also be a
    matrix [F, H]."""
    precision = precision if precision is not None else _DEFAULT_PRECISION
    if isinstance(X, FieldOnehot):
        return _fields_matvec(X, v)
    if isinstance(X, PaddedRows):
        L = _SPARSE_LANES
        if L is not None and v.ndim == 1:
            # lane-replicated table; the barrier keeps XLA from simplifying
            # gather-of-broadcast back into the scalar gather being avoided
            table = jax.lax.optimization_barrier(
                jnp.broadcast_to(v[:, None], (v.shape[0], L))
            )
            g = jnp.take(table, X.indices, axis=0)  # [n, nnz, L]
            per_lane = jnp.einsum(
                "nk,nkl->nl", X.values, g, precision=precision
            )
            # exact: lanes are identical and L is a power of two
            return per_lane.sum(axis=1) * (1.0 / L)
        gathered = jnp.take(v, X.indices, axis=0)  # [n, nnz] or [n, nnz, H]
        if v.ndim == 1:
            return jnp.sum(X.values * gathered, axis=1)
        return jnp.einsum("nk,nkh->nh", X.values, gathered, precision=precision)
    def _margin_matmul(vec, **matmul_kwargs):
        """Margin matvec, optionally via the cols lowering: replicate the
        vector operand to [F, C] behind a barrier so XLA lowers a
        tileable matmul; column 0 is the exact answer."""
        C = _DENSE_MARGIN_COLS
        if C is not None and v.ndim == 1:
            bt = lax.optimization_barrier(
                jnp.broadcast_to(vec[:, None], (vec.shape[0], C))
            )
            return jnp.matmul(X, bt, **matmul_kwargs)[..., 0]
        return jnp.matmul(X, vec, **matmul_kwargs)

    if X.dtype == jnp.bfloat16 and v.dtype != X.dtype:
        # bf16 DATA mode: keep the streamed operand bf16 — promoting X to
        # match f32 params would make XLA materialize (and re-read) an f32
        # copy of the whole stack, voiding the mode's halved-HBM-traffic
        # point. Cast the tiny vector operand down instead; the MXU
        # accumulates natively in f32 (preferred_element_type).
        return _margin_matmul(
            v.astype(X.dtype), preferred_element_type=jnp.float32
        )
    return _margin_matmul(v, precision=precision)


def rmatvec(X: Features, r: jnp.ndarray, precision=None) -> jnp.ndarray:
    """X.T @ r (scatter-add for PaddedRows/FieldOnehot); r is [n] or [n, H]."""
    precision = precision if precision is not None else _DEFAULT_PRECISION
    if isinstance(X, FieldOnehot):
        return _fields_rmatvec(X, r)
    if isinstance(X, PaddedRows):
        # Lanes deliberately do NOT apply here: v5e measurement
        # (tools/profile_sparse.py, window 1 round 3) put the L=8 lane
        # scatter at 112 ms vs 102 ms scalar at the covtype slot stack —
        # the scatter-add's read-modify-write serializes on the accumulator
        # row either way, so lane replication only adds traffic. The lane
        # win is gather-side only (97 -> 37 ms), so set_sparse_lanes scopes
        # to matvec.
        if r.ndim == 1:
            contrib = (X.values * r[:, None]).reshape(-1)  # [n*nnz]
            return jnp.zeros(X.n_cols, contrib.dtype).at[
                X.indices.reshape(-1)
            ].add(contrib)
        contrib = X.values[:, :, None] * r[:, None, :]  # [n, nnz, H]
        return (
            jnp.zeros((X.n_cols, r.shape[1]), contrib.dtype)
            .at[X.indices.reshape(-1)]
            .add(contrib.reshape(-1, r.shape[1]))
        )
    if X.dtype == jnp.bfloat16 and r.dtype != X.dtype:
        # see matvec: stream X as stored, cast the small operand down,
        # accumulate f32 on the MXU
        return jnp.matmul(
            X.T, r.astype(X.dtype), preferred_element_type=jnp.float32
        )
    return jnp.matmul(X.T, r, precision=precision)


def n_rows(X: Features) -> int:
    return X.shape[0]
