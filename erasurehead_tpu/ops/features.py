"""Feature-matrix ops over dense and TPU-friendly sparse representations.

The reference stores dense text matrices for synthetic data and scipy CSR for
the real one-hot datasets (src/util.py:13-24). scipy CSR cannot live on a TPU;
the idiomatic TPU representation for bounded-nnz one-hot data is a *padded
row-sparse* matrix: fixed ``nnz_per_row`` column-index and value arrays, so
every op is a static-shape gather / scatter-add that XLA maps onto the
hardware (embedding-lookup style) — no dynamic shapes, no host round-trips.

All model code routes matrix products through :func:`matvec` / :func:`rmatvec`
so dense ndarray and PaddedRows inputs are interchangeable.

Precision: this environment's XLA lowers fp32 matmuls to bf16-style MXU passes
by default (measured ~1.5e-2 relative error), which is fine for neural-net
training but corrupts the convex-GLM loss-curve science and is catastrophic
when amplified by large MDS decode weights. All products here therefore
default to ``HIGHEST`` precision; perf-oriented callers can opt down with
:func:`set_default_precision`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_DEFAULT_PRECISION = lax.Precision.HIGHEST


def set_default_precision(p: Union[str, lax.Precision, None]) -> None:
    """Set the module-wide matmul precision (HIGHEST / HIGH / DEFAULT)."""
    global _DEFAULT_PRECISION
    _DEFAULT_PRECISION = lax.Precision(p) if p is not None else None


def get_default_precision():
    return _DEFAULT_PRECISION


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class PaddedRows:
    """Row-sparse matrix with a fixed number of stored entries per row.

    ``values[r, k]`` sits at column ``indices[r, k]``; padding entries carry
    value 0.0 (their index may repeat a real one — zero value makes them
    inert in both gather and scatter directions).
    """

    indices: jnp.ndarray  # [n_rows, nnz] int32
    values: jnp.ndarray  # [n_rows, nnz] float
    n_cols: int

    @property
    def shape(self):
        return (self.indices.shape[0], self.n_cols)

    def tree_flatten(self):
        return (self.indices, self.values), self.n_cols

    @classmethod
    def tree_unflatten(cls, n_cols, children):
        return cls(children[0], children[1], n_cols)

    @classmethod
    def from_scipy(cls, csr, nnz: int | None = None) -> "PaddedRows":
        """Convert a scipy CSR matrix, padding every row to ``nnz`` entries."""
        csr = csr.tocsr()
        counts = np.diff(csr.indptr)
        width = int(counts.max()) if nnz is None else nnz
        if counts.max() > width:
            raise ValueError(f"row with {counts.max()} nnz exceeds width {width}")
        n = csr.shape[0]
        idx = np.zeros((n, width), dtype=np.int32)
        val = np.zeros((n, width), dtype=csr.data.dtype)
        # vectorized scatter: entry k of row r lands at padded column
        # k - indptr[r]
        rows = np.repeat(np.arange(n), counts)
        cols = np.arange(csr.indptr[-1]) - np.repeat(csr.indptr[:-1], counts)
        idx[rows, cols] = csr.indices
        val[rows, cols] = csr.data
        return cls(jnp.asarray(idx), jnp.asarray(val), int(csr.shape[1]))

    @classmethod
    def from_dense(cls, dense: np.ndarray, nnz: int) -> "PaddedRows":
        import scipy.sparse as sps

        return cls.from_scipy(sps.csr_matrix(dense), nnz)

    def to_dense(self) -> jnp.ndarray:
        n, width = self.indices.shape
        out = jnp.zeros((n, self.n_cols), self.values.dtype)
        rows = jnp.repeat(jnp.arange(n), width)
        return out.at[rows, self.indices.reshape(-1)].add(self.values.reshape(-1))


Features = Union[jnp.ndarray, PaddedRows]

# Sparse gather/scatter lane width. TPU scalar gather/scatter throughput is
# ~7 ns/element (measured, tools/profile_sparse.py) — each of the nnz
# lookups moves 4 bytes through a path sized for 512-byte vector rows. With
# lanes=L, matvec gathers L-wide rows from a lane-replicated [F, L] table
# and rmatvec scatter-adds L-wide rows into a [F, L] accumulator (all lanes
# identical; lane 0 is the answer), trading L x memory traffic for
# vectorized addressing. None = plain scalar lowering (CPU default; exact
# same arithmetic).
_SPARSE_LANES: Optional[int] = None


def validate_lanes(L: Optional[int]) -> Optional[int]:
    """Normalize/validate a lane width: None, or a power of two in [1, 1024].
    Single home for the rule — RunConfig validation calls this too."""
    if L is None:
        return None
    L = int(L)
    if L < 1 or L > 1024 or (L & (L - 1)):
        raise ValueError(
            f"sparse lane width must be a power of two in [1, 1024], got {L}"
        )
    return L


def set_sparse_lanes(L: Optional[int]) -> None:
    """Set the PaddedRows gather/scatter lane width (None = scalar path).

    L must be a power of two: the lane reduction ``sum(lanes) / L`` is then
    exactly a single lane's value (all lanes are identical; summing L equal
    f32 values is an exponent shift). The full op still agrees with the
    scalar path only to f32 reduction tolerance — XLA may reassociate the
    per-row contraction differently per shape. A lane-0 slice instead of
    the reduction would invite XLA to narrow the gather back into the
    scalar form this path exists to avoid.
    """
    global _SPARSE_LANES
    _SPARSE_LANES = validate_lanes(L)


def get_sparse_lanes() -> Optional[int]:
    return _SPARSE_LANES


def matvec(X: Features, v: jnp.ndarray, precision=None) -> jnp.ndarray:
    """X @ v for dense [n, F] or PaddedRows; v may also be a matrix [F, H]."""
    precision = precision if precision is not None else _DEFAULT_PRECISION
    if isinstance(X, PaddedRows):
        L = _SPARSE_LANES
        if L is not None and v.ndim == 1:
            # lane-replicated table; the barrier keeps XLA from simplifying
            # gather-of-broadcast back into the scalar gather being avoided
            table = jax.lax.optimization_barrier(
                jnp.broadcast_to(v[:, None], (v.shape[0], L))
            )
            g = jnp.take(table, X.indices, axis=0)  # [n, nnz, L]
            per_lane = jnp.einsum(
                "nk,nkl->nl", X.values, g, precision=precision
            )
            # exact: lanes are identical and L is a power of two
            return per_lane.sum(axis=1) * (1.0 / L)
        gathered = jnp.take(v, X.indices, axis=0)  # [n, nnz] or [n, nnz, H]
        if v.ndim == 1:
            return jnp.sum(X.values * gathered, axis=1)
        return jnp.einsum("nk,nkh->nh", X.values, gathered, precision=precision)
    if X.dtype == jnp.bfloat16 and v.dtype != X.dtype:
        # bf16 DATA mode: keep the streamed operand bf16 — promoting X to
        # match f32 params would make XLA materialize (and re-read) an f32
        # copy of the whole stack, voiding the mode's halved-HBM-traffic
        # point. Cast the tiny vector operand down instead; the MXU
        # accumulates natively in f32 (preferred_element_type).
        return jnp.matmul(
            X, v.astype(X.dtype), preferred_element_type=jnp.float32
        )
    return jnp.matmul(X, v, precision=precision)


def rmatvec(X: Features, r: jnp.ndarray, precision=None) -> jnp.ndarray:
    """X.T @ r (scatter-add for PaddedRows); r is [n] or [n, H]."""
    precision = precision if precision is not None else _DEFAULT_PRECISION
    if isinstance(X, PaddedRows):
        L = _SPARSE_LANES
        if L is not None and r.ndim == 1:
            contrib = (X.values * r[:, None]).reshape(-1, 1)  # [n*nnz, 1]
            rows = jax.lax.optimization_barrier(
                jnp.broadcast_to(contrib, (contrib.shape[0], L))
            )
            out = (
                jnp.zeros((X.n_cols, L), contrib.dtype)
                .at[X.indices.reshape(-1)]
                .add(rows)
            )
            # exact: every lane accumulated the identical add sequence
            return out.sum(axis=1) * (1.0 / L)
        if r.ndim == 1:
            contrib = (X.values * r[:, None]).reshape(-1)  # [n*nnz]
            return jnp.zeros(X.n_cols, contrib.dtype).at[
                X.indices.reshape(-1)
            ].add(contrib)
        contrib = X.values[:, :, None] * r[:, None, :]  # [n, nnz, H]
        return (
            jnp.zeros((X.n_cols, r.shape[1]), contrib.dtype)
            .at[X.indices.reshape(-1)]
            .add(contrib.reshape(-1, r.shape[1]))
        )
    if X.dtype == jnp.bfloat16 and r.dtype != X.dtype:
        # see matvec: stream X as stored, cast the small operand down,
        # accumulate f32 on the MXU
        return jnp.matmul(
            X.T, r.astype(X.dtype), preferred_element_type=jnp.float32
        )
    return jnp.matmul(X.T, r, precision=precision)


def n_rows(X: Features) -> int:
    return X.shape[0]
