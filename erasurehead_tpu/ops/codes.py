"""Coding-theory core: data-assignment layouts, generator matrices, decode weights.

This is the pure-math layer of the framework: it knows nothing about devices,
meshes, or data. A *layout* describes which data partitions each logical worker
holds and with which linear-coding coefficient it folds each partition's
gradient into the single message it "sends"; *decode weights* recover (exactly
or approximately) the full-batch gradient from an arbitrary subset of worker
messages, as a fixed-shape, jit-compatible masked computation.

Reference behavior being matched (citations are file:line in /root/reference):
  - cyclic MDS supports (worker w holds partitions w..w+s mod W):
    src/coded.py:33-48, src/util.py:68-73
  - generator matrix B for exact gradient coding: src/util.py:64-83
  - fractional-repetition (FRC) assignment (groups of s+1 workers sharing
    rotated copies of the same s+1 partitions): src/replication.py:46-49,
    src/approximate_coding.py:47-50
  - partial two-slice layouts (unique uncoded partitions + a coded band):
    src/partial_coded.py:20-43,125-126 and src/partial_replication.py:24-50
  - online lstsq decode over the completed subset: src/coded.py:147-149,
    src/partial_coded.py:192-194
  - precomputed all-patterns decode table (defined, unused at runtime in the
    reference): src/util.py:85-103

Design notes (TPU-first):
  - Layout construction is host-side numpy: it happens once at setup, produces
    static integer index tables, and its outputs become *static shapes* for the
    jitted step.
  - Decoding is jnp and fixed-shape: the reference's dynamic-shape
    ``lstsq(B[completed, :].T, 1)`` becomes a masked full-shape lstsq whose
    minimum-norm solution provably has support only on the completed rows
    (the masked-out rows of ``mask[:, None] * B`` are zero, and the min-norm
    lstsq solution lies in the row space of the system matrix).
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Optional

import jax.numpy as jnp
import numpy as np

__all__ = [
    "CodingLayout",
    "uncoded_layout",
    "cyclic_mds_layout",
    "frc_layout",
    "sparse_graph_layout",
    "expander_layout",
    "partial_cyclic_layout",
    "partial_frc_layout",
    "cyclic_generator_matrix",
    "mds_decode_weights",
    "mds_decode_weights_host",
    "enumerate_decode_table",
    "straggler_pattern_index",
    "MdsDecodeTable",
    "build_decode_table",
    "straggler_pattern_index_jnp",
]


@dataclasses.dataclass(frozen=True)
class CodingLayout:
    """Static description of a coded data assignment.

    Each of the ``n_workers`` logical workers holds ``n_slots`` partition
    slots. Slot ``s`` of worker ``w`` holds global partition
    ``assignment[w, s]`` and contributes ``coeffs[w, s] * grad(partition)`` to
    the worker's transmitted message. Partial ("two-part") schemes mark some
    slots as *separate* (uncoded, always required by the master) via
    ``slot_is_coded[s] == False``.
    """

    name: str
    n_workers: int
    n_partitions: int  # number of distinct global partitions
    assignment: np.ndarray  # [W, S] int32, values in [0, n_partitions)
    coeffs: np.ndarray  # [W, S] float64 linear-coding coefficients
    slot_is_coded: np.ndarray  # [S] bool; False = "separate"/uncoded slot
    n_stragglers: int = 0
    groups: Optional[np.ndarray] = None  # [W] int32 FRC group ids, else None
    B: Optional[np.ndarray] = None  # [W, W] generator matrix (MDS family)

    def __post_init__(self):
        W, S = self.assignment.shape
        assert self.n_workers == W
        assert self.coeffs.shape == (W, S)
        assert self.slot_is_coded.shape == (S,)
        assert self.assignment.min() >= 0 and self.assignment.max() < self.n_partitions

    @property
    def n_slots(self) -> int:
        return self.assignment.shape[1]

    @property
    def n_groups(self) -> int:
        if self.groups is None:
            return self.n_workers
        return int(self.groups.max()) + 1

    @property
    def storage_overhead(self) -> float:
        """Copies of the dataset stored across workers (1.0 = uncoded)."""
        return self.assignment.size / self.n_partitions

    @property
    def uncoded_frac(self) -> float:
        """Partial-scheme timing model: the uncoded ("separate") part is
        sent when its slots are done, i.e. at this fraction of the worker's
        full compute time (both control planes share this constant —
        parallel/collect.py and parallel/dynamic.py)."""
        n_sep = int((~np.asarray(self.slot_is_coded)).sum())
        return n_sep / self.n_slots

    def effective_matrix(self) -> np.ndarray:
        """[W, n_partitions] matrix E with ``message = E @ partition_grads``.

        Row w scatters ``coeffs[w, :]`` into the partition columns this worker
        holds (coded slots only; separate slots form their own always-on
        message in partial schemes).
        """
        E = np.zeros((self.n_workers, self.n_partitions))
        for w in range(self.n_workers):
            for s in range(self.n_slots):
                if self.slot_is_coded[s]:
                    E[w, self.assignment[w, s]] += self.coeffs[w, s]
        return E

    def fold_slot_weights(self, slot_weights: np.ndarray) -> np.ndarray:
        """Fold FINAL per-slot weights [..., W, S] onto per-partition weights.

        ``slot_weights`` must already include the coding coefficients — it is
        the output of ``parallel.step.expand_slot_weights`` (the single home
        of the coded/separate weighting rule). Returns ``p_w``
        [..., n_partitions] such that the decoded gradient equals
        ``sum_p p_w[p] * grad_p``. This is what makes the *deduplicated*
        compute mode possible: instead of every worker redundantly computing
        its (s+1) partition gradients, each partition gradient is computed
        once and combined with these weights — numerically identical to
        decode-of-messages, with 1/(s+1) the FLOPs. Host-side float64 numpy,
        arbitrary leading batch dims (e.g. rounds).
        """
        slot_weights = np.asarray(slot_weights)
        lead = slot_weights.shape[:-2]
        flat = slot_weights.reshape(*lead, -1)  # [..., W*S]
        out = np.zeros((*lead, self.n_partitions))
        np.add.at(
            out.reshape(-1, self.n_partitions),
            (
                np.arange(int(np.prod(lead)) or 1)[:, None],
                self.assignment.reshape(-1)[None, :],
            ),
            flat.reshape(-1, flat.shape[-1]),
        )
        return out


# ---------------------------------------------------------------------------
# Generator matrix (exact gradient coding, cyclic supports)
# ---------------------------------------------------------------------------


def cyclic_generator_matrix(
    n_workers: int, n_stragglers: int, seed: int = 0
) -> np.ndarray:
    """Random cyclic-support generator matrix B for exact gradient coding.

    Math (Tandon et al.; reference impl at src/util.py:64-83): pick
    H in R^{s x W} whose rows each sum to zero; row i of B is supported on
    S_i = {i, ..., i+s mod W} with B[i, i] = 1 and the remaining s entries
    solving H[:, S_i] @ B[i, S_i] = 0, i.e. every row of B lies in null(H).
    Since H @ 1 = 0, the all-ones vector is in the (W-s)-dimensional null
    space too, and for generic H any W-s rows of B span it — so the master
    can reconstruct the exact full gradient from any W-s worker messages.

    Deviation from the reference: the reference draws H unseeded from the
    global numpy RNG (src/util.py:65), making runs non-reproducible; we take
    an explicit seed (default 0).
    """
    if not 0 <= n_stragglers < n_workers:
        raise ValueError("need 0 <= n_stragglers < n_workers")
    if n_stragglers == 0:
        return np.eye(n_workers)
    rng = np.random.default_rng(seed)
    s, W = n_stragglers, n_workers
    H = rng.standard_normal((s, W))
    H[:, -1] = -H[:, :-1].sum(axis=1)  # rows sum to zero => H @ 1 = 0
    B = np.zeros((W, W))
    for i in range(W):
        support = (i + np.arange(s + 1)) % W
        B[i, support[0]] = 1.0
        B[i, support[1:]] = -np.linalg.solve(H[:, support[1:]], H[:, support[0]])
    # Row scaling is a free choice (decode weights compensate); unit rows keep
    # the masked fp32 decode well-behaved. The reference's B[i,i]=1 convention
    # (src/util.py:76) is not load-bearing.
    return B / np.linalg.norm(B, axis=1, keepdims=True)


# ---------------------------------------------------------------------------
# Layouts
# ---------------------------------------------------------------------------


def uncoded_layout(n_workers: int, n_stragglers: int = 0) -> CodingLayout:
    """One unique partition per worker, coefficient 1 (naive & avoidstragg).

    Reference: row-sharded uncoded data, src/naive.py:26-36,
    src/avoidstragg.py:24-32. ``n_stragglers`` carries avoidstragg's
    tolerated-straggler count into the collection rule (naive uses 0:
    it waits for everyone).
    """
    return CodingLayout(
        name="uncoded",
        n_workers=n_workers,
        n_partitions=n_workers,
        assignment=np.arange(n_workers, dtype=np.int32)[:, None],
        coeffs=np.ones((n_workers, 1)),
        slot_is_coded=np.array([True]),
        n_stragglers=n_stragglers,
    )


def cyclic_mds_layout(
    n_workers: int, n_stragglers: int, seed: int = 0
) -> CodingLayout:
    """Cyclic MDS exact gradient coding ("cyccoded" / EGC-MDS).

    Worker w holds the s+1 cyclically-consecutive partitions w..w+s (mod W)
    (src/coded.py:33-48) and pre-scales each by its generator-matrix entry
    B[w, p] (src/coded.py:92-95), so its message is row w of B applied to the
    partition-gradient stack.
    """
    W, s = n_workers, n_stragglers
    B = cyclic_generator_matrix(W, s, seed)
    assignment = (np.arange(W)[:, None] + np.arange(s + 1)[None, :]) % W
    coeffs = np.take_along_axis(B, assignment, axis=1)
    return CodingLayout(
        name="cyclic_mds",
        n_workers=W,
        n_partitions=W,
        assignment=assignment.astype(np.int32),
        coeffs=coeffs,
        slot_is_coded=np.ones(s + 1, dtype=bool),
        n_stragglers=s,
        B=B,
    )


def _frc_groups(n_workers: int, n_stragglers: int) -> np.ndarray:
    if n_workers % (n_stragglers + 1):
        raise ValueError(
            "n_workers must be a multiple of n_stragglers+1 for FRC layouts "
            "(reference guard: src/replication.py:24-26)"
        )
    return (np.arange(n_workers) // (n_stragglers + 1)).astype(np.int32)


def frc_layout(n_workers: int, n_stragglers: int) -> CodingLayout:
    """Fractional repetition code ("repcoded" / EGC-FRC; also AGC's layout).

    Workers form W/(s+1) groups of s+1; all members of group a hold the same
    s+1 partitions {(s+1)a, ..., (s+1)a+s}, each member starting the rotation
    at its own position: member b's slot i holds partition
    (s+1)a + (b+i) mod (s+1) (src/replication.py:46-49,
    src/approximate_coding.py:47-50). All coefficients are 1, so any single
    member's message equals the group's summed partition gradient.
    """
    W, s = n_workers, n_stragglers
    groups = _frc_groups(W, s)
    w = np.arange(W)[:, None]
    a, b = w // (s + 1), w % (s + 1)
    i = np.arange(s + 1)[None, :]
    assignment = (s + 1) * a + (b + i) % (s + 1)
    return CodingLayout(
        name="frc",
        n_workers=W,
        n_partitions=W,
        assignment=assignment.astype(np.int32),
        coeffs=np.ones((W, s + 1)),
        slot_is_coded=np.ones(s + 1, dtype=bool),
        n_stragglers=s,
        groups=groups,
    )


def random_regular_layout(
    n_workers: int, n_stragglers: int, seed: int = 0
) -> CodingLayout:
    """Sparse random d-regular bipartite assignment, d = s+1 (beyond the
    reference; arXiv 1711.06771 via PAPERS.md).

    W partitions, each worker holds d distinct partitions and each partition
    sits on d distinct workers (superimposed random perfect matchings —
    the configuration model). All coefficients 1; the decode is the optimal
    least-squares combination of whichever messages arrive (arXiv
    2006.09638), via the same masked-lstsq machinery as the MDS path
    (mds_decode_weights_host on the 0/1 incidence matrix B). Same s+1
    storage overhead as FRC; the structural difference is graceful
    degradation — error shrinks smoothly with every extra message and hits
    exactly zero at full collection ((1/d)*sum of all rows == all-ones),
    where FRC-AGC erases whole groups all-or-nothing. (At small W with
    light straggling FRC's group structure can still decode tighter;
    tests pin the provable properties, not scheme dominance.)
    """
    W, d = n_workers, n_stragglers + 1
    if d > W:
        raise ValueError(f"degree {d} exceeds n_workers {W}")
    rng = np.random.default_rng(seed)
    assignment = np.empty((W, d), dtype=np.int64)
    # d superimposed random perfect matchings (configuration model),
    # re-drawing any matching that would hand a worker a duplicate
    # partition. Dense degrees (d close to W) reject most draws, so after
    # bounded retries fall back to d shifts of one random permutation —
    # still d-regular and seeded, just less graph-random.
    def _draw() -> bool:
        for k in range(d):
            for _ in range(200):
                perm = rng.permutation(W)
                if k == 0 or not any(
                    perm[w] in assignment[w, :k] for w in range(W)
                ):
                    assignment[:, k] = perm
                    break
            else:
                return False
        return True

    if not _draw():
        sigma = rng.permutation(W)
        for k in range(d):
            assignment[:, k] = (sigma + k) % W
    B = np.zeros((W, W))
    B[np.arange(W)[:, None], assignment] = 1.0
    return CodingLayout(
        name="randreg",
        n_workers=W,
        n_partitions=W,
        assignment=assignment.astype(np.int32),
        coeffs=np.ones((W, d)),
        slot_is_coded=np.ones(d, dtype=bool),
        n_stragglers=n_stragglers,
        B=B,
    )


def sparse_graph_layout(
    n_workers: int, n_stragglers: int, seed: int = 0
) -> CodingLayout:
    """Sparse random bipartite-graph code ("sparsegraph"; arXiv
    1711.06771's random-graph family, beyond the reference).

    Each of the W partitions lands on exactly d = s+1 workers drawn
    uniformly at random (random d-regular on the PARTITION side — the
    structural difference from :func:`random_regular_layout`, which is
    d-regular on both sides): worker loads come out ragged, like a real
    random bipartite assignment. The fixed-shape [W, S] slot table takes
    S = the maximum worker degree, padding lighter workers with
    zero-coefficient slots (they contribute nothing to messages, decode
    folds, or the effective matrix — only redundant gather compute).

    All real-edge coefficients are 1 and every partition has degree
    exactly d, so ``w = 1/d`` decodes the exact full gradient at full
    collection ((1/d) * column sums == all-ones) — the standard
    zero-straggling partial-decode == full-gradient pin. Under
    straggling, the first-``num_collect`` lstsq-optimal combination
    (collect_first_k_optimal over the 0/1 incidence B) degrades
    gracefully like randreg.
    """
    W, d = n_workers, n_stragglers + 1
    if d > W:
        raise ValueError(f"degree {d} exceeds n_workers {W}")
    rng = np.random.default_rng(seed)
    holders = [rng.choice(W, size=d, replace=False) for _ in range(W)]
    per_worker: list[list[int]] = [[] for _ in range(W)]
    for p, ws in enumerate(holders):
        for w in ws:
            per_worker[int(w)].append(p)
    S = max(1, max(len(ps) for ps in per_worker))
    assignment = np.zeros((W, S), dtype=np.int32)
    coeffs = np.zeros((W, S))
    for w, ps in enumerate(per_worker):
        assignment[w, : len(ps)] = ps
        coeffs[w, : len(ps)] = 1.0
    layout = CodingLayout(
        name="sparse_graph",
        n_workers=W,
        n_partitions=W,
        assignment=assignment,
        coeffs=coeffs,
        slot_is_coded=np.ones(S, dtype=bool),
        n_stragglers=n_stragglers,
    )
    # the 0/1 incidence matrix IS the effective coding matrix here; the
    # first-k lstsq rules and the dynamic decode both key on layout.B
    return dataclasses.replace(layout, B=layout.effective_matrix())


def expander_layout(n_workers: int, n_stragglers: int) -> CodingLayout:
    """Deterministic circulant expander-style code ("expander"; the
    cyclic/expander constructions of arXiv 1707.03858, beyond the
    reference).

    Worker w holds the d = s+1 partitions ``w + floor(j*W/d) mod W`` —
    evenly spread circulant chords (distinct because consecutive offsets
    differ by >= 1 when W >= d), giving a d-regular bipartite graph on
    both sides whose union of spread cyclic shifts mixes arrival subsets
    the way the expander constructions intend, with ONE seed-independent
    layout (a whole seed sweep shares its data stack and cohort).
    Coefficients 1; ``w = 1/d`` is the exact full-collection decode; the
    first-``num_collect`` lstsq-optimal rule covers the straggling
    regime, as for sparsegraph/randreg.
    """
    W, d = n_workers, n_stragglers + 1
    if d > W:
        raise ValueError(f"degree {d} exceeds n_workers {W}")
    offsets = np.array([(j * W) // d for j in range(d)], dtype=np.int64)
    assignment = (np.arange(W)[:, None] + offsets[None, :]) % W
    layout = CodingLayout(
        name="expander",
        n_workers=W,
        n_partitions=W,
        assignment=assignment.astype(np.int32),
        coeffs=np.ones((W, d)),
        slot_is_coded=np.ones(d, dtype=bool),
        n_stragglers=n_stragglers,
    )
    return dataclasses.replace(layout, B=layout.effective_matrix())


def partial_cyclic_layout(
    n_workers: int,
    n_partitions_per_worker: int,
    n_stragglers: int,
    seed: int = 0,
) -> CodingLayout:
    """Partial coded ("partialcyccoded"): unique uncoded slots + cyclic coded band.

    Worker w holds n_sep = p-s-1 unique partitions (global ids
    n_sep*w + i, src/partial_coded.py:33-36) plus s+1 partitions of a shared
    W-partition coded band (global ids n_sep*W + (w + j) mod W for j in 0..s,
    src/partial_coded.py:38-43), the coded slots scaled by
    B[w, (w + j) mod W] (src/partial_coded.py:125-126). The master requires
    *all* uncoded parts and decodes the coded band from any W-s coded parts.
    """
    W, p, s = n_workers, n_partitions_per_worker, n_stragglers
    n_sep = p - s - 1
    if n_sep < 1:
        raise ValueError("need n_partitions_per_worker >= n_stragglers + 2")
    B = cyclic_generator_matrix(W, s, seed)
    w = np.arange(W)[:, None]
    sep = n_sep * w + np.arange(n_sep)[None, :]
    band = (w + np.arange(s + 1)[None, :]) % W
    assignment = np.concatenate([sep, n_sep * W + band], axis=1)
    coeffs = np.concatenate(
        [np.ones((W, n_sep)), np.take_along_axis(B, band, axis=1)], axis=1
    )
    return CodingLayout(
        name="partial_cyclic",
        n_workers=W,
        n_partitions=n_sep * W + W,
        assignment=assignment.astype(np.int32),
        coeffs=coeffs,
        slot_is_coded=np.arange(p) >= n_sep,
        n_stragglers=s,
        B=B,
    )


def partial_frc_layout(
    n_workers: int, n_partitions_per_worker: int, n_stragglers: int
) -> CodingLayout:
    """Partial replication ("partialrepcoded"): unique slots + FRC coded band.

    Same unique slice as partial_cyclic; the coded band is group-replicated:
    every member of group a holds the same s+1 band partitions
    n_sep*W + a*(s+1) + b, b in 0..s, unscaled
    (src/partial_replication.py:44-50). The master requires all uncoded parts
    plus one coded part per group.
    """
    W, p, s = n_workers, n_partitions_per_worker, n_stragglers
    n_sep = p - s - 1
    if n_sep < 1:
        raise ValueError("need n_partitions_per_worker >= n_stragglers + 2")
    groups = _frc_groups(W, s)
    w = np.arange(W)[:, None]
    sep = n_sep * w + np.arange(n_sep)[None, :]
    band = groups[:, None] * (s + 1) + np.arange(s + 1)[None, :]
    assignment = np.concatenate([sep, n_sep * W + band], axis=1)
    return CodingLayout(
        name="partial_frc",
        n_workers=W,
        n_partitions=n_sep * W + W,
        assignment=assignment.astype(np.int32),
        coeffs=np.ones((W, p)),
        slot_is_coded=np.arange(p) >= n_sep,
        n_stragglers=s,
        groups=groups,
    )


# ---------------------------------------------------------------------------
# Decoding
# ---------------------------------------------------------------------------


def mds_decode_weights(B: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Decode weights a with support on ``mask`` s.t. a @ B ~= all-ones.

    Fixed-shape jit/TPU-safe replacement for the reference's per-iteration
    dynamic solve ``np.linalg.lstsq(B[completed, :].T, ones(W))``
    (src/coded.py:147-149): we zero the masked-out *rows* of B and take the
    minimum-norm least-squares solution of (mask*B)^T a = 1. That solution
    lies in range(mask*B), whose vectors vanish on masked-out coordinates, so
    a is automatically supported on the completed workers and coincides with
    the reference's solution there. When >= W-s workers are unmasked the MDS
    property makes the reconstruction exact.
    """
    Bm = jnp.where(mask[:, None], B, 0.0)
    ones = jnp.ones(B.shape[0], B.dtype)
    pinv = jnp.linalg.pinv(Bm.T)
    a = pinv @ ones
    # Two rounds of iterative refinement: random cyclic codes can have
    # ill-conditioned straggler patterns, and in fp32 the one-shot solve can
    # lose 1e-2 of the all-ones target; refinement recovers it.
    for _ in range(2):
        a = a + pinv @ (ones - Bm.T @ a)
    # The min-norm solution is supported on ``mask`` in exact arithmetic;
    # hard-zero the rest so fp32 noise can never touch an uncollected
    # worker's message.
    return jnp.where(mask, a, 0.0)


def mds_decode_weights_host(B: np.ndarray, masks: np.ndarray) -> np.ndarray:
    """Float64 host-side decode weights for a batch of completion masks.

    The data plane (gradient einsums) runs on TPU, but decode-weight *control*
    data is tiny ([rounds, W]) and, under the seeded straggler simulator, the
    completion masks for every round are known before the training scan starts
    — exactly as the reference's seeded delay schedule predetermines arrivals
    (src/naive.py:141-148). Solving here in float64 numpy sidesteps a real
    fp32 hazard: random cyclic codes at the reference's canonical W=30 scale
    have straggler patterns whose decode systems are so ill-conditioned that
    an on-device fp32 solve fails outright (measured error ~1.0); the
    reference never hit this only because its per-iteration
    ``np.linalg.lstsq`` (src/coded.py:147-149) ran in float64 on the master.
    Use :func:`mds_decode_weights` only for small-W online/dynamic decoding.

    Args:
      B: [W, W] generator matrix.
      masks: [rounds, W] boolean completion masks.

    Returns:
      [rounds, W] float64 decode weights, zero outside each mask.
    """
    masks = np.asarray(masks, dtype=bool)
    W = B.shape[0]
    ones = np.ones(W)
    # straggler patterns repeat across rounds (only ~C(W, s) exist), so solve
    # each distinct mask once — keeps the control plane sub-second at R=10k
    uniq, inverse = np.unique(masks, axis=0, return_inverse=True)
    out = np.zeros(uniq.shape)
    for k in range(uniq.shape[0]):
        live = np.flatnonzero(uniq[k])
        out[k, live] = np.linalg.lstsq(B[live, :].T, ones, rcond=None)[0]
    return out[inverse.reshape(-1)]


def enumerate_decode_table(B: np.ndarray, n_stragglers: int) -> np.ndarray:
    """Precompute decode weights for every C(W, s) straggler pattern.

    Parity with the reference's (runtime-unused) ``getA`` (src/util.py:85-103):
    row k holds the decode weights for the k-th s-subset of stragglers in
    ``itertools.combinations`` order. Useful on TPU to replace the in-loop
    lstsq with a table gather when C(W, s) is small.
    """
    W = B.shape[0]
    patterns = list(itertools.combinations(range(W), n_stragglers))
    A = np.zeros((len(patterns), W))
    ones = np.ones(W)
    for k, stragglers in enumerate(patterns):
        live = np.setdiff1d(np.arange(W), stragglers)
        A[k, live] = np.linalg.lstsq(B[live, :].T, ones, rcond=None)[0]
    return A


@dataclasses.dataclass(frozen=True)
class MdsDecodeTable:
    """Precomputed f64-solved decode weights for all straggler patterns of
    size 0..max_stragglers, indexable from inside jit.

    This is the production fix for the fp32 hazard documented on
    :func:`mds_decode_weights_host`: at the reference's canonical W=30, some
    straggler patterns of the random cyclic code are so ill-conditioned that
    an on-device fp32 solve fails outright (~1.0 error). Here every pattern
    is solved ONCE on host in float64 (≙ the reference's runtime-unused
    ``getA``, src/util.py:85-103) and the per-round decode becomes a single
    table-row gather keyed by the traced completion mask — exact arithmetic
    replaced by indexing, which fp32 cannot corrupt.

    Covers patterns with UP TO max_stragglers stragglers (not just exactly
    s) so the partial schemes — whose completed set can exceed W-s when the
    all-first-parts condition binds last (src/partial_coded.py:174-191) —
    use the same table.
    """

    table: np.ndarray  # [sum_{r<=s} C(W,r), W] float64 decode weights
    offsets: np.ndarray  # [s+1] int32; r-straggler block starts at offsets[r]
    comb: np.ndarray  # [W+1, s+1] int32 binomial table for traced ranking
    max_stragglers: int

    def lookup(self, mask: jnp.ndarray) -> jnp.ndarray:
        """Decode weights for a traced completion mask (True = collected)."""
        stragglers = ~mask
        s_cnt = stragglers.sum()
        rank = straggler_pattern_index_jnp(
            stragglers, self.max_stragglers, self.comb
        )
        row = jnp.asarray(self.offsets)[s_cnt] + rank
        return jnp.asarray(self.table, jnp.float32)[row]


def build_decode_table(
    B: np.ndarray,
    max_stragglers: int,
    cap_rows: int = 20_000,
    exact_only: bool = False,
) -> Optional[MdsDecodeTable]:
    """Build an :class:`MdsDecodeTable`, or None if it would exceed cap_rows.

    ``exact_only`` builds just the exactly-max_stragglers block — the
    first-k collection rules (cyccoded, randreg) always complete exactly
    W-k workers, so the 0..s-1 blocks would be dead rows counted against
    the cap (e.g. randreg W=27, k=23: C(27,4)=17,550 fits the cap while
    the 0..4 sum does not). Partial schemes need the full 0..s range
    (their completed sets can exceed W-s).

    At the canonical W=30, s=3 the full table is 1+30+435+4060 = 4,526
    rows (~540 KB f32 on device). C(W,s) growth makes the cap necessary:
    e.g. randreg with num_collect=W/2 would need C(30,15) ≈ 155M rows.
    """
    W = B.shape[0]
    counts = [
        0 if (exact_only and r < max_stragglers) else math.comb(W, r)
        for r in range(max_stragglers + 1)
    ]
    if sum(counts) > cap_rows:
        return None
    tables = [
        np.zeros((0, W)) if n == 0 else enumerate_decode_table(B, r)
        for r, n in enumerate(counts)
    ]
    offsets = np.cumsum([0] + [t.shape[0] for t in tables])[:-1]
    comb = np.array(
        [
            [math.comb(n, r) for r in range(max_stragglers + 1)]
            for n in range(W + 1)
        ],
        dtype=np.int32,
    )
    return MdsDecodeTable(
        table=np.concatenate(tables, axis=0),
        offsets=offsets.astype(np.int32),
        comb=comb,
        max_stragglers=max_stragglers,
    )


def straggler_pattern_index_jnp(
    straggler_mask: jnp.ndarray, max_stragglers: int, comb_table: np.ndarray
) -> jnp.ndarray:
    """Traced combinatorial rank of a straggler set among same-size subsets.

    jit-compatible equivalent of :func:`straggler_pattern_index` (≙ the
    reference's lookup helpers, src/util.py:105-134) for any actual
    straggler count <= max_stragglers. The per-position inner sum of the
    host version telescopes via the hockey-stick identity to
    ``C(W - prev_j - 1, r_j) - C(W - p_j, r_j)`` with ``r_j = s_cnt - j``,
    turning the ranking into a fixed-shape gather + sum.
    """
    W = straggler_mask.shape[0]
    s_max = max_stragglers
    if s_max == 0:
        return jnp.zeros((), jnp.int32)
    idx = jnp.arange(W)
    # ascending straggler positions, padded with sentinel W (sorts last)
    pos = jnp.sort(jnp.where(straggler_mask, idx, W))[:s_max]
    s_cnt = straggler_mask.sum()
    prev = jnp.concatenate([jnp.array([-1]), pos[:-1]])
    j = jnp.arange(s_max)
    r = jnp.clip(s_cnt - j, 0, s_max)
    ct = jnp.asarray(comb_table)
    hi = ct[W - prev - 1, r]
    lo = ct[jnp.clip(W - pos, 0, W), r]
    return jnp.where(j < s_cnt, hi - lo, 0).sum()


def straggler_pattern_index(straggler_mask: np.ndarray) -> int:
    """Row index into :func:`enumerate_decode_table` for a straggler set.

    Combinatorial rank of the sorted straggler positions in
    ``itertools.combinations(range(W), s)`` order (the reference's equivalent
    lookup helpers are src/util.py:105-134).
    """
    W = len(straggler_mask)
    positions = np.flatnonzero(straggler_mask)
    s = len(positions)
    index = 0
    prev = -1
    remaining = s
    for pos in positions:
        for skipped in range(prev + 1, pos):
            index += math.comb(W - skipped - 1, remaining - 1)
        prev = pos
        remaining -= 1
    return index
