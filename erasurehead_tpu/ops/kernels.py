"""Pallas TPU kernels for the hot op: fused decoded-gradient computation.

The coded-GD iteration is bandwidth-bound: the per-slot GLM gradient needs
two passes over the feature stack X — a margin matvec ``p = X @ beta`` and a
transpose matvec ``g = X^T @ s(p, y)`` (reference closed forms
src/naive.py:137-139, 341-346). Under XLA these are two HBM reads of X per
step. This kernel fuses margin -> residual -> transpose-accumulate into ONE
pass over X, and folds the per-slot decode weights (parallel/collect.py) in
as well, so the *decoded* gradient

    g = sum_m w_m * sum_r s(p_{m,r}, y_{m,r}) * X[m, r, :]

comes out of a single streaming read. s is the residual:
  logistic: s = -y / (exp(p*y) + 1)        (src/naive.py:137-139)
  linear:   s = -2 * (y - p)               (src/naive.py:341-346)

Grid: (M slots, row blocks). TPU grids run sequentially, so the (1, F)
output block accumulates across all grid steps (initialized at step 0).
Zero-padded rows (X row = 0, y = 0) contribute exactly 0 for both residuals
— padding to a block multiple is safe with no masking.

The deduped/faithful compute modes (parallel/step.py) both reduce to the
[M, R, F] slot-major shape this kernel takes; `parallel/step.py` wires it
under shard_map with a trailing psum over the worker axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

GLM_KINDS = ("logistic", "linear")

# VMEM budget per X block (double-buffered by the pipeline, keep modest)
_X_BLOCK_BYTES = 2 * 1024 * 1024
_MAX_BLOCK_ROWS = 512


def choose_block_rows(
    n_rows: int, n_features: int, sublane: int = 8
) -> int:
    """Largest multiple-of-``sublane`` row block that fits the VMEM budget.

    ``sublane`` is the TPU tile's second-minor size for the streamed dtype:
    8 for f32, 16 for bf16 — a bf16 block whose row count is not a
    multiple of 16 would force Mosaic to retile."""
    by_vmem = _X_BLOCK_BYTES // max(1, 4 * n_features)
    cap = min(
        _MAX_BLOCK_ROWS, max(sublane, by_vmem // sublane * sublane)
    )
    padded = -(-n_rows // sublane) * sublane
    return min(cap, padded)


def _residual(kind: str, p, y):
    if kind == "logistic":
        return -y / (jnp.exp(p * y) + 1.0)
    if kind == "linear":
        return -2.0 * (y - p)
    raise ValueError(f"unknown GLM kind {kind!r}")


def _kernel(kind: str, b_ref, x_ref, y_ref, w_ref, o_ref):
    """One (slot m, row block) step: o += w_m * X_blk^T s(X_blk b, y_blk)."""
    m, rb = pl.program_id(0), pl.program_id(1)

    @pl.when((m == 0) & (rb == 0))
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    x = x_ref[0].astype(jnp.float32)  # (BR, F); upcast once per block so a
    # bf16-stored stack streams at half the HBM bytes but contracts exactly
    y = y_ref[0]  # (BR, 1)
    w = w_ref[m, 0]  # scalar from SMEM, dynamic slot index
    # Both contractions run on the VPU (elementwise multiply + reduce) in
    # true f32: the MXU default would round products to ~bf16 (measured
    # 8.8e-4 relative), which MDS decode weights then amplify (see
    # ops/features.py docstring), and precision=HIGHEST hangs the Mosaic
    # compiler in this toolchain. The op is HBM-bound, so idle MXUs are
    # free; matvecs use 1/128 of the MXU anyway.
    p = jnp.sum(x * b_ref[...], axis=1, keepdims=True)  # (BR, 1)
    s = _residual(kind, p, y) * w  # (BR, 1)
    o_ref[...] += jnp.sum(x * s, axis=0, keepdims=True)  # (1, F)


@functools.partial(
    jax.jit, static_argnames=("kind", "interpret", "block_rows")
)
def fused_glm_grad(
    beta: jnp.ndarray,  # [F]
    X: jnp.ndarray,  # [M, R, F] slot-major dense stack
    y: jnp.ndarray,  # [M, R]
    w: jnp.ndarray,  # [M] decode weight per slot
    kind: str = "logistic",
    *,
    interpret: bool = False,
    block_rows: int | None = None,
) -> jnp.ndarray:
    """Decoded GLM gradient in one pass over X. Returns [F] float32."""
    M, R, F = X.shape
    x_dtype = jnp.bfloat16 if X.dtype == jnp.bfloat16 else jnp.float32
    BR = block_rows or choose_block_rows(
        R, F, sublane=16 if x_dtype == jnp.bfloat16 else 8
    )
    Rp = -(-R // BR) * BR
    if Rp != R:
        # zero rows contribute zero gradient for both residuals; XLA hoists
        # this out of training scans because X is loop-invariant there
        X = jnp.pad(X, ((0, 0), (0, Rp - R), (0, 0)))
        y = jnp.pad(y, ((0, 0), (0, Rp - R)))
    beta2 = beta.astype(jnp.float32).reshape(1, F)
    y3 = y.astype(jnp.float32).reshape(M, Rp, 1)
    w2 = w.astype(jnp.float32).reshape(M, 1)

    out = pl.pallas_call(
        functools.partial(_kernel, kind),
        grid=(M, Rp // BR),
        in_specs=[
            pl.BlockSpec((1, F), lambda m, rb: (0, 0)),  # beta
            pl.BlockSpec((1, BR, F), lambda m, rb: (m, rb, 0)),  # X
            pl.BlockSpec((1, BR, 1), lambda m, rb: (m, rb, 0)),  # y
            # per-slot decode weights are scalars: whole array in SMEM
            pl.BlockSpec(memory_space=pltpu.SMEM),  # w
        ],
        out_specs=pl.BlockSpec((1, F), lambda m, rb: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, F), jnp.float32),
        interpret=interpret,
    )(beta2, X.astype(x_dtype), y3, w2)
    return out[0]


def reference_glm_grad(beta, X, y, w, kind: str = "logistic"):
    """Plain-XLA oracle for the fused kernel (two passes over X)."""
    p = jnp.einsum(
        "mrf,f->mr", X, beta, precision=lax.Precision.HIGHEST
    )
    s = _residual(kind, p, y) * w[:, None]
    return jnp.einsum(
        "mrf,mr->f", X, s, precision=lax.Precision.HIGHEST
    )


class FusedSupport:
    """Verdict of the fused-kernel auto-gate, with the refusal reason.

    Truthiness is the verdict (so ``if supports_fused(...)`` keeps
    working); ``reason`` says why — surfaced as a one-time ``warning``
    event by the trainer so ``use_pallas="auto"`` never declines silently.
    """

    __slots__ = ("ok", "reason")

    def __init__(self, ok: bool, reason: str):
        self.ok = ok
        self.reason = reason

    def __bool__(self) -> bool:
        return self.ok

    def __repr__(self) -> str:
        return f"FusedSupport(ok={self.ok}, reason={self.reason!r})"


def supports_fused(X, model_name: str, platform: str) -> "FusedSupport":
    """Auto-gate for the fused GLM kernel.

    The hardcoded fallback is *decline*: XLA won the original race.
    Measured on v5e at the bench shape ([90, 4400, 128] slot stack, timed
    inside one dispatch, tools/kernel_race.py):
      - MXU-dot variant:   2.7 ms  vs XLA 2.05 ms  (r1, slower — bf16
        rounding also failed the science, see _kernel comment)
      - exact-f32 VPU variant (this file): logistic 2.60 ms vs XLA 1.87 ms,
        linear 2.58 ms vs XLA 1.90 ms (r2, slower)
      - fusion-favorable retry at [30, 26400, 64] bf16-stored (tall rows,
        narrow F, half the bytes/pass — the shape most generous to a
        single-streaming-pass kernel): logistic pallas 3.48 ms vs XLA
        1.87 ms, speedup 0.54x (r3, decisively slower)
    XLA's two-pass lowering overlaps the margin and transpose matvecs well
    enough that the single-streaming-pass VPU kernel cannot beat it — the
    VPU multiply-reduce is the bottleneck, not HBM, so halving HBM bytes
    (bf16) widens XLA's lead rather than closing it. Those three races are
    a measured negative at *those* shapes; since ISSUE 19 the verdict is
    re-raceable per shape through the tune decision cache
    (erasurehead_tpu/tune/): a cached ``glm_fused`` win at this run's
    shape flips the gate through data, not a code edit. Absent a cached
    win, the hardcoded decline stands and use_pallas="on" remains the
    correctness alternative, not a performance option. Tests pin the
    kernel to the XLA oracle in interpret mode either way.
    """
    if model_name not in GLM_KINDS:
        return FusedSupport(
            False,
            f"use_pallas auto declined: model {model_name!r} is not a "
            f"dense GLM (fused kernel covers {GLM_KINDS})",
        )
    if not (isinstance(X, jax.Array) and X.ndim in (3, 4)):
        return FusedSupport(
            False,
            "use_pallas auto declined: feature stack is not a dense "
            "slot-major array (sparse/padded/compressed layouts have no "
            "fused lowering)",
        )
    if platform != "tpu":
        return FusedSupport(
            False,
            f"use_pallas auto declined: platform {platform!r} has no "
            "Mosaic backend (interpret mode is a correctness path, not a "
            "fast one)",
        )
    from erasurehead_tpu import tune as tune_lib

    sig = tune_lib.glm_fused_signature(X.shape, str(X.dtype), model_name)
    choice = tune_lib.lookup("glm_fused", sig, fallback="xla")
    if choice == "pallas":
        return FusedSupport(
            True,
            f"use_pallas auto accepted: cached glm_fused race win at "
            f"shape {sig}",
        )
    return FusedSupport(
        False,
        "use_pallas auto declined: XLA won the glm_fused race (v5e, "
        "three shapes — kernels.supports_fused docstring) and no cached "
        "tune decision at this shape overrides it",
    )


# --------------------------------------------------------------------------
# fused blockwise decode (ISSUE 19): the layer-coding decode contraction
# without the materialized per-partition grad table

# lane-dim column block for the decode kernel; multiples of 128 keep the
# Mosaic tiling natural for every dtype
_DECODE_BLOCK_COLS = 2048


def choose_block_cols(n_slots: int, n_cols: int, lane: int = 128) -> int:
    """Largest multiple-of-``lane`` column block within the VMEM budget."""
    by_vmem = _X_BLOCK_BYTES // max(1, 4 * n_slots)
    cap = min(
        _DECODE_BLOCK_COLS, max(lane, by_vmem // lane * lane)
    )
    padded = -(-n_cols // lane) * lane
    return min(cap, padded)


def _decode_kernel(w_ref, g_ref, o_ref):
    """One column block: o[0, :] = w[1, M] · g[M, BC].

    A dot_general (not a VPU multiply-reduce) on purpose: the MXU dot at
    precision=HIGHEST reduces in the same order as the einsum decode it
    replaces, which is what makes the tier-1 bitwise pin possible — the
    elementwise multiply+sum form reduces in a different order and drifts
    in the last ulp (measured on CPU, ISSUE 19).
    """
    o_ref[...] = lax.dot_general(
        w_ref[...], g_ref[...], (((1,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
        preferred_element_type=o_ref.dtype,
    )


@functools.partial(
    jax.jit, static_argnames=("use_pallas", "interpret", "block_cols")
)
def fused_block_decode(
    w: jnp.ndarray,  # [M] decode weight per slot, einsum reduction order
    g: jnp.ndarray,  # [M, D] per-slot flattened leaf gradients
    *,
    use_pallas: bool = False,
    interpret: bool = False,
    block_cols: int | None = None,
) -> jnp.ndarray:
    """Decoded leaf gradient: contraction of per-slot gradients over slots.

    This is the per-leaf half of the blockwise decode
    (parallel/step._layer_block_local_body) with the per-partition grad
    *table* fused away: instead of packing every slot's gradient pytree
    into a zero-padded [M, L, width] table and einsum-decoding it
    treewise, each leaf's [M, D_leaf] slot view contracts directly —
    no padding columns are ever materialized or streamed. ``w`` must
    already be flattened in the einsum reduction order of the contract it
    replaces (s-major for the faithful "ws" contract — see
    parallel/step._fused_layer_block_local_body).

    ``use_pallas=False`` lowers the same contraction through one XLA
    dot_general (the fast CPU path); ``use_pallas=True`` runs the Mosaic
    kernel (``interpret=True`` for the CPU parity pin). All three are
    bitwise-identical at precision=HIGHEST — pinned by tier-1.
    """
    M, D = g.shape
    w = w.astype(g.dtype)
    if not use_pallas:
        return lax.dot_general(
            w, g, (((0,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
        )
    BC = block_cols or choose_block_cols(M, D)
    Dp = -(-D // BC) * BC
    gp = jnp.pad(g, ((0, 0), (0, Dp - D))) if Dp != D else g
    out = pl.pallas_call(
        _decode_kernel,
        grid=(Dp // BC,),
        in_specs=[
            pl.BlockSpec((1, M), lambda c: (0, 0)),  # w
            pl.BlockSpec((M, BC), lambda c: (0, c)),  # g columns
        ],
        out_specs=pl.BlockSpec((1, BC), lambda c: (0, c)),
        out_shape=jax.ShapeDtypeStruct((1, Dp), g.dtype),
        interpret=interpret,
    )(w.reshape(1, M), gp)
    return out[0, :D]
