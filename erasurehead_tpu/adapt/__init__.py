"""Online straggler-adaptive collection (ISSUE 8 / ROADMAP item 5).

``train_adaptive`` runs the scan trainer in chunks and lets a seeded
discounted-reward bandit (:class:`AdaptiveController`) re-choose the
collection policy — a registry-compatible :class:`Arm` of (scheme,
collect count, deadline) — at every chunk boundary, reading the run's own
decode-error and arrival telemetry. Decisions are journaled as typed
``adapt`` events; see README "Schemes & adaptive collection".
"""

from erasurehead_tpu.adapt.controller import (
    AdaptiveController,
    Arm,
    ChunkStats,
    ControllerConfig,
)
from erasurehead_tpu.adapt.driver import (
    AdaptiveResult,
    default_arms,
    train_adaptive,
)

__all__ = [
    "AdaptiveController",
    "AdaptiveResult",
    "Arm",
    "ChunkStats",
    "ControllerConfig",
    "default_arms",
    "train_adaptive",
]
