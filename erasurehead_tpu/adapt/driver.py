"""train_adaptive: run the scan in chunks under the bandit's chosen arms.

The driver is deliberately a THIN composition of existing machinery:

  - each chunk is a plain ``trainer.train`` call covering rounds
    [lo, hi) via the ``initial_state``/``initial_round`` mid-schedule
    restart contract (the elastic-recovery hook) — so every chunk's math,
    caching, telemetry and decode-error accounting are exactly the
    single-run trainer's;
  - the arrival matrix is drawn ONCE for the whole horizon
    (trainer.default_arrivals — the ``ERASUREHEAD_REGIME`` shift applies
    here) and every arm sees the same stream, the paired-comparison
    contract compare() uses;
  - arm switches are weight-table switches: arms must share the base
    config's layout-stack signature (validated up front), so no data
    re-upload ever happens mid-run, and in deduped mode all arms share
    one compiled executable (the weight table is a traced argument).

Between chunks the controller reads the chunk's own telemetry quantities
(sim seconds, decode-error mean, masked arrival stats) and decides the
next arm; each decision is journaled as a typed ``adapt`` event
(obs/events.py). Decisions are deterministic given (controller seed,
arrival schedule), so kill→resume — or simply rerunning — replays the
same sequence bitwise (tests/test_adapt.py; chaos site "adapt" arms a
mid-adaptation fault).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence

import numpy as np

from erasurehead_tpu.adapt.controller import (
    AdaptiveController,
    Arm,
    ChunkStats,
    ControllerConfig,
)
from erasurehead_tpu.utils.config import RunConfig


@dataclasses.dataclass
class AdaptiveResult:
    """A merged TrainResult plus the controller's decision record."""

    result: object  # trainer.TrainResult over the full horizon
    decisions: list[dict]  # one per chunk (controller.decisions)
    arms: list[Arm]
    #: per-chunk (arm label, ChunkStats) pairs, decision order
    chunk_stats: list[tuple]
    #: the controller's own cost: wall seconds spent in choose/observe/
    #: event emission across all chunks (the bench `adapt` extra's <2%%
    #: bar divides this by total_wall_s)
    decision_overhead_s: float
    #: everything outside the chunk train() calls (schedule refits,
    #: cache lookups, history stitching) — the chunked-dispatch fixed
    #: cost, reported separately from the controller's own math
    driver_overhead_s: float
    #: sum of the chunks' device wall seconds
    train_wall_s: float
    #: whole-run wall seconds (train + driver + decisions)
    total_wall_s: float


def default_arms(cfg: RunConfig) -> list[Arm]:
    """A reasonable registry-compatible arm set for ``cfg``: the config's
    own policy plus the uncoded-layout alternatives every straggler
    regime ranks differently (wait-for-all, ignore-stragglers, and — when
    the config carries a deadline — deadline collection). All share the
    deduped partition-major stack; in faithful mode only stack-compatible
    arms survive the driver's validation."""
    arms = [Arm(cfg.scheme.value, cfg.num_collect, cfg.deadline)]

    def add(arm: Arm):
        if all(a.label != arm.label for a in arms):
            arms.append(arm)

    add(Arm("naive"))
    add(Arm("avoidstragg"))
    if cfg.deadline is not None:
        add(Arm("deadline", deadline=cfg.deadline))
    return arms


def _arm_config(cfg: RunConfig, arm: Arm, rounds: int) -> RunConfig:
    return dataclasses.replace(
        cfg, rounds=rounds, lr_schedule=cfg.resolve_lr_schedule()[:rounds],
        **arm.overrides(),
    )


def _validate_arms(cfg: RunConfig, arms: Sequence[Arm]):
    """Every arm must (a) validate as a config and (b) build the SAME
    device data stack as the base config — the no-re-upload contract that
    makes arm switches cheap. Returns the arms' layouts."""
    from erasurehead_tpu import schemes
    from erasurehead_tpu.train import cache as cache_lib
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils.config import ComputeMode

    faithful = cfg.compute_mode == ComputeMode.FAITHFUL
    base_layout = trainer.build_layout(cfg)
    base_sig = cache_lib.layout_stack_signature(
        base_layout, worker_major=faithful
    )
    layouts = []
    for arm in arms:
        desc = schemes.get(arm.scheme)
        if desc.partial:
            raise ValueError(
                f"arm {arm.label!r}: partial two-part schemes change the "
                "partition count and cannot share the base data stack"
            )
        arm_cfg = _arm_config(cfg, arm, cfg.rounds)
        lay = trainer.build_layout(arm_cfg)
        sig = cache_lib.layout_stack_signature(lay, worker_major=faithful)
        if sig != base_sig:
            raise ValueError(
                f"arm {arm.label!r} builds a different device data stack "
                "than the base config (layout-stack signatures differ); "
                "adaptive arm switches must be weight-table-only — use "
                "compute_mode='deduped' (partition-major stacks are "
                "scheme-independent) or stack-compatible schemes"
            )
        layouts.append(lay)
    return layouts


def train_adaptive(
    cfg: RunConfig,
    dataset,
    arms: Optional[Sequence[Arm]] = None,
    controller: Optional[ControllerConfig] = None,
    mesh=None,
    arrivals: Optional[np.ndarray] = None,
    priors: Optional[dict] = None,
) -> AdaptiveResult:
    """Train ``cfg.rounds`` rounds, re-choosing the collection policy at
    every ``controller.chunk_rounds`` boundary (module docstring).

    ``cfg`` provides everything but the per-chunk policy: model, data
    shape, update rule, decode mode, memory knobs. ``arms`` defaults to
    :func:`default_arms`. ``priors`` ({arm label: simulated expected
    reward}, e.g. a what-if surface's ``adapt_priors``) seeds the
    bandit's cold start so the warm-up only explores arms the surface
    could not rank (controller docstring). Returns an
    :class:`AdaptiveResult` whose ``result`` quacks like a single
    ``trainer.train`` result over the full horizon (history, clocks with
    the -1 sentinel, decode-error series stitched from the chunks).
    """
    import jax

    from erasurehead_tpu.obs import events as obs_events
    from erasurehead_tpu.train import trainer
    from erasurehead_tpu.utils import chaos as chaos_lib

    if cfg.arrival_mode != "simulated":
        raise ValueError(
            "train_adaptive drives the scan trainer in chunks; "
            "arrival_mode='measured' has no chunked implementation"
        )
    arms = list(arms) if arms is not None else default_arms(cfg)
    ctl_cfg = controller or ControllerConfig()
    _validate_arms(cfg, arms)
    ctl = AdaptiveController(arms, ctl_cfg, priors=priors)

    # shift_source="regime": the live estimator (obs/regime.py) watches
    # every ROUND of the raw arrival schedule and hands its change-point
    # verdict to observe() — chunk-size-independent detection, plus the
    # Hill tail-index machinery the chunk-mean rule lacks
    estimator = None
    if ctl_cfg.shift_source == "regime":
        from erasurehead_tpu.obs import regime as regime_lib

        estimator = regime_lib.ArrivalRegimeEstimator(
            shift_factor=ctl_cfg.shift_factor
        )

    # chunk-boundary loss probe (reward_mode="progress"): one-snapshot
    # eval replays on the full host training set — evaluate.replay caches
    # its jitted scan per model identity, so each probe is one tiny
    # program execution, counted into decision_overhead_s
    from erasurehead_tpu.train import evaluate as evaluate_lib
    from erasurehead_tpu.train import trainer as trainer_lib

    probe_model = trainer_lib.build_model(cfg)

    def _loss_of(params) -> float:
        import jax as _jax

        hist = _jax.tree.map(lambda l: np.asarray(l)[None], params)
        ev = evaluate_lib.replay(
            probe_model, cfg.model, hist,
            dataset.X_train, dataset.y_train,
            dataset.X_test, dataset.y_test,
        )
        return float(ev.training_loss[-1])

    if arrivals is None:
        arrivals = trainer.default_arrivals(cfg)
    arrivals = np.asarray(arrivals, dtype=np.float64)
    if arrivals.shape != (cfg.rounds, cfg.n_workers):
        raise ValueError(
            f"arrivals shape {arrivals.shape} != "
            f"({cfg.rounds}, {cfg.n_workers})"
        )

    R, W = cfg.rounds, cfg.n_workers
    run_id = obs_events.new_run_id() if obs_events.current() else None
    state = None
    pieces = []  # per-chunk params_history trees
    timeset = np.zeros(R)
    worker_times = np.full((R, W), -1.0)
    collected = np.zeros((R, W), dtype=bool)
    decode_err = np.zeros(R)
    chunk_stats: list[tuple] = []
    train_wall = 0.0
    decision_wall = 0.0
    last_res = None
    t_total0 = time.perf_counter()
    loss_prev: Optional[float] = None
    if ctl_cfg.reward_mode == "progress":
        p0 = trainer_lib._init_params_f32(
            cfg, probe_model, dataset.n_features
        )
        # warm the probe's jitted replay scan outside the timed region
        # (same contract as the trainers' executable warm-up: one-time
        # compile cost is not a property of the per-chunk decision)
        _loss_of(p0)
        t_dec0 = time.perf_counter()
        loss_prev = _loss_of(p0)
        decision_wall += time.perf_counter() - t_dec0
    lo = 0
    while lo < R:
        hi = min(lo + ctl_cfg.chunk_rounds, R)
        # chaos site "adapt": a kill here is a preemption mid-adaptation;
        # rerunning replays the decision prefix bitwise (determinism)
        chaos_lib.maybe_fire("adapt")
        t_dec = time.perf_counter()
        idx, reason = ctl.choose()
        decision_wall += time.perf_counter() - t_dec
        arm = arms[idx]
        arm_cfg = _arm_config(cfg, arm, hi)
        res = trainer.train(
            arm_cfg, dataset, mesh=mesh, arrivals=arrivals[:hi],
            initial_state=state, initial_round=lo if state is not None else 0,
            measure=False,
        )
        state = res.final_state
        last_res = res
        train_wall += res.wall_time
        # the chunk's own telemetry: clocks + decode errors for [lo, hi)
        timeset[lo:hi] = res.timeset[lo:hi]
        worker_times[lo:hi] = res.worker_times[lo:hi]
        collected[lo:hi] = res.collected[lo:hi]
        decode_err[lo:hi] = res.decode_error[lo:hi]
        pieces.append(res.params_history)
        t_dec = time.perf_counter()
        # arrival stats for SHIFT DETECTION come from the raw schedule
        # window, not the collected-masked worker_times: masked stats are
        # policy-dependent (avoidstragg never stamps the straggler it
        # skipped), and a policy-dependent detector would read every arm
        # switch as a regime change
        raw_rows = arrivals[lo:hi]
        raw = raw_rows[np.isfinite(raw_rows)]
        loss_delta = None
        if loss_prev is not None:
            loss_now = _loss_of(res.final_params)
            loss_delta = loss_prev - loss_now
            loss_prev = loss_now
        stats = ChunkStats(
            n_rounds=hi - lo,
            sim_time=float(res.timeset[lo:hi].sum()),
            decode_error_mean=float(res.decode_error[lo:hi].mean()),
            arrival_mean=float(raw.mean()) if raw.size else None,
            arrival_p90=(
                float(np.quantile(raw, 0.9)) if raw.size else None
            ),
            loss_delta=loss_delta,
        )
        verdict = None
        if estimator is not None:
            # same raw (policy-independent) rows the jump rule reads,
            # but per-round — the estimator's change-point fires within
            # its short window instead of waiting out a chunk mean
            estimator.update_rounds(lo, raw_rows)
            verdict = estimator.poll_shift()
        shift = ctl.observe(idx, stats, regime_shift=verdict)
        chunk_stats.append((arm.label, stats))
        obs_events.emit(
            "adapt",
            run_id=run_id,
            round=lo,
            n_rounds=hi - lo,
            arm=arm.label,
            scheme=arm.scheme,
            num_collect=arm.num_collect,
            deadline=arm.deadline,
            reason=reason,
            reward=round(ctl.reward(stats), 8),
            sim_per_round=round(stats.sim_per_round, 8),
            decode_error_mean=round(stats.decode_error_mean, 10),
            regime_shift=bool(shift),
            values=ctl.snapshot()["values"],
        )
        decision_wall += time.perf_counter() - t_dec
        lo = hi

    history = (
        pieces[0]
        if len(pieces) == 1
        else jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *pieces
        )
    )
    total_wall = time.perf_counter() - t_total0
    driver_overhead = max(total_wall - train_wall - decision_wall, 0.0)
    merged = trainer.TrainResult(
        params_history=history,
        final_params=state.params,
        final_state=state,
        timeset=timeset,
        worker_times=worker_times,
        collected=collected,
        sim_total_time=float(timeset.sum()),
        wall_time=train_wall,
        steps_per_sec=R / train_wall if train_wall > 0 else 0.0,
        n_train=last_res.n_train,
        config=cfg,
        layout=last_res.layout,
        decode_error=decode_err,
        run_id=run_id,
        cache_info=last_res.cache_info,
    )
    return AdaptiveResult(
        result=merged,
        decisions=list(ctl.decisions),
        arms=arms,
        chunk_stats=chunk_stats,
        decision_overhead_s=decision_wall,
        driver_overhead_s=driver_overhead,
        train_wall_s=train_wall,
        total_wall_s=total_wall,
    )
