"""The online straggler-adaptive collection controller (ROADMAP item 5).

The telemetry subsystem (obs/) was built to be consumed, not just
rendered: every run already measures its per-round decode-error norm
(obs/decode.py — the central quantity of arXiv:2006.09638) and masked
arrival statistics (obs/events.arrival_summary), yet collection policy is
fixed for the whole run. This module closes the loop: a discounted-reward
bandit over registry-compatible *arms* — (scheme, collect count, deadline)
triples sharing the run's device data stack — reads each chunk's own
telemetry and switches policy when the straggler regime shifts, exactly
the non-stationary setting where "Fundamental Limits of Approximate
Gradient Coding" (arXiv:1901.08166) shows a fixed policy costs the most.

Design constraints, in order:

  1. **Determinism.** Decisions are a pure function of (seed, observed
     telemetry); telemetry under the simulated-arrival trainer is itself
     deterministic, so a killed-and-rerun adaptive run replays the same
     decision sequence bitwise (the kill→resume invariance the chaos
     harness pins, composing with PR 5's journal/resume). Exploration
     uses a seeded ``numpy`` Generator, never wall-clock or OS entropy.
  2. **Observability.** Every decision is journaled as a typed ``adapt``
     event (obs/events.py) carrying the chosen arm, the reason
     (warmup/exploit/explore/regime_shift), and the per-arm value
     snapshot — a run's policy trajectory is reconstructible from its
     event log alone.
  3. **Cheap switches.** Arms must be registry-compatible — same
     layout-stack signature, so an arm switch is a new per-round weight
     table (a traced argument), never a re-upload; the executable cache
     makes the compiled scan shared across arms in deduped mode.

Reward: the controller maximizes *useful progress per simulated second*.
The default ``reward_mode="progress"`` scores a chunk as the training-
loss decrease it achieved divided by the simulated seconds it cost
(the driver measures the loss at each chunk boundary from a one-snapshot
eval replay) — exactly the quantity time-to-target integrates, so the
bandit's optimum is the time-to-target optimum in each regime. It also
self-corrects the speed/error tradeoff: an aggressive low-collect arm
earns big rewards while far from convergence and near zero once its decode
error floors its progress, at which point the controller escalates to a
lower-error arm. ``reward_mode="time_error"`` is the telemetry-only
fallback (no loss evals): ``-(sim_seconds/round) * (1 + error_penalty *
decode_error_mean^2)`` — the clock inflated by how wrong the decoded
gradient was.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class Arm:
    """One collection policy the controller may run a chunk under."""

    scheme: str
    num_collect: Optional[int] = None
    deadline: Optional[float] = None

    @property
    def label(self) -> str:
        parts = [self.scheme]
        if self.num_collect is not None:
            parts.append(f"c{self.num_collect}")
        if self.deadline is not None:
            parts.append(f"d{self.deadline:g}")
        return ":".join(parts)

    def overrides(self) -> dict:
        """dataclasses.replace() kwargs turning a base config into this
        arm's config (None fields keep the base value — a deadline-less
        arm must not clear the base deadline another arm needs)."""
        out: dict = {"scheme": self.scheme}
        if self.num_collect is not None:
            out["num_collect"] = self.num_collect
        if self.deadline is not None:
            out["deadline"] = self.deadline
        return out


@dataclasses.dataclass(frozen=True)
class ControllerConfig:
    """Knobs of the chunk-boundary bandit."""

    #: rounds per decision window (the scan runs chunk_rounds at a time)
    chunk_rounds: int = 10
    #: discount on older observations per new one (0 = only the latest
    #: chunk counts, 1 = plain running mean). Small = fast re-adaptation.
    discount: float = 0.5
    #: seeded epsilon-greedy exploration rate after the warm-up pass
    epsilon: float = 0.1
    #: "progress" (default): reward = chunk loss decrease / sim seconds
    #: (the driver measures chunk-boundary losses); "time_error": the
    #: telemetry-only fallback reward (module docstring)
    reward_mode: str = "progress"
    #: decode-error penalty weight in the time_error reward
    error_penalty: float = 25.0
    #: arrival-mean jump factor (vs the previous chunk) that flags a
    #: regime shift and resets the per-arm values so the bandit
    #: re-explores instead of trusting stale pre-shift rewards
    shift_factor: float = 2.5
    #: where the shift verdict comes from: "chunk_mean" (default — the
    #: controller's own arrival-mean jump rule above) or "regime" — the
    #: caller passes the live estimator's verdict into ``observe``
    #: (obs/regime.ArrivalRegimeEstimator.poll_shift, which sees every
    #: ROUND's arrivals instead of one mean per chunk and also carries
    #: the tail-index change-point machinery)
    shift_source: str = "chunk_mean"
    #: exploration seed (decision replay: same seed + same telemetry ->
    #: same decisions, bitwise)
    seed: int = 0
    #: observation weight a simulated prior counts as (controller
    #: ``priors``): 1.0 = one real chunk's worth of evidence — strong
    #: enough to skip the warm-up visit, weak enough that one real
    #: observation halves its influence under the default discount
    prior_weight: float = 1.0

    def __post_init__(self):
        if self.chunk_rounds < 1:
            raise ValueError(
                f"chunk_rounds must be >= 1, got {self.chunk_rounds}"
            )
        if not 0.0 <= self.discount <= 1.0:
            raise ValueError(f"discount must be in [0, 1], got {self.discount}")
        if not 0.0 <= self.epsilon < 1.0:
            raise ValueError(f"epsilon must be in [0, 1), got {self.epsilon}")
        if self.reward_mode not in ("progress", "time_error"):
            raise ValueError(
                f"reward_mode must be progress/time_error, got "
                f"{self.reward_mode!r}"
            )
        if self.shift_factor <= 1.0:
            raise ValueError(
                f"shift_factor must be > 1, got {self.shift_factor}"
            )
        if self.shift_source not in ("chunk_mean", "regime"):
            raise ValueError(
                f"shift_source must be chunk_mean/regime, got "
                f"{self.shift_source!r}"
            )
        if self.prior_weight <= 0.0:
            raise ValueError(
                f"prior_weight must be > 0, got {self.prior_weight}"
            )


@dataclasses.dataclass(frozen=True)
class ChunkStats:
    """What the controller reads back after one chunk: the run's OWN
    telemetry quantities (obs/decode.py error norms, obs/events
    arrival_summary fields), never anything the trainers don't already
    produce."""

    n_rounds: int
    sim_time: float  # summed simulated seconds of the chunk
    decode_error_mean: float  # mean ||pw - 1||/sqrt(P) over the chunk
    arrival_mean: Optional[float]  # masked mean arrival (None = none arrived)
    arrival_p90: Optional[float]
    #: training-loss decrease over the chunk (loss at the previous chunk
    #: boundary minus loss at this one); None = the driver did not
    #: measure boundary losses (reward_mode="time_error")
    loss_delta: Optional[float] = None

    @property
    def sim_per_round(self) -> float:
        return self.sim_time / max(self.n_rounds, 1)


class AdaptiveController:
    """Discounted-reward epsilon-greedy bandit over arms (module docstring).

    ``choose()`` -> (arm_index, reason); ``observe(arm_index, stats)``
    feeds the chunk's telemetry back. The decision log (``decisions``)
    is the journal payload: one dict per choice, stable field order.

    ``priors`` seeds the cold start from a what-if surface
    (whatif/surface.Surface.adapt_priors): {arm label: prior value} in
    the controller's own reward units. A primed arm starts with its
    simulated expected reward at ``cfg.prior_weight`` observations of
    evidence instead of zero at zero — so the warm-up pass (which
    otherwise burns one chunk per arm exploring policies the registry's
    simulation could already rank) only visits arms the surface could
    NOT speak for, and the first free choice exploits the simulated
    ranking. Real telemetry then overwrites the prior at the discount's
    usual pace; a detected regime shift still wipes primed values — the
    priors were conditioned on the regime that just ended.
    """

    def __init__(
        self,
        arms: Sequence[Arm],
        cfg: ControllerConfig = None,
        priors: Optional[dict] = None,
    ):
        self.arms = list(arms)
        if not self.arms:
            raise ValueError("AdaptiveController needs at least one arm")
        labels = [a.label for a in self.arms]
        if len(set(labels)) != len(labels):
            raise ValueError(f"duplicate arms: {labels}")
        self.cfg = cfg or ControllerConfig()
        self._rng = np.random.default_rng(self.cfg.seed)
        n = len(self.arms)
        # discounted value estimate + discounted observation weight per arm
        self._value = np.zeros(n)
        self._weight = np.zeros(n)
        self.priors = dict(priors) if priors else {}
        if self.priors:
            unknown = sorted(set(self.priors) - set(labels))
            if unknown:
                raise ValueError(
                    f"priors name unknown arms {unknown}; controller arms "
                    f"are {labels}"
                )
            for i, label in enumerate(labels):
                if label in self.priors:
                    self._value[i] = float(self.priors[label])
                    self._weight[i] = self.cfg.prior_weight
        self._last_arrival_mean: Optional[float] = None
        self._chunk_index = 0
        self._pending_shift = False
        self.decisions: list[dict] = []

    # ---- policy ----------------------------------------------------------

    def choose(self) -> tuple[int, str]:
        """Pick the next chunk's arm. Warm-up visits every arm once (in
        order — deterministic), then epsilon-greedy on discounted value;
        a detected regime shift forces a fresh warm-up pass (the stale
        values were reset by ``observe``)."""
        unvisited = np.flatnonzero(self._weight == 0.0)
        if unvisited.size:
            idx = int(unvisited[0])
            reason = "regime_shift" if self._pending_shift else "warmup"
        elif self._rng.random() < self.cfg.epsilon:
            idx = int(self._rng.integers(len(self.arms)))
            reason = "explore"
        else:
            idx = int(np.argmax(self._value))
            reason = "exploit"
        self.decisions.append(
            {
                "chunk": self._chunk_index,
                "arm": self.arms[idx].label,
                "arm_index": idx,
                "reason": reason,
                "values": [round(float(v), 8) for v in self._value],
            }
        )
        self._chunk_index += 1
        return idx, reason

    # ---- feedback --------------------------------------------------------

    def reward(self, stats: ChunkStats) -> float:
        if self.cfg.reward_mode == "progress" and stats.loss_delta is not None:
            # loss decrease per simulated second — the quantity
            # time-to-target integrates (negative when the arm regressed)
            return float(stats.loss_delta) / max(stats.sim_time, 1e-9)
        err = float(stats.decode_error_mean)
        return -stats.sim_per_round * (
            1.0 + self.cfg.error_penalty * err * err
        )

    def observe(
        self,
        arm_index: int,
        stats: ChunkStats,
        regime_shift: Optional[bool] = None,
    ) -> Optional[str]:
        """Feed one chunk's telemetry back; returns "regime_shift" when
        the arrival statistics jumped past ``shift_factor`` (per-arm
        values are then reset so the next choices re-explore — the
        discounted estimates from the old regime are evidence about a
        world that no longer exists).

        Under ``shift_source="regime"`` the jump rule is replaced by the
        caller's verdict: ``regime_shift`` is the live estimator's
        ``poll_shift()`` for this chunk (obs/regime.py), and a chunk
        observed without a verdict falls back to the jump rule so a
        driver that stopped feeding the estimator degrades to the old
        behavior instead of going shift-blind."""
        r = self.reward(stats)
        g = self.cfg.discount
        self._weight *= g
        self._value[arm_index] = (
            (self._value[arm_index] * self._weight[arm_index] + r)
            / (self._weight[arm_index] + 1.0)
        )
        self._weight[arm_index] += 1.0
        shift = None
        mean = stats.arrival_mean
        use_verdict = (
            self.cfg.shift_source == "regime" and regime_shift is not None
        )
        if use_verdict:
            shifted = bool(regime_shift)
        else:
            shifted = False
            if mean is not None and self._last_arrival_mean is not None:
                lo, hi = sorted(
                    (max(mean, 1e-12), max(self._last_arrival_mean, 1e-12))
                )
                shifted = hi / lo >= self.cfg.shift_factor
        if shifted:
            shift = "regime_shift"
            # keep only THIS chunk's reward (it is from the new
            # regime); every other arm restarts from scratch
            self._value[:] = 0.0
            self._weight[:] = 0.0
            self._value[arm_index] = r
            self._weight[arm_index] = 1.0
            self._pending_shift = True
        if shift is None:
            self._pending_shift = False
        self._last_arrival_mean = mean
        return shift

    # ---- introspection ---------------------------------------------------

    def snapshot(self) -> dict:
        return {
            "arms": [a.label for a in self.arms],
            "values": [round(float(v), 8) for v in self._value],
            "weights": [round(float(w), 6) for w in self._weight],
            "chunks": self._chunk_index,
        }

    # ---- persistence (elastic/driver.py checkpoints the bandit in its
    # aux sidecar so a killed->resumed elastic-with-adapt run replays the
    # identical arm sequence: values, weights AND the exploration rng
    # state all round-trip through JSON exactly)

    def state_dict(self) -> dict:
        """JSON-serializable full state; :meth:`load_state_dict` restores
        it bitwise (floats survive JSON via repr round-trip, the seeded
        Generator via its bit_generator state dict)."""
        import json

        return {
            "value": [float(v) for v in self._value],
            "weight": [float(w) for w in self._weight],
            "last_arrival_mean": self._last_arrival_mean,
            "chunk_index": self._chunk_index,
            "pending_shift": self._pending_shift,
            "decisions": list(self.decisions),
            # the bit-generator state is plain ints/lists after one JSON
            # round-trip, matching what a restored aux sidecar holds
            "rng_state": json.loads(
                json.dumps(self._rng.bit_generator.state)
            ),
        }

    def load_state_dict(self, state: dict) -> None:
        n = len(self.arms)
        value = np.asarray(state["value"], dtype=np.float64)
        weight = np.asarray(state["weight"], dtype=np.float64)
        if value.shape != (n,) or weight.shape != (n,):
            raise ValueError(
                f"state_dict covers {value.shape[0]} arms, controller has "
                f"{n} — arm sets must match to restore"
            )
        self._value = value
        self._weight = weight
        self._last_arrival_mean = state.get("last_arrival_mean")
        self._chunk_index = int(state["chunk_index"])
        self._pending_shift = bool(state.get("pending_shift", False))
        self.decisions = list(state.get("decisions", []))
        rng_state = state.get("rng_state")
        if rng_state is not None:
            self._rng.bit_generator.state = rng_state
