"""Distributed backend init: multi-host pods over DCN.

The reference's multi-node substrate is mpirun + a hostfile + MPI4Py
point-to-point (SURVEY.md §2.3); its cluster bring-up is tools/pytorch_ec2.py
writing hosts files for mpirun (pytorch_ec2.py:656-708). The TPU-native
equivalent is ``jax.distributed.initialize``: each TPU VM host joins the same
SPMD program, the worker mesh axis spans all hosts\' local devices, and the
``psum`` in parallel/step.py rides ICI within a slice and DCN across slices —
no rank-0 master process exists at all.

On a single host (including the CI CPU mesh and the one-chip bench) this is
a no-op. The entry point is idempotent and safe to call unconditionally at
program start.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Join (or skip) the multi-host JAX runtime; returns topology info.

    With no arguments and no cluster env (JAX_COORDINATOR_ADDRESS etc. or
    TPU pod metadata), runs single-process. With arguments or cluster env
    present, calls ``jax.distributed.initialize`` exactly once.
    """
    global _initialized
    in_cluster = (
        coordinator_address is not None
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        # GKE's TPU webhook injects the worker hostnames into every pod of
        # a TPU podslice; jax's own cluster detection derives coordinator
        # and ranks from it when no manual env is set. Only a MULTI-host
        # list means there is a cluster to form — single-host runtimes
        # (incl. this sandbox's relay plugin) set a lone hostname
        or "," in os.environ.get("TPU_WORKER_HOSTNAMES", "")
    )
    if in_cluster and not _initialized:
        # Manual-coordinator path only: this jax build does not read
        # JAX_NUM_PROCESSES/JAX_PROCESS_ID itself, and a k8s indexed Job
        # (the JobSet deployment, tools/k8s/) hands each pod its rank as
        # JOB_COMPLETION_INDEX. On TPU-metadata deployments (MEGASCALE_*),
        # jax's own cluster detection computes the GLOBAL rank
        # (slice_id x hosts_per_slice + worker_id); JOB_COMPLETION_INDEX
        # restarts at 0 per slice there and must not preempt it.
        manual = coordinator_address is not None or os.environ.get(
            "JAX_COORDINATOR_ADDRESS"
        )
        if manual and not os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
            if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
                num_processes = int(os.environ["JAX_NUM_PROCESSES"])
            if process_id is None:
                rank = os.environ.get(
                    "JAX_PROCESS_ID", os.environ.get("JOB_COMPLETION_INDEX")
                )
                if rank is not None:
                    process_id = int(rank)
            if process_id is not None and num_processes is None:
                # forwarding the partial pair would fail deep inside
                # jax.distributed with an opaque library error; name the
                # missing knob instead (ADVICE r5 #3 — validate_jobset
                # only protects the committed manifest, not ad-hoc runs)
                raise ValueError(
                    "distributed init resolved a process rank "
                    f"(process_id={process_id} via JAX_PROCESS_ID/"
                    "JOB_COMPLETION_INDEX) but no process count; set "
                    "JAX_NUM_PROCESSES (or pass num_processes) so "
                    "jax.distributed.initialize receives the full pair"
                )
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    return topology_info()


def topology_info() -> dict:
    """Process/device counts — the analogue of the reference\'s
    size==n_procs sanity check (main.py:55-57)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }
