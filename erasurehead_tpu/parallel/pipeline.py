"""Bounded-staleness pipelined collection: break the round barrier.

Every synchronous trainer in this repo serializes rounds on the master
clock — round t+1 cannot dispatch until round t's collection closes, so a
single heavy-tail straggler stalls the whole pipeline even when the coding
scheme could absorb the erasure. ``pipeline_depth=1`` overlaps adjacent
rounds instead: round t+1's worker compute is dispatched against params
from round t-1 while round t's arrivals drain (staleness tau = 1, the
regime ErasureHead's decay-rate analysis tolerates for APPROXIMATE
schemes; exact-decode schemes are config-refused —
utils.config.PipelineRefusal via the descriptor's ``staleness_tolerant``
flag).

This module is the pipelined CONTROL PLANE: a deterministic host-float64
recurrence over the same drawn arrival matrix the synchronous schedule
reads, reusing each scheme's own stop rule (collect.build_schedule) per
round on the workers' *effective* relative arrivals. Nothing here is
async-racy — the whole schedule is a pure function of (cfg, arrivals,
layout), so journal replays and chaos kill->resume runs stay bitwise.

The timing model (absolute simulated master clock):

  dispatch[r] = max(dispatch[r-1], done[r-2])     params p_{r-1} ready
  start[r,w]  = max(dispatch[r], free[w])         worker finishes r-1 first
  arrive[r,w] = start[r,w] + t[r,w]               t = drawn per-round times
  stop[r]     = dispatch[r] + scheme stop rule over (arrive - dispatch)
  done[r]     = max(done[r-1], stop[r])           decode+update applied
  free[w]     = arrive[r,w] if collected else min(arrive[r,w], done[r])
                                                  (stragglers are cancelled
                                                   when the round closes)

At depth 0 the recurrence collapses to ``dispatch[r] = done[r-1]``; every
worker is free by then, the effective relative arrivals equal the drawn
matrix row, and the schedule is BITWISE the synchronous
``collect.build_schedule`` output (tests/test_pipeline.py pins it).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from erasurehead_tpu.parallel import collect

# re-exported here so pipeline consumers need one import; the class lives
# in utils.config (beside the validation that raises it) to avoid an
# import cycle through collect -> config
from erasurehead_tpu.utils.config import PipelineRefusal  # noqa: F401


@dataclasses.dataclass(frozen=True)
class PipelinedSchedule:
    """Collection schedule of a pipelined run.

    Duck-types :class:`parallel.collect.CollectionSchedule` (the trainer
    and the obs/decode error series read only the four shared fields) and
    adds the pipeline's own timing artifacts:

      - ``dispatch`` [R]: absolute simulated time each round's compute was
        dispatched to the workers;
      - ``done`` [R]: absolute time each round's decode+update applied;
      - ``dispatch_ahead`` [R]: how far ahead of the synchronous barrier
        the dispatch ran — ``done[r-1] - dispatch[r]`` (>= 0; 0 everywhere
        at depth 0) — the overlap the pipeline actually bought;
      - ``staleness`` [R]: the per-round staleness schedule (tau), 0 for
        the warm-up rounds that still compute at fresh params.
    """

    message_weights: np.ndarray  # [R, W] float64
    sim_time: np.ndarray  # [R] float64 (done[r] - done[r-1])
    worker_times: np.ndarray  # [R, W] float64, collect.NEVER sentinel
    collected: np.ndarray  # [R, W] bool
    dispatch: np.ndarray  # [R] float64, absolute
    done: np.ndarray  # [R] float64, absolute
    dispatch_ahead: np.ndarray  # [R] float64, >= 0
    staleness: np.ndarray  # [R] int64


def staleness_schedule(rounds: int, depth: int) -> np.ndarray:
    """[R] per-round staleness tau: round r computes its gradient at the
    params of round ``r - tau[r]``. Depth-1 pipelining is tau = 1 from
    round 1 on; rounds 0..depth-1 are the fresh warm-up (there is no older
    iterate to be stale against). Rides the run signature via
    cfg.pipeline_depth — no independent randomness, so replays are
    bitwise."""
    tau = np.minimum(np.arange(rounds, dtype=np.int64), int(depth))
    return tau


def pipelined_schedule(
    cfg,
    t: np.ndarray,
    layout,
) -> PipelinedSchedule:
    """Build the depth-``cfg.pipeline_depth`` pipelined schedule for one
    run (module docstring timing model).

    ``t`` is the SAME [R, W] drawn arrival matrix the synchronous trainer
    feeds ``collect.build_schedule`` — per-round relative compute+delay
    times. Each round's stop rule runs on the workers' effective relative
    arrivals (skewed by busy workers), so every scheme's collection
    semantics — first-k, group coverage, deadline cutoff, optimal refit —
    compose unchanged. Host float64 throughout; exceptions the per-round
    rules raise (missing num_collect etc.) propagate untouched.
    """
    t = np.asarray(t, dtype=np.float64)
    R, W = t.shape
    depth = int(cfg.pipeline_depth)

    weights = np.zeros((R, W))
    wtimes = np.zeros((R, W))
    coll = np.zeros((R, W), dtype=bool)
    dispatch = np.zeros(R)
    done = np.zeros(R)
    sim = np.zeros(R)
    ahead = np.zeros(R)

    free = np.zeros(W)  # absolute time each worker is next available
    done_prev = 0.0  # done[r-1]
    done_lag = 0.0  # done[r-1-depth]: the dispatch gate
    recent: list = []  # trailing done values, for the lagged gate
    for r in range(R):
        disp = max(dispatch[r - 1] if r else 0.0, done_lag)
        # effective relative arrivals, built WITHOUT round-tripping through
        # the absolute clock: a worker free by dispatch time contributes
        # skew exactly 0.0, so at depth 0 (free <= disp always) the rule
        # sees the drawn row t[r] bitwise — the synchronous identity
        skew = np.maximum(free - disp, 0.0)
        rel = skew + t[r]
        # the scheme's own stop rule on THIS round's effective relative
        # arrivals — one [1, W] schedule per round; the decode="optimal"
        # refit composes exactly as it does synchronously
        sched = collect.build_schedule(
            cfg.scheme, rel[None, :], layout,
            num_collect=cfg.num_collect, deadline=cfg.deadline,
            decode=cfg.decode,
        )
        stop_rel = float(sched.sim_time[0])
        # delta <= 0 when the dispatch ran ahead of the previous round's
        # close; exactly 0.0 at depth 0 — sim[r] then IS stop_rel bitwise
        delta = disp - done_prev
        sim[r] = max(0.0, delta + stop_rel)
        d = done_prev + sim[r]
        weights[r] = sched.message_weights[0]
        wtimes[r] = sched.worker_times[0]
        coll[r] = sched.collected[0]
        dispatch[r] = disp
        done[r] = d
        ahead[r] = max(-delta, 0.0)
        # collected workers freed at their own arrival; stragglers are
        # cancelled when the round closes (the reference master's abort)
        arrive = disp + rel
        free = np.where(coll[r], arrive, np.minimum(arrive, d))
        recent.append(d)
        done_prev = d
        done_lag = recent[-1 - depth] if len(recent) > depth else 0.0
    return PipelinedSchedule(
        message_weights=weights,
        sim_time=sim,
        worker_times=wtimes,
        collected=coll,
        dispatch=dispatch,
        done=done,
        dispatch_ahead=ahead,
        staleness=staleness_schedule(R, depth),
    )


def overlap_summary(schedule: PipelinedSchedule) -> dict:
    """Host summary of the pipeline's dispatch-ahead overlap (the
    "dispatch_ahead" typed event's payload fields)."""
    ahead = np.asarray(schedule.dispatch_ahead, dtype=np.float64)
    return {
        "ahead_mean_s": round(float(ahead.mean()), 6) if ahead.size else 0.0,
        "ahead_max_s": round(float(ahead.max()), 6) if ahead.size else 0.0,
        "overlap_total_s": round(float(ahead.sum()), 6),
    }
